"""Render flight-recorder output as Chrome trace-event JSON (loadable
in Perfetto / chrome://tracing).

Input is any JSON file carrying a step ring and/or request spans in
the obs formats (quintnet_tpu/obs/):

- a crash dump (``obs/crashdump.py``: ``{"kind": "crash_dump",
  "ring": [...], "traces": {...}}``) — the post-mortem, visualized;
- a raw obs dump (``{"ring": [...], "traces": {...}}``) — what
  ``tools/serve_bench.py --trace-out`` writes from a timed replay.

Mapping (the Chrome trace-event format, JSON Array/Object flavor):

- each engine STEP becomes a complete ("ph": "X") slice on the
  "engine steps" thread — duration = the step's clock window, args =
  the step's phase mix / occupancy / KV pressure / chunk + spec
  ledgers, so the Perfetto timeline shows exactly the prefill/decode
  interference Sarathi argues about;
- each request SPAN becomes an async begin/end pair ("ph": "b"/"e",
  id = trace id) on the "requests" track, instants (t1 == t0) become
  instant events ("ph": "i") — one row per request from queue to
  finish, migrations included (the id stitches cross-process spans);
- each fleet LIFECYCLE EVENT (obs/events.py — crash dumps embed the
  recent ring) becomes an instant marker ("ph": "i") on the "fleet
  events" track. SLO-judgment events (``slo_breach`` /
  ``slo_recovered`` / ``rebalance_recommended``, obs/slo.py +
  obs/signals.py) are scoped GLOBAL ("s": "g") so Perfetto draws a
  full-height line: "the fast+slow burn windows tripped HERE" and
  "the planner recommended decode→prefill HERE" line up visually
  against the step slices that caused them.

Timestamps are microseconds (the format's unit), re-based to the
earliest event so Perfetto opens at t=0 instead of hours into a
monotonic clock.

Usage:
  python tools/trace_view.py DUMP.json -o trace.json
  python tools/trace_view.py DUMP.json            # stdout

Library surface: :func:`chrome_trace` (dict in, dict out — the bench
and tests call this), :func:`validate_chrome_trace` (structural check
used by CI so the export can never drift off-format).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

_US = 1e6

# pid/tid are display coordinates in the trace-event format; one
# process row with named threads reads best in Perfetto
PID = 1
TID_STEPS = 1
TID_REQUESTS = 2
TID_EVENTS = 3

# fleet events drawn as FULL-HEIGHT markers ("s": "g"): the SLO
# judgment layer's output, which the reader wants to line up against
# every track at once. Everything else stays a thread-local tick.
_GLOBAL_EVENT_KINDS = frozenset({
    "slo_breach", "slo_recovered", "rebalance_recommended",
})


def _base_ts(ring: List[Dict], traces: Dict[str, List[Dict]],
             fleet_events: Optional[List[Dict]] = None) -> float:
    ts = [r["t0"] for r in ring]
    ts += [s["t0"] for spans in traces.values() for s in spans]
    ts += [e["ts"] for e in (fleet_events or []) if "ts" in e]
    return min(ts) if ts else 0.0


def chrome_trace(ring: Optional[List[Dict]] = None,
                 traces: Optional[Dict[str, List[Dict]]] = None,
                 fleet_events: Optional[List[Dict]] = None,
                 *, label: str = "quintnet-serve") -> Dict:
    """Build the Chrome trace-event JSON object (see module
    docstring). ``ring``: StepRecorder.snapshot(); ``traces``:
    Tracer.snapshot(); ``fleet_events``: EventLog.snapshot() (what a
    crash dump's ``events`` field carries)."""
    ring = ring or []
    traces = traces or {}
    fleet_events = fleet_events or []
    t_base = _base_ts(ring, traces, fleet_events)
    events: List[Dict] = [
        {"ph": "M", "pid": PID, "name": "process_name",
         "args": {"name": label}},
        {"ph": "M", "pid": PID, "tid": TID_STEPS, "name": "thread_name",
         "args": {"name": "engine steps"}},
        {"ph": "M", "pid": PID, "tid": TID_REQUESTS,
         "name": "thread_name", "args": {"name": "requests"}},
        {"ph": "M", "pid": PID, "tid": TID_EVENTS,
         "name": "thread_name", "args": {"name": "fleet events"}},
    ]
    for rec in ring:
        args = {k: v for k, v in rec.items()
                if k not in ("t0", "t1", "attrs")}
        args.update(rec.get("attrs") or {})
        events.append({
            "name": f"step {rec.get('step', '?')}",
            "cat": "engine", "ph": "X",
            "ts": (rec["t0"] - t_base) * _US,
            "dur": max(rec["t1"] - rec["t0"], 0.0) * _US,
            "pid": PID, "tid": TID_STEPS, "args": args,
        })
    for trace_id, spans in sorted(traces.items()):
        for s in spans:
            common = {"cat": "request", "id": trace_id, "pid": PID,
                      "tid": TID_REQUESTS,
                      "args": dict(s.get("attrs") or {})}
            t0 = (s["t0"] - t_base) * _US
            if s["t1"] > s["t0"]:
                events.append({"name": s["name"], "ph": "b",
                               "ts": t0, **common})
                events.append({"name": s["name"], "ph": "e",
                               "ts": (s["t1"] - t_base) * _US,
                               **common})
            else:
                # instant: scope "t" (thread) keeps it a tick mark
                events.append({"name": s["name"], "ph": "i", "s": "t",
                               "ts": t0, **common})
    for e in fleet_events:
        if "ts" not in e or "kind" not in e:
            continue        # not an EventLog record; skip, don't guess
        kind = e["kind"]
        name = kind
        args = {k: v for k, v in e.items()
                if k not in ("ts", "seq", "kind")}
        if kind == "slo_breach":
            # the marker label carries the judgment: which objective,
            # which pool, how hard it is burning
            name = (f"slo_breach {args.get('objective', '?')} "
                    f"[{args.get('pool', '?')}] "
                    f"{args.get('burn_fast', 0):.1f}x")
        elif kind == "rebalance_recommended":
            name = (f"rebalance {args.get('direction', '?')}"
                    + (" (revert)" if args.get("revert") else ""))
        events.append({
            "name": name, "cat": "fleet", "ph": "i",
            "s": "g" if kind in _GLOBAL_EVENT_KINDS else "t",
            "ts": (e["ts"] - t_base) * _US,
            "pid": PID, "tid": TID_EVENTS, "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"source": label}}


def validate_chrome_trace(obj: Dict) -> int:
    """Structural validation of a trace-event JSON object; returns the
    event count. Raises ValueError on anything Perfetto would choke
    on — the CI gate behind 'the export validates as Chrome
    trace-event JSON'."""
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError("not a trace-event object: no 'traceEvents'")
    events = obj["traceEvents"]
    if not isinstance(events, list):
        raise ValueError("'traceEvents' must be a list")
    open_async: Dict = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict):
            raise ValueError(f"event {i} is not an object")
        ph = e.get("ph")
        if ph is None or "pid" not in e or "name" not in e:
            raise ValueError(
                f"event {i} is missing ph/pid/name: {e}")
        if ph == "M":
            continue
        if "ts" not in e or not isinstance(e["ts"], (int, float)):
            raise ValueError(f"event {i} has no numeric ts: {e}")
        if ph == "X":
            if "dur" not in e or e["dur"] < 0:
                raise ValueError(
                    f"complete event {i} needs a dur >= 0: {e}")
        elif ph in ("b", "e"):
            if "id" not in e or "cat" not in e:
                raise ValueError(
                    f"async event {i} needs id + cat: {e}")
            key = (e["cat"], e["id"], e["name"])
            if ph == "b":
                open_async[key] = open_async.get(key, 0) + 1
            else:
                if open_async.get(key, 0) < 1:
                    raise ValueError(
                        f"async end without begin at event {i}: {e}")
                open_async[key] -= 1
        elif ph == "i":
            if e.get("s") not in (None, "t", "p", "g"):
                raise ValueError(
                    f"instant event {i} has invalid scope: {e}")
        else:
            raise ValueError(f"event {i} has unknown ph {ph!r}")
    dangling = {k: v for k, v in open_async.items() if v}
    if dangling:
        raise ValueError(f"unbalanced async begin/end: {dangling}")
    return len(events)


def _load_dump(path: str) -> Dict:
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict):
        raise SystemExit(f"{path}: expected a JSON object")
    if ("ring" not in payload and "traces" not in payload
            and "events" not in payload):
        raise SystemExit(
            f"{path}: no 'ring', 'traces' or 'events' — not a crash "
            f"dump or obs dump (tools/serve_bench.py --trace-out "
            f"writes one)")
    return payload


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_view",
        description="crash dump / obs dump -> Chrome trace-event JSON "
                    "(Perfetto)")
    ap.add_argument("dump", help="crash-dump or obs-dump JSON file")
    ap.add_argument("-o", "--out", default=None,
                    help="output file (default: stdout)")
    args = ap.parse_args(argv)

    payload = _load_dump(args.dump)
    label = payload.get("replica") or "quintnet-serve"
    trace = chrome_trace(payload.get("ring"), payload.get("traces"),
                         payload.get("events"), label=label)
    validate_chrome_trace(trace)
    text = json.dumps(trace, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {len(trace['traceEvents'])} events to "
              f"{args.out}", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())

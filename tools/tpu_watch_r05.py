#!/usr/bin/env python
"""Round-5 TPU watcher: wait for the tunnel, then capture perf evidence
in priority order (round-4 windows lasted ~45 min, so the headline
number comes first, A/Bs after).

Order:
  1. headline default bench (the driver-equivalent number)  -> headline_r05.json
  2. remat-policy / scan-unroll A/B grid                    -> remat_unroll_r05.json
  3. flash-attn kernel at 1024/2048/4096/8192               -> flash_r05.json
  4. chunked-CE A/B                                         -> loss_chunk_r05.json
  5. medium preset (MFU headroom check)                     -> medium_r05.json

Availability is probed in a subprocess with a hard timeout (the
tunnel's failure modes are UNAVAILABLE errors and silent hangs).
Run: python tools/tpu_watch_r05.py   (or via Bash run_in_background)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")

PLAN = [
    ("headline_r05.json", [
        ["--steps", "30"],
        ["--steps", "30"],  # second sample for run-to-run variance
    ]),
    ("remat_unroll_r05.json", [
        ["--remat-policy", "dots"],
        ["--remat-policy", "dots", "--scan-unroll", "2"],
        ["--scan-unroll", "2"],
        ["--scan-unroll", "3"],
        ["--mu-dtype", "bfloat16"],
        ["--mu-dtype", "bfloat16", "--remat-policy", "dots"],
        [],  # default re-measured in the same session for a fair A/B
    ]),
    ("flash_r05.json", [
        # crossover hunt at the flagship's training seqs: single-k-pass
        # geometries (bk == s kills the online-softmax correction steps;
        # scores tile [bq, s] f32 still fits VMEM at these sizes)
        ["--model", "flash-attn", "--seq", "1024", "--steps", "30"],
        ["--model", "flash-attn", "--seq", "1024", "--steps", "30",
         "--block-q", "512", "--block-k", "1024"],
        ["--model", "flash-attn", "--seq", "1024", "--steps", "30",
         "--block-q", "1024", "--block-k", "1024"],
        ["--model", "flash-attn", "--seq", "1024", "--steps", "30",
         "--block-q", "256", "--block-k", "1024"],
        ["--model", "flash-attn", "--seq", "2048", "--steps", "30"],
        ["--model", "flash-attn", "--seq", "2048", "--steps", "30",
         "--block-q", "512", "--block-k", "2048"],
        ["--model", "flash-attn", "--seq", "2048", "--steps", "30",
         "--block-q", "1024", "--block-k", "2048"],
        ["--model", "flash-attn", "--seq", "4096", "--steps", "30"],
        ["--model", "flash-attn", "--seq", "8192", "--steps", "30"],
    ]),
    ("loss_chunk_r05.json", [
        ["--loss-chunk", "128"],
        ["--loss-chunk", "64"],
        ["--seq", "1024", "--loss-chunk", "128"],
        ["--seq", "1024"],
    ]),
    ("medium_r05.json", [
        ["--preset", "medium", "--steps", "10"],
        ["--preset", "medium", "--steps", "10", "--remat-policy", "dots"],
    ]),
]


def tpu_up(timeout=90):
    code = "import jax; print(len(jax.devices()))"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
        return r.returncode == 0 and r.stdout.strip().isdigit()
    except subprocess.TimeoutExpired:
        return False


def run_bench(argv, timeout=1200):
    cmd = [sys.executable, os.path.join(REPO, "bench.py"),
           "--steps", "20"] + argv
    print("::", " ".join(argv) or "(default)", flush=True)
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                           timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"error": "bench_timeout", "argv": argv}
    line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{}"
    try:
        d = json.loads(line)
    except json.JSONDecodeError:
        d = {"error": "unparseable", "stderr": r.stderr[-300:]}
    d["argv"] = argv
    d["rc"] = r.returncode
    print("  ->", json.dumps({k: d.get(k) for k in
                              ("value", "vs_baseline", "error")}), flush=True)
    return d


def main():
    n = 0
    while not tpu_up():
        n += 1
        print(f"tunnel down (probe {n}); sleeping 120s", flush=True)
        time.sleep(120)
    print("tunnel is UP — running round-5 plan", flush=True)
    for fname, grid in PLAN:
        out = []
        for argv in grid:
            out.append(run_bench(argv))
            with open(os.path.join(ART, fname), "w") as f:
                json.dump(out, f, indent=1)
        print(f"{fname} done", flush=True)
    print("round-5 capture complete", flush=True)


if __name__ == "__main__":
    main()

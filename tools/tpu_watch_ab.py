#!/usr/bin/env python
"""Wait for the TPU tunnel to come back, then run the round-4 remat-policy /
scan-unroll A/B grid and write artifacts/remat_unroll_r04.json.

The tunnel's observed failure modes are UNAVAILABLE errors and silent
hangs, so availability is probed in a subprocess with a hard timeout.
Run under tmux: python tools/tpu_watch_ab.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GRID = [
    ["--remat-policy", "dots"],
    ["--remat-policy", "dots", "--scan-unroll", "2"],
    ["--scan-unroll", "2"],
    ["--scan-unroll", "3"],
    [],  # default full/1 re-measured in the same session for a fair A/B
]

# After the A/B: re-confirm the new flash tile defaults and one MoE
# point on the same session -> artifacts/confirm_r04.json
CONFIRM = [
    ["--model", "flash-attn", "--seq", "8192", "--steps", "30"],
    ["--model", "flash-attn", "--seq", "4096", "--steps", "30"],
    ["--model", "gpt2-moe", "--steps", "20"],
    ["--preset", "medium", "--steps", "10"],
    ["--preset", "medium", "--steps", "10", "--remat-policy", "dots"],
]


def tpu_up(timeout=90):
    code = "import jax; print(len(jax.devices()))"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=timeout)
        return r.returncode == 0 and r.stdout.strip().isdigit()
    except subprocess.TimeoutExpired:
        return False


def run_bench(argv, timeout=1200):
    cmd = [sys.executable, os.path.join(REPO, "bench.py"),
           "--steps", "20"] + argv
    print("::", " ".join(argv) or "(default)", flush=True)
    r = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                       timeout=timeout)
    line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{}"
    try:
        d = json.loads(line)
    except json.JSONDecodeError:
        d = {"error": "unparseable", "stderr": r.stderr[-300:]}
    d["argv"] = argv
    d["rc"] = r.returncode
    print("  ->", json.dumps({k: d.get(k) for k in
                              ("value", "vs_baseline", "error")}), flush=True)
    return d


def main():
    n = 0
    while not tpu_up():
        n += 1
        print(f"tunnel down (probe {n}); sleeping 120s", flush=True)
        time.sleep(120)
    print("tunnel is UP — running A/B grid", flush=True)
    out = []
    for argv in GRID:
        out.append(run_bench(argv))
        with open(os.path.join(REPO, "artifacts/remat_unroll_r04.json"),
                  "w") as f:
            json.dump(out, f, indent=1)
    print("A/B done -> artifacts/remat_unroll_r04.json", flush=True)
    out = []
    for argv in CONFIRM:
        out.append(run_bench(argv))
        with open(os.path.join(REPO, "artifacts/confirm_r04.json"),
                  "w") as f:
            json.dump(out, f, indent=1)
    print("confirm done -> artifacts/confirm_r04.json", flush=True)


if __name__ == "__main__":
    main()

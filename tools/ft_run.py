"""Fault-tolerance supervisor: relaunch training until it completes,
inject deterministic kills, and report goodput as ONE JSON line:

  {"metric": "ft_goodput", "value": 0.87, "unit": "fraction", "rc": 0,
   "extras": {"faults_survived": 2, "restarts": 2, "useful_steps": 18,
   "lost_steps": 1, "checkpoint_overhead_s": .., ...}}

The restart loop is what ``pod_run train`` lacked before
``--max-restarts``: a child exiting with a fault-tolerance sentinel
code (75 = graceful preemption snapshot saved, 113 = hard chaos kill)
is RELAUNCHED, and the step-granular cursor in the checkpoint
(quintnet_tpu/ft/) makes the relaunched process continue mid-epoch
with bit-identical results (tests/test_ft.py proves the bit-identity;
this tool proves the operational loop end-to-end and prices it).

Faults are armed per attempt through the ``QT_CHAOS`` env var: each
launch gets the next un-consumed kill from ``--kill-at`` (GLOBAL step
numbers — the relaunched run resumes, passes its old death point, and
dies at the next armed step, the repeated-preemption pod scenario).

Modes:
  python tools/ft_run.py                         # 2 hard kills, CPU-ok
  python tools/ft_run.py --kill-at 5,11 --kill-mode sigterm
  python tools/ft_run.py --epochs 2 --samples 48 --kill-at 2  # smoke
      (CI runs this — tests/test_ft_bench.py — so the CLI can never rot)
  python tools/ft_run.py --child ...             # internal: one attempt

``--out FILE`` appends the record to an artifacts JSON list the same
way serve_bench.py artifacts are kept (bench.last_known_result scans
them — goodput gets the same staleness story as the perf benches).
Report schema: docs/fault_tolerance.md.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# child: one training attempt (resumes from whatever the checkpoint holds)


def run_child(args) -> int:
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.data import ArrayDataset, make_batches
    from quintnet_tpu.data.datasets import synthetic_mnist
    from quintnet_tpu.ft import (ChaosMonkey, FTContext, GoodputMeter,
                                 PREEMPTED_EXIT_CODE, PreemptionHandler,
                                 TrainingPreempted)
    from quintnet_tpu.models.vit import ViTConfig, vit_model_spec
    from quintnet_tpu.train.trainer import Trainer

    cfg = Config.from_dict({
        "mesh_dim": [1], "mesh_name": ["dp"],
        "training": {"batch_size": args.batch_size, "epochs": args.epochs,
                     "optimizer": "adam", "learning_rate": 1e-3,
                     "log_every": 0, "seed": args.seed,
                     "save_every_steps": args.save_every},
    })
    vcfg = ViTConfig(image_size=28, patch_size=7, in_channels=1,
                     hidden_dim=16, depth=2, num_heads=2, num_classes=10)
    x, y = synthetic_mnist(args.samples, seed=args.seed)
    ds = ArrayDataset(x, y)

    trainer = Trainer(cfg, vit_model_spec(vcfg),
                      checkpoint_dir=os.path.join(args.run_dir,
                                                  "checkpoints"))
    meter = GoodputMeter(emit_markers=True)
    ft = FTContext(preemption=None, chaos=ChaosMonkey.from_env(),
                   goodput=meter)
    with PreemptionHandler() as handler:
        ft.preemption = handler
        try:
            hist = trainer.fit(
                lambda ep, start=0: make_batches(
                    ds, args.batch_size, seed=ep, start_batch=start),
                ft=ft)
        except TrainingPreempted:
            meter.emit(completed=False)
            return PREEMPTED_EXIT_CODE
    hist.to_jsonl(os.path.join(args.run_dir, "history.jsonl"))
    meter.emit(completed=True)
    return 0


# ---------------------------------------------------------------------------
# supervisor: restart loop + goodput aggregation


def supervise(args) -> dict:
    from quintnet_tpu.ft.chaos import CHAOS_ENV, CHAOS_KILL_EXIT_CODE
    from quintnet_tpu.ft.goodput import aggregate
    from quintnet_tpu.ft.preempt import PREEMPTED_EXIT_CODE

    os.makedirs(args.run_dir, exist_ok=True)
    kills = [int(k) for k in args.kill_at.split(",") if k] \
        if args.kill_at else []
    child_cmd = [sys.executable, os.path.abspath(__file__), "--child",
                 "--run-dir", args.run_dir,
                 "--epochs", str(args.epochs),
                 "--samples", str(args.samples),
                 "--batch-size", str(args.batch_size),
                 "--save-every", str(args.save_every),
                 "--seed", str(args.seed),
                 "--platform", args.platform or ""]

    attempts, faults, restarts = [], [], 0
    last_ckpt = 0  # newest checkpointed global step we know of
    t0 = time.time()
    rc = None
    while True:
        env = dict(os.environ)
        env.pop(CHAOS_ENV, None)
        armed = kills[len(faults)] if len(faults) < len(kills) else None
        if armed is not None:
            env[CHAOS_ENV] = json.dumps(
                {"kill_at_step": armed, "mode": args.kill_mode})
        print(f"[ft_run] attempt {restarts + 1}"
              + (f" (armed: kill at step {armed}, {args.kill_mode})"
                 if armed is not None else ""), flush=True)
        resumed_at, killed_at = last_ckpt, None
        p = subprocess.Popen(child_cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)
        for line in p.stdout:
            s = line.decode(errors="replace")
            sys.stdout.write("  " + s)
            try:
                rec = json.loads(s)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if "ft_attempt" in rec:
                attempts.append(rec["ft_attempt"])
                # graceful exits checkpoint at their last reached step
                # (emergency snapshot / end-of-run save)
                last_ckpt = max(last_ckpt, rec["ft_attempt"]["reached"])
            elif "ft_start" in rec:
                resumed_at = last_ckpt = rec["ft_start"]["resumed_at"]
            elif "ft_kill" in rec:
                killed_at = rec["ft_kill"]["global_step"]
                faults.append({"kind": "hard_kill", **rec["ft_kill"]})
        rc = p.wait()
        print(f"[ft_run] attempt {restarts + 1} exited rc={rc}", flush=True)
        if rc == 0:
            break
        if killed_at is not None:
            # hard kill: the attempt never emitted its report — account
            # its executed-but-possibly-lost steps from the markers
            attempts.append({
                "resumed_at": resumed_at, "reached": killed_at,
                "steps_run": max(killed_at - resumed_at, 0),
                "wall_s": 0.0, "save_blocking_s": 0.0, "restore_s": 0.0,
                "fallback_steps": 0, "completed": False,
                "synthetic": True})
        if rc == PREEMPTED_EXIT_CODE and armed is not None:
            # sigterm-mode kill: graceful snapshot, no ft_kill marker
            faults.append({"kind": "preemption", "global_step": armed})
        if restarts >= args.max_restarts:
            print(f"[ft_run] giving up after {restarts} restarts "
                  f"(last rc={rc})", file=sys.stderr)
            break
        if rc not in (PREEMPTED_EXIT_CODE, CHAOS_KILL_EXIT_CODE):
            print(f"[ft_run] rc={rc} is not a fault-tolerance sentinel "
                  "(75/113) — restarting anyway, a preemption can kill "
                  "harder than SIGTERM", file=sys.stderr)
        restarts += 1

    g = aggregate(attempts, wall_s=time.time() - t0, final_step=last_ckpt)
    return {
        "metric": "ft_goodput",
        "value": g["goodput"],
        "unit": "fraction",
        "vs_baseline": 1.0,
        "rc": 0 if rc == 0 else 1,
        "extras": {
            **{k: v for k, v in g.items() if k != "goodput"},
            "faults_injected": len(kills),
            "faults_survived": len(faults),
            "restarts": restarts,
            "kill_mode": args.kill_mode,
            "kill_at": kills,
            "save_every_steps": args.save_every,
            "epochs": args.epochs,
            "samples": args.samples,
            "batch_size": args.batch_size,
            "completed": rc == 0,
        },
    }


def main():
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true",
                    help="internal: run ONE training attempt")
    ap.add_argument("--run-dir", default="runs/ft")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--samples", type=int, default=96,
                    help="synthetic dataset size (steps/epoch = "
                         "samples // batch_size)")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--save-every", type=int, default=2,
                    help="checkpoint cadence in steps "
                         "(training.save_every_steps)")
    ap.add_argument("--kill-at", default="5,11",
                    help="comma-separated GLOBAL steps to kill at, "
                         "consumed one per attempt ('' = no faults)")
    ap.add_argument("--kill-mode", default="hard",
                    choices=("hard", "sigterm"))
    ap.add_argument("--max-restarts", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--platform", default="cpu",
                    help="'cpu' (default: runs anywhere) or 'tpu'")
    ap.add_argument("--out", default=None,
                    help="append the record to this artifacts JSON file")
    args = ap.parse_args()

    if args.child:
        sys.exit(run_child(args))

    out = supervise(args)
    line = json.dumps(out)
    print(line)
    if args.out:
        records = []
        if os.path.exists(args.out):
            try:
                with open(args.out) as f:
                    prev = json.load(f)
                records = prev if isinstance(prev, list) else [prev]
            except (OSError, json.JSONDecodeError):
                records = []
        records.append(out)
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)
    sys.exit(out["rc"])


if __name__ == "__main__":
    main()

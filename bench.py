"""Benchmark: GPT-2 124M training throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no throughput numbers anywhere (BASELINE.md:21),
so ``vs_baseline`` is a real ratio against THIS repo's committed round-1
measurement (BENCH_r01.json: 181.3 samples/s/chip for the default
config, v5e chip, bs 8, seq 512, bf16, remat on) — >1.0 means the
default config got faster than what round 1 shipped. Configs without a
committed point report vs_baseline 1.0.

Modes:
  python bench.py                      # gpt2 training throughput (default)
  python bench.py --model vit          # ViT training throughput
  python bench.py --model gpt2-moe     # MoE variant
  python bench.py --model flash-attn --seq 8192
      # flash-attention kernel vs XLA sdpa forward+backward micro-bench
      # (substantiates the long-seq kernel speedup claim with a
      # measured ratio in the JSON: extras.speedup_vs_sdpa)

``--seq`` > 1024 raises GPT-2 n_positions to match and enables the
flash path (ops/flash_attention.py engages Pallas at seq >= 4096).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

# Round-1 committed reference points (same chip class, default flags of
# that round: bs 8, seq 512, bf16, remat=1). Keyed by metric name.
COMMITTED_BASELINES = {
    "gpt2_124m_seq512_train_samples_per_sec_per_chip": 181.3,
}

HEADLINE_METRIC = "gpt2_124m_seq512_train_samples_per_sec_per_chip"


def _parse_as_of(s):
    """ISO timestamp -> aware UTC datetime for ordering. Git emits
    committer-local offsets (`%cI`), mtime fallbacks are naive local
    time; lexicographic comparison of such mixed strings picks the
    wrong "newest" (e.g. "2026-07-01T09:00:00+09:00" sorts before
    "2026-06-30T21:00:00-08:00" despite being later). Parse, treat
    naive as local, normalize to UTC. Unparseable -> epoch (never
    beats a real timestamp)."""
    import datetime

    try:
        dt = datetime.datetime.fromisoformat(s)
    except (TypeError, ValueError):
        return datetime.datetime.fromtimestamp(0, datetime.timezone.utc)
    if dt.tzinfo is None:
        dt = dt.astimezone()  # naive (mtime fallback) = local time
    return dt.astimezone(datetime.timezone.utc)


def last_known_result(art_dir=None, metric=HEADLINE_METRIC):
    """Most recent committed measurement of ``metric`` from
    artifacts/*.json, clearly labelled stale.

    Rounds 3/4 recorded NO number because the tunneled TPU was down at
    the driver's capture time even though real measurements sat in
    committed sweep artifacts. When the backend is unavailable the
    diagnostic JSON now carries the latest such record under
    ``last_known`` (``stale: true`` + its provenance) so a dead tunnel
    can never zero out a round's perf evidence again.

    Provenance timestamp: the artifact's last git commit date, falling
    back to file mtime (dirty/untracked trees).
    """
    import glob
    import os
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    art_dir = art_dir or os.path.join(repo, "artifacts")
    best = None
    for path in sorted(glob.glob(os.path.join(art_dir, "*.json"))):
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        records = data if isinstance(data, list) else [data]
        hits = [r for r in records if isinstance(r, dict)
                and r.get("metric") == metric
                and r.get("rc", 0) == 0 and r.get("value", 0) > 0]
        if not hits:
            continue
        try:
            out = subprocess.run(
                ["git", "log", "-1", "--format=%cI", "--", path],
                capture_output=True, text=True, cwd=repo, timeout=10)
            as_of = out.stdout.strip()
        except (OSError, subprocess.SubprocessError):
            as_of = ""
        if not as_of:
            import datetime

            as_of = datetime.datetime.fromtimestamp(
                os.path.getmtime(path)).isoformat()
        as_of_dt = _parse_as_of(as_of)
        for r in hits:
            # prefer newest artifact (by PARSED timestamp — mixed git
            # offsets / naive mtimes don't sort lexicographically), then
            # records measured under the committed-baseline config
            # (extras.baseline set), then rate
            default_cfg = (r.get("extras") or {}).get("baseline") is not None
            key = (as_of_dt, default_cfg, r.get("value", 0.0))
            if best is None or key > best[0]:
                best = (key, {
                    "stale": True,
                    "as_of": as_of,
                    "source": os.path.relpath(path, repo),
                    "metric": r["metric"],
                    "value": r["value"],
                    "unit": r.get("unit", "samples/s/chip"),
                    "vs_baseline": r.get("vs_baseline"),
                    "mfu": (r.get("extras") or {}).get("mfu"),
                })
    return best[1] if best else None


def _unavailable_json(error_detail, retries=None):
    out = {
        "metric": "backend_unavailable",
        "value": 0.0,
        "unit": "none",
        "vs_baseline": 0.0,
        "error": "tpu_unavailable",
        "error_detail": str(error_detail)[:500],
    }
    if retries is not None:
        out["retries"] = retries
    last = last_known_result()
    if last is not None:
        out["last_known"] = last
    return out


def init_backend_with_retry(retries: int = 5, backoff_s: float = 10.0,
                            attempt_timeout_s: float = 120.0):
    """Touch the JAX backend, retrying transient tunnel outages.

    Round 3 shipped zero perf evidence because the tunneled TPU backend
    returned UNAVAILABLE at capture time and bench.py died with a
    traceback (rc=1). A flaky tunnel must degrade to a diagnostic JSON
    line, never a zeroed round: retry with linear backoff, and on
    persistent failure print well-formed JSON and exit 0.

    The tunnel's other observed failure mode is a HANG (connect blocks
    forever instead of erroring — seen round 4): each attempt runs in a
    daemon thread with a deadline; a stuck attempt counts as a failure
    and the loop still terminates with the diagnostic JSON.
    """
    import threading

    import jax
    import jax.extend.backend

    def try_devices():
        box = {}

        def target():
            try:
                box["devices"] = jax.devices()
            except Exception as e:  # noqa: BLE001 — classified below
                box["err"] = e

        t = threading.Thread(target=target, daemon=True)
        t.start()
        t.join(attempt_timeout_s)
        if t.is_alive():
            # distinct from any backend-RAISED TimeoutError: only this
            # flag means the thread is wedged holding jax's init lock
            box["hang"] = True
        return box

    last_err = None
    for attempt in range(retries):
        box = try_devices()
        if "devices" in box:
            return box["devices"]
        if box.get("hang"):
            # the hung thread holds jax's backend-init lock; no retry
            # can succeed in this process — bail out now
            last_err = TimeoutError(
                f"backend init still blocked after {attempt_timeout_s}s "
                "(tunnel hang)")
            break
        last_err = box["err"]
        if not isinstance(last_err, RuntimeError):
            # not jax's backend-init wrapper: a genuine code/environment
            # bug (ImportError, AttributeError...) — retrying or soft-
            # exiting would mask it as a flaky tunnel; fail loudly
            raise last_err
        if attempt + 1 < retries:
            # Failed backend inits are cached per-process by jax;
            # clear so the next attempt actually retries.
            jax.extend.backend.clear_backends()
            time.sleep(backoff_s * (attempt + 1))
    print(json.dumps(_unavailable_json(last_err, retries=retries)))
    sys.exit(0)


def flops_per_token_gpt2(cfg) -> float:
    """Approximate training FLOPs/token: 6 * N_active params (fwd+bwd).

    For MoE configs the FFN term counts the executed capacity rows —
    top_k * capacity_factor per token (the [E, C, D] expert einsums run
    over padding rows too) — plus the router matmul."""
    d = cfg.n_embd
    attn_params = 4 * d * d
    ffn_params = 8 * d * d
    if getattr(cfg, "n_experts", 0) > 0:
        ffn_params = (cfg.expert_top_k * cfg.capacity_factor * 8 * d * d
                      + d * cfg.n_experts)
    n_params = (
        cfg.vocab_size * d
        + cfg.n_positions * d
        + cfg.n_layer * (attn_params + ffn_params + 13 * d)
    )
    return 6.0 * n_params


def bench_flash_attn(args):
    """Forward+backward attention micro-bench: Pallas flash kernel vs
    the plain XLA sdpa path, GPT-2-base head geometry."""
    import jax
    import jax.numpy as jnp

    from quintnet_tpu.nn.attention import sdpa
    from quintnet_tpu.ops.flash_attention import flash_attention

    B, H, S, Dh = 1, 12, args.seq, 64
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, Dh), jnp.bfloat16)
               for kk in ks)
    blk = dict(block_q=args.block_q, block_k=args.block_k)

    def run(fn):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v).astype(jnp.float32))

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        out = g(q, k, v)  # compile
        float(jnp.sum(out[0].astype(jnp.float32)))
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = g(q, k, v)
        float(jnp.sum(out[0].astype(jnp.float32)))
        return (time.perf_counter() - t0) / args.steps

    t_flash = run(lambda q, k, v: flash_attention(q, k, v, causal=True,
                                                  **blk))
    t_sdpa = run(lambda q, k, v: sdpa(q, k, v, causal=True))

    # causal attention fwd+bwd ~ 3.5 * 2 * B*H*S^2*Dh (fwd 2 matmuls,
    # bwd 5, halved by causal masking in the flash kernel's pruned grid)
    flops = 3.5 * 2.0 * B * H * S * S * Dh
    print(json.dumps({
        "metric": f"flash_attn_seq{args.seq}_fwdbwd_time_ms",
        "value": round(t_flash * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(t_sdpa / t_flash, 3),
        "extras": {
            "sdpa_time_ms": round(t_sdpa * 1e3, 3),
            "speedup_vs_sdpa": round(t_sdpa / t_flash, 3),
            "flash_tflops": round(flops / t_flash / 1e12, 2),
            "block_q": args.block_q,
            "block_k": args.block_k,
            "backend": jax.default_backend(),
        },
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2",
                    choices=["gpt2", "gpt2-moe", "vit", "flash-attn",
                             "llama", "llama-moe"])
    ap.add_argument("--preset", default="base",
                    choices=["base", "medium", "large", "xl"],
                    help="GPT-2 size preset (--model gpt2/gpt2-moe); "
                         "bigger presets raise arithmetic intensity and "
                         "MFU on one chip until HBM runs out")
    ap.add_argument("--experts", type=int, default=8,
                    help="expert count for --model gpt2-moe")
    from quintnet_tpu.ops.flash_attention import (PALLAS_BLOCK_K,
                                                  PALLAS_BLOCK_Q)

    ap.add_argument("--block-q", type=int, default=PALLAS_BLOCK_Q,
                    help="flash kernel q tile (--model flash-attn; "
                         "default tracks the library's measured-best "
                         "ops/flash_attention.PALLAS_BLOCK_Q)")
    ap.add_argument("--block-k", type=int, default=PALLAS_BLOCK_K,
                    help="flash kernel k tile (--model flash-attn)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--remat", default=1, type=int,
                    help="rematerialise blocks in backward (default 1: "
                         "measured faster on v5e — 188.3 vs 169.5 "
                         "samples/s/chip at bs 8/seq 512, round-2 A-B. "
                         "Remat shrinks the live activation set, so XLA "
                         "keeps the backward working set in VMEM/HBM "
                         "without spilling; the recompute FLOPs are "
                         "cheaper than the saved memory traffic)")
    ap.add_argument("--remat-policy", default="full",
                    choices=["full", "dots"],
                    help="remat granularity when --remat 1: 'full' "
                         "recomputes the whole block in backward; "
                         "'dots' keeps matmul outputs and recomputes "
                         "only elementwise work (jax dots_saveable)")
    ap.add_argument("--scan-unroll", type=int, default=1,
                    help="lax.scan unroll factor over the layer stack")
    ap.add_argument("--mu-dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="Adam first-moment dtype: bfloat16 halves the "
                         "m read+write HBM traffic in the optimizer "
                         "tail (the trace-measured ~4.5 ms batch-"
                         "independent span, docs/PERF_r04.md); nu "
                         "stays f32 (second moments span too many "
                         "decades). Mirrors training.adam_mu_dtype.")
    ap.add_argument("--vocab-parallel", action="store_true",
                    help="shard wte + sharded-CE over tp (multi-chip)")
    ap.add_argument("--loss-chunk", type=int, default=0,
                    help="chunked CE: compute the CLM loss in sequence "
                         "chunks of N positions so full [B,S,V] f32 "
                         "logits never materialise (0=off)")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the timed "
                         "steps into DIR (inspect with xprof/tensorboard)")
    ap.add_argument("--platform", default=None,
                    help="override the JAX platform (e.g. 'cpu' to smoke-"
                         "test the bench loop without the TPU tunnel; "
                         "this environment's sitecustomize pins 'axon' "
                         "and ignores the JAX_PLATFORMS env var)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.parallel.strategy import get_strategy

    devices = init_backend_with_retry()

    if args.model == "flash-attn":
        bench_flash_attn(args)
        return

    n_dev = len(devices)
    cfg = Config.from_dict({
        "mesh_dim": [n_dev], "mesh_name": ["dp"],
        "training": {"batch_size": args.batch * n_dev,
                     "optimizer": "adamw", "grad_clip_norm": 1.0,
                     "remat": bool(args.remat)},
    })
    strat = get_strategy("auto" if n_dev > 1 else "dp", cfg)

    compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else None
    remat = ("dots" if (args.remat and args.remat_policy == "dots")
             else bool(args.remat))

    if args.model in ("gpt2", "gpt2-moe"):
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec

        preset = getattr(GPT2Config, args.preset)()
        if args.model == "gpt2-moe":
            gcfg = dataclasses.replace(preset, n_experts=args.experts,
                                       expert_top_k=2)
        else:
            gcfg = preset
        use_flash = args.seq >= 4096
        if args.seq > gcfg.n_positions:
            gcfg = dataclasses.replace(gcfg, n_positions=args.seq)
        if args.vocab_parallel:
            gcfg = dataclasses.replace(gcfg, vocab_parallel=True,
                                       padded_vocab_size=50304)
        if args.loss_chunk:
            gcfg = dataclasses.replace(gcfg, loss_chunk=args.loss_chunk)
        if args.scan_unroll != 1:
            gcfg = dataclasses.replace(gcfg, scan_unroll=args.scan_unroll)
        model = gpt2_model_spec(gcfg, remat=remat,
                                use_flash=use_flash,
                                compute_dtype=compute_dtype)
        ids = np.random.default_rng(0).integers(
            0, gcfg.vocab_size, size=(args.batch * n_dev, args.seq),
            dtype=np.int32)
        batch = (jnp.asarray(ids), jnp.asarray(ids))
        flops_per_step = (flops_per_token_gpt2(gcfg)
                          * args.batch * n_dev * args.seq)
        size = {"base": "124m", "medium": "355m", "large": "774m",
                "xl": "1558m"}[args.preset]
        name = f"gpt2_{size}" if args.model == "gpt2" else \
            f"gpt2_moe{args.experts}"
        metric = f"{name}_seq{args.seq}_train_samples_per_sec_per_chip"
    elif args.model in ("llama", "llama-moe"):
        from quintnet_tpu.models.llama import LlamaConfig, llama_init, \
            llama_model_spec

        lmap = {"base": LlamaConfig.llama_160m,
                "xl": LlamaConfig.llama32_1b}
        if args.preset not in lmap:
            ap.error(f"--model {args.model} supports --preset base "
                     f"(160M) or xl (3.2-1B); got {args.preset!r}")
        lcfg = lmap[args.preset]()
        if args.model == "llama-moe":
            lcfg = dataclasses.replace(lcfg, n_experts=args.experts,
                                       expert_top_k=2)
        if args.seq > lcfg.n_positions:
            lcfg = dataclasses.replace(lcfg, n_positions=args.seq)
        if args.scan_unroll != 1:
            lcfg = dataclasses.replace(lcfg, scan_unroll=args.scan_unroll)
        model = llama_model_spec(lcfg, remat=remat,
                                 use_flash=args.seq >= 4096,
                                 compute_dtype=compute_dtype)
        ids = np.random.default_rng(0).integers(
            0, lcfg.vocab_size, size=(args.batch * n_dev, args.seq),
            dtype=np.int32)
        batch = (jnp.asarray(ids), jnp.asarray(ids))
        n_params = sum(int(np.prod(l.shape)) for l in
                       jax.tree.leaves(llama_init(jax.random.key(0), lcfg)))
        flops_per_step = 6.0 * n_params * args.batch * n_dev * args.seq
        tag = ("llama" if args.model == "llama"
               else f"llama_moe{args.experts}")
        metric = (f"{tag}_{round(n_params / 1e6)}m_seq{args.seq}"
                  "_train_samples_per_sec_per_chip")
    else:
        from quintnet_tpu.models.vit import (ViTConfig, vit_init,
                                             vit_model_spec)

        vcfg = ViTConfig(hidden_dim=64, depth=8, num_heads=4)
        model = vit_model_spec(vcfg)
        x = np.random.default_rng(0).normal(
            size=(args.batch * n_dev, 28, 28, 1)).astype(np.float32)
        y = np.random.default_rng(1).integers(0, 10, size=(args.batch * n_dev,))
        batch = (jnp.asarray(x), jnp.asarray(y.astype(np.int32)))
        # actual parameter count (round 1 used a fabricated constant)
        n_params = sum(int(np.prod(l.shape)) for l in
                       jax.tree.leaves(vit_init(jax.random.key(0), vcfg)))
        flops_per_step = (6.0 * n_params * vcfg.seq_len
                          * args.batch * n_dev)
        metric = "vit_mnist_train_samples_per_sec_per_chip"

    opt = optax.adamw(1e-4, mu_dtype=(jnp.bfloat16
                                      if args.mu_dtype == "bfloat16"
                                      else None))
    params = strat.shard_params(model, model.init(jax.random.key(0)))
    opt_state = strat.init_opt_state(model, opt, params)
    b = strat.shard_batch(batch, model)
    step = strat.make_train_step(model, opt)

    # compile + warmup. NOTE: float(loss) (device->host copy) is the sync
    # barrier — jax.block_until_ready returns early on the tunneled
    # 'axon' TPU platform in this environment.
    for _ in range(args.warmup):
        params, opt_state, loss = step(params, opt_state, b)
    float(loss)

    if args.trace:
        jax.profiler.start_trace(args.trace)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, b)
    loss_val = float(loss)
    dt = (time.perf_counter() - t0) / args.steps
    if args.trace:
        jax.profiler.stop_trace()

    samples_per_sec = args.batch * n_dev / dt
    per_chip = samples_per_sec / n_dev
    flops_rate = flops_per_step / dt / n_dev
    # v5e peak: 197 TFLOP/s bf16 per chip
    mfu = flops_rate / 197e12 if jax.default_backend() == "tpu" else 0.0
    # a committed baseline applies only to the config class it was
    # measured under (bs 8/chip, bf16, dense loss); remat is the knob
    # being tuned, so it MAY differ — that improvement is the point
    default_config = (args.batch == 8 and args.dtype == "bfloat16"
                      and not args.vocab_parallel)
    baseline = COMMITTED_BASELINES.get(metric) if default_config else None

    print(json.dumps({
        "metric": metric,
        "value": round(per_chip, 3),
        "unit": "samples/s/chip",
        "vs_baseline": (round(per_chip / baseline, 4)
                        if baseline else 1.0),
        "extras": {
            "step_time_s": round(dt, 4),
            "devices": n_dev,
            "backend": jax.default_backend(),
            "batch_per_chip": args.batch,
            "dtype": args.dtype,
            "remat": bool(args.remat),
            "remat_policy": args.remat_policy,
            "scan_unroll": args.scan_unroll,
            "mu_dtype": args.mu_dtype,
            "mfu": round(mfu, 4),
            "loss": loss_val,
            "baseline": baseline,
        },
    }))


if __name__ == "__main__":
    try:
        main()
    except RuntimeError as e:
        # Still emit one JSON line, but only classify genuine tunnel
        # outages as soft failures (rc=0); other RuntimeErrors (OOM,
        # XlaRuntimeError mid-run) are real regressions and keep rc=1
        # so they can't masquerade as infrastructure noise.
        msg = str(e)
        unavailable = ("UNAVAILABLE" in msg or "Unable to initialize"
                       in msg or "failed to connect" in msg.lower())
        out = {
            "metric": "backend_failed_midrun",
            "value": 0.0,
            "unit": "none",
            "vs_baseline": 0.0,
            "error": "tpu_unavailable" if unavailable else "runtime_error",
            "error_detail": msg[:500],
        }
        if unavailable:
            last = last_known_result()
            if last is not None:
                out["last_known"] = last
        print(json.dumps(out))
        sys.exit(0 if unavailable else 1)

"""Benchmark: GPT-2 124M training throughput on the local chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no throughput numbers anywhere (BASELINE.md:21),
so vs_baseline is reported against a fixed reference point derived from
the reference's own hardware story: its GPT-2 run config processes a
512-sample global batch per step on 8xA100 (micro 32 x grad_acc 8 x dp2,
examples/gpt2_config.yaml); lacking its samples/sec we normalise to 1.0
and additionally report measured MFU in the JSON extras.

Usage: python bench.py [--model gpt2|vit] [--steps 20] [--batch N]
"""

from __future__ import annotations

import argparse
import json
import time


def flops_per_token_gpt2(cfg) -> float:
    """Approximate training FLOPs/token: 6 * N_active params (fwd+bwd).

    For MoE configs the FFN term counts the executed capacity rows —
    top_k * capacity_factor per token (the [E, C, D] expert einsums run
    over padding rows too) — plus the router matmul."""
    d = cfg.n_embd
    attn_params = 4 * d * d
    ffn_params = 8 * d * d
    if getattr(cfg, "n_experts", 0) > 0:
        ffn_params = (cfg.expert_top_k * cfg.capacity_factor * 8 * d * d
                      + d * cfg.n_experts)
    n_params = (
        cfg.vocab_size * d
        + cfg.n_positions * d
        + cfg.n_layer * (attn_params + ffn_params + 13 * d)
    )
    return 6.0 * n_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2",
                    choices=["gpt2", "gpt2-moe", "vit"])
    ap.add_argument("--experts", type=int, default=8,
                    help="expert count for --model gpt2-moe")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float32"])
    ap.add_argument("--remat", default=1, type=int,
                    help="rematerialise blocks in backward (1) or keep "
                         "activations (0); 0 is faster when HBM allows")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.parallel.strategy import get_strategy

    n_dev = len(jax.devices())
    cfg = Config.from_dict({
        "mesh_dim": [n_dev], "mesh_name": ["dp"],
        "training": {"batch_size": args.batch * n_dev,
                     "optimizer": "adamw", "grad_clip_norm": 1.0,
                     "remat": bool(args.remat)},
    })
    strat = get_strategy("auto" if n_dev > 1 else "dp", cfg)

    if args.model in ("gpt2", "gpt2-moe"):
        from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec

        if args.model == "gpt2-moe":
            gcfg = GPT2Config(n_experts=args.experts,
                              expert_top_k=2)
        else:
            gcfg = GPT2Config.base()
        compute_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else None
        model = gpt2_model_spec(gcfg, remat=bool(args.remat),
                                compute_dtype=compute_dtype)
        ids = np.random.default_rng(0).integers(
            0, gcfg.vocab_size, size=(args.batch * n_dev, args.seq),
            dtype=np.int32)
        batch = (jnp.asarray(ids), jnp.asarray(ids))
        flops_per_step = (flops_per_token_gpt2(gcfg)
                          * args.batch * n_dev * args.seq)
        name = "gpt2_124m" if args.model == "gpt2" else \
            f"gpt2_moe{args.experts}"
        metric = f"{name}_seq{args.seq}_train_samples_per_sec_per_chip"
    else:
        from quintnet_tpu.models.vit import ViTConfig, vit_model_spec

        vcfg = ViTConfig(hidden_dim=64, depth=8, num_heads=4)
        model = vit_model_spec(vcfg)
        x = np.random.default_rng(0).normal(
            size=(args.batch * n_dev, 28, 28, 1)).astype(np.float32)
        y = np.random.default_rng(1).integers(0, 10, size=(args.batch * n_dev,))
        batch = (jnp.asarray(x), jnp.asarray(y.astype(np.int32)))
        n_params = 0
        flops_per_step = 6.0 * 800_000 * args.batch * n_dev  # ~0.8M params
        metric = "vit_mnist_train_samples_per_sec_per_chip"

    opt = optax.adamw(1e-4)
    params = strat.shard_params(model, model.init(jax.random.key(0)))
    opt_state = strat.init_opt_state(model, opt, params)
    b = strat.shard_batch(batch)
    step = strat.make_train_step(model, opt)

    # compile + warmup. NOTE: float(loss) (device->host copy) is the sync
    # barrier — jax.block_until_ready returns early on the tunneled
    # 'axon' TPU platform in this environment.
    for _ in range(args.warmup):
        params, opt_state, loss = step(params, opt_state, b)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        params, opt_state, loss = step(params, opt_state, b)
    loss_val = float(loss)
    dt = (time.perf_counter() - t0) / args.steps

    samples_per_sec = args.batch * n_dev / dt
    per_chip = samples_per_sec / n_dev
    flops_rate = flops_per_step / dt / n_dev
    # v5e peak: 197 TFLOP/s bf16 per chip
    mfu = flops_rate / 197e12 if jax.default_backend() == "tpu" else 0.0

    print(json.dumps({
        "metric": metric,
        "value": round(per_chip, 3),
        "unit": "samples/s/chip",
        "vs_baseline": 1.0,
        "extras": {
            "step_time_s": round(dt, 4),
            "devices": n_dev,
            "backend": jax.default_backend(),
            "mfu": round(mfu, 4),
            "loss": loss_val,
        },
    }))


if __name__ == "__main__":
    main()

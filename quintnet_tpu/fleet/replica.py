"""One ServeEngine on a worker thread.

The replica owns the thread that drives ``engine.step()`` and the tiny
inbox the fleet's dispatcher feeds. Everything request-shaped flows
through two callbacks back into the fleet (``on_finish``, ``on_death``)
so the fleet keeps a single source of truth for routing state.

Lock discipline (deadlock-free by construction):

- the replica's own condition lock guards ONLY the inbox and the
  pause/stop flags; the worker drains the inbox under it, releases,
  then runs the engine and fleet callbacks WITHOUT it;
- ``in_flight`` / ``outstanding_tokens`` are routing counters owned by
  the FLEET and mutated only under the fleet lock (dispatch and the
  finish/death callbacks all hold it);
- the dispatcher calls :meth:`enqueue` while holding the fleet lock —
  safe, because the worker never acquires the fleet lock while holding
  the replica lock.

Death contract: ANY exception out of the step loop (a
``ft.ChaosMonkey`` raise, a real engine bug) marks the replica DEAD
and hands the fleet every unfinished request's
:class:`~quintnet_tpu.serve.scheduler.RequestProgress` — engine-known
work via ``engine.export_progress()`` (exact at the step boundary:
generated tokens + the evolved PRNG key) plus inbox items the worker
never ingested (their original payloads). The fleet re-submits these
to healthy replicas via ``engine.restore_progress`` and the output
stream continues token-identically.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

from quintnet_tpu.fleet.admission import Overloaded
from quintnet_tpu.fleet.health import DEAD, HEALTHY, STOPPED


class Replica:
    """A named ServeEngine + its worker thread."""

    def __init__(self, name: str, engine_factory: Callable, *,
                 chaos=None, max_dispatch: Optional[int] = None,
                 on_finish: Callable = None, on_death: Callable = None,
                 on_reject: Callable = None, poll_s: float = 0.05):
        self.name = name
        self.engine = engine_factory()
        self.chaos = chaos
        # dispatch window: how many unfinished requests the fleet may
        # park on this replica before the rest waits in the FLEET queue
        # (where shedding policy applies) — engine slots + one refill
        self.max_dispatch = int(max_dispatch or 2 * self.engine.max_slots)
        self._on_finish = on_finish
        self._on_death = on_death
        self._on_reject = on_reject
        self._poll_s = poll_s

        self.state = HEALTHY
        self.error: Optional[BaseException] = None
        self.steps = 0              # engine steps taken (chaos counter)
        # fleet-owned routing counters (mutated under the FLEET lock)
        self.in_flight = 0
        self.outstanding_tokens = 0

        self._cv = threading.Condition()
        self._inbox: List[Tuple] = []        # (fleet_req, progress|None)
        self._paused = False
        self._stop = False
        self._rid2freq = {}                  # engine rid -> fleet request
        self._thread = threading.Thread(
            target=self._worker, name=f"fleet-{name}", daemon=True)
        self._thread.start()

    # ---- fleet-facing surface (dispatcher/fleet-lock side) -----------
    @property
    def paused(self) -> bool:
        return self._paused

    def adapter_resident(self, adapter_id: str) -> bool:
        """The router's affinity predicate: is the adapter's weight
        tree resident in THIS replica's registry right now? (Registry
        reads are registry-lock protected; the dispatcher calls this
        under the fleet lock without touching engine state.)"""
        reg = getattr(self.engine, "adapters", None)
        return reg is not None and reg.is_resident(adapter_id)

    def enqueue(self, freq, progress=None) -> None:
        """Hand one fleet request (optionally with a migration resume
        payload) to the worker."""
        with self._cv:
            self._inbox.append((freq, progress))
            self._cv.notify_all()

    def pause(self) -> None:
        """Stop stepping (and stop being a dispatch candidate); already
        dispatched work freezes in place until :meth:`resume`."""
        with self._cv:
            self._paused = True
            self._cv.notify_all()

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def stop(self, *, join_timeout: float = 10.0) -> None:
        """Clean shutdown: the worker exits without a death callback.
        In-flight requests are abandoned — the fleet errors them (this
        is the close() path, after drain has emptied the fleet)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=join_timeout)
        if self.state == HEALTHY:
            self.state = STOPPED

    def unfinished(self) -> List:
        """Fleet requests dispatched here and not yet finished (read
        under the fleet lock at death/close time)."""
        with self._cv:
            inbox = [f for f, _p in self._inbox]
        return inbox + list(self._rid2freq.values())

    def drain_inbox(self) -> List[Tuple]:
        """Take everything still in the inbox. The fleet calls this
        (under the fleet lock) when handling this replica's death: the
        worker sets DEAD and exports WITHOUT the fleet lock, so the
        dispatcher can race one last enqueue into the dead inbox —
        re-draining under the lock that enqueues are made under closes
        the window."""
        with self._cv:
            items, self._inbox = self._inbox, []
        return items

    # ---- worker ------------------------------------------------------
    def _ingest(self, freq, progress) -> None:
        # every request routes engine tokens through freq.deliver: it
        # stamps first-token time (fleet TTFT includes queue wait) and
        # forwards to the user's streaming callback when there is one
        def deliver(_rid, token, last, _freq=freq):
            _freq.deliver(token, last)

        if progress is None:
            # the fleet's deadline becomes the ENGINE's: remaining
            # budget re-anchored on this engine's clock, so a request
            # mid-decode at its deadline is retired typed
            # (DeadlineExceeded) instead of finishing a stream the
            # client abandoned
            deadline_s = freq.remaining_deadline()
            if deadline_s is not None and deadline_s <= 0:
                raise Overloaded(
                    "deadline",
                    f"request {freq.fid} reached its deadline between "
                    f"dispatch and ingest")
            rid = self.engine.submit(
                freq.prompt, freq.max_new_tokens, key=freq.key,
                priority=freq.priority, on_token=deliver,
                adapter_id=freq.adapter_id, deadline_s=deadline_s,
                trace_id=getattr(freq, "trace_id", None))
        else:
            # progress carries the adapter binding; restore re-pins it
            # from THIS replica's registry (loading on a cold replica)
            rid = self.engine.restore_progress(progress,
                                               on_token=deliver)
        self._rid2freq[rid] = freq

    def _worker(self) -> None:
        try:
            while True:
                with self._cv:
                    while (not self._stop and not self._inbox
                           and (self._paused
                                or not self.engine.has_work)):
                        self._cv.wait(self._poll_s)
                    if self._stop:
                        return
                    work, self._inbox = self._inbox, []
                    paused = self._paused
                for freq, progress in work:
                    try:
                        self._ingest(freq, progress)
                    except (ValueError, KeyError, Overloaded) as e:
                        # a REQUEST-scoped rejection (engine submit/
                        # restore validation, unknown adapter, typed
                        # Overloaded/DeadlineExceeded) must not kill
                        # the replica: error that request's waiter only
                        self._on_reject(self, freq, e)
                if paused or not self.engine.has_work:
                    continue
                finished = self.engine.step()
                self.steps += 1
                for rid in finished:
                    freq = self._rid2freq.pop(rid)
                    err = self.engine.request(rid).error
                    if err is not None:
                        # typed terminal failure (DeadlineExceeded):
                        # the waiter gets the error, the replica lives
                        self._on_reject(self, freq, err)
                    else:
                        self._on_finish(self, freq,
                                        self.engine.result(rid))
                if self.chaos is not None:
                    self.chaos.on_step_end(self.steps)
        except Exception as e:  # ChaosKilled or a real engine fault
            self.error = e
            self.state = DEAD
            self._on_death(self, e, self._export_unfinished())

    def _export_unfinished(self) -> List[Tuple]:
        """(fleet_req, RequestProgress) for every request this replica
        held when it died: engine-known work exported exactly (evolved
        keys), never-ingested inbox items with their original payloads."""
        out: List[Tuple] = []
        with self._cv:
            leftover, self._inbox = self._inbox, []
        try:
            for prog in self.engine.export_progress():
                freq = self._rid2freq.pop(prog.rid, None)
                if freq is not None:
                    out.append((freq, prog))
        except Exception:
            # the engine is too broken even to export; fall back to the
            # last checkpoint the FLEET holds for each request (its
            # submit payload, or the progress from a previous
            # migration) — completion is preserved, though a streaming
            # request may see tokens since that checkpoint re-delivered
            pass
        for freq in self._rid2freq.values():
            out.append((freq, freq.progress))
        self._rid2freq.clear()
        out.extend(leftover)
        return out

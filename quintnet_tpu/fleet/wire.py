"""Versioned wire serialization + length-prefixed framing for the
cross-process fleet.

Everything that crosses a process boundary in ``fleet/proc.py`` (and
anything a future remote dispatcher would persist) goes through here:

- **payloads** — ``progress_to_wire``/``progress_from_wire`` for
  :class:`~quintnet_tpu.serve.scheduler.RequestProgress` (THE migration
  contract: prompt + committed tokens + evolved PRNG key + adapter
  binding + remaining deadline), ``request_to_wire``/``request_from_wire``
  for :class:`~quintnet_tpu.serve.scheduler.Request` submit payloads,
  and ``error_to_wire``/``error_from_wire`` for the typed rejection
  types (:class:`~quintnet_tpu.fleet.admission.Overloaded`,
  :class:`~quintnet_tpu.serve.scheduler.DeadlineExceeded`, plus plain
  ``ValueError``/``KeyError`` request-scoped rejections). Every payload
  carries ``{"kind": ..., "v": N}``; a payload whose version this
  build does not speak is rejected with an actionable
  :class:`WireVersionError` naming both versions — never a KeyError
  three fields deep.
- **framing** — ``send_frame``/``recv_frame``: 4-byte big-endian
  length prefix + UTF-8 JSON over any stream socket. JSON, not pickle:
  a replica process must never be able to execute code in the
  dispatcher by crafting a payload, and the frames stay inspectable
  with tcpdump. Arrays ride as base64 raw bytes + dtype + shape, so a
  PRNG key round-trips bit-exactly (a float/list round-trip would not
  be bit-exact for every dtype and the resume contract IS bit-exactness).

The committed-tokens-only discipline of ``RequestProgress``
(speculative drafts never reach an export, serve/scheduler.py) is what
makes this wire format complete: there is no engine-internal state —
spec drafts, prefix-cache chains, tentative blocks — that needs to
cross the wire for a resume to be token-identical. The restoring
engine rebuilds all of it from ``prompt + generated + key_data``.
"""

from __future__ import annotations

import base64
import json
import struct
from typing import Dict, Optional, Tuple

import numpy as np

# Bump when a payload's schema changes shape. Readers accept exactly
# the versions they know how to decode; unknown versions fail with an
# actionable error instead of silently mis-parsing.
WIRE_VERSION = 1

_LEN = struct.Struct(">I")
MAX_FRAME_BYTES = 64 * 1024 * 1024  # a corrupt length prefix must not
#                                     allocate gigabytes


class WireError(ValueError):
    """Malformed wire payload (bad kind, missing field, bad frame)."""


class WireVersionError(WireError):
    """Payload version this build does not speak."""


class ConnectionClosed(ConnectionError):
    """The peer closed the stream mid-protocol (or before a frame)."""


# ---------------------------------------------------------------------------
# primitives


def _enc_array(a: Optional[np.ndarray]) -> Optional[Dict]:
    if a is None:
        return None
    a = np.ascontiguousarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def _dec_array(d: Optional[Dict]) -> Optional[np.ndarray]:
    if d is None:
        return None
    try:
        raw = base64.b64decode(d["b64"])
        a = np.frombuffer(raw, dtype=np.dtype(d["dtype"]))
        return a.reshape(d["shape"]).copy()
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"malformed array payload {d!r}: {e}") from e


def _check_header(payload: Dict, kind: str,
                  known_versions: Tuple[int, ...] = (WIRE_VERSION,)):
    if not isinstance(payload, dict):
        raise WireError(
            f"expected a {kind!r} payload dict, got {type(payload).__name__}")
    got_kind = payload.get("kind")
    if got_kind != kind:
        raise WireError(
            f"expected payload kind {kind!r}, got {got_kind!r} — the "
            f"frame was routed to the wrong decoder")
    v = payload.get("v")
    if v not in known_versions:
        raise WireVersionError(
            f"{kind} payload version {v!r} is not supported by this "
            f"build (speaks {list(known_versions)}); upgrade the older "
            f"side of the connection — dispatcher and replicas must "
            f"deserialize each other's payloads")


def _require(payload: Dict, kind: str, *fields: str):
    missing = [f for f in fields if f not in payload]
    if missing:
        raise WireError(
            f"{kind} payload (v{payload.get('v')}) is missing required "
            f"field(s) {missing}: {sorted(payload)} present")


# ---------------------------------------------------------------------------
# RequestProgress — the migration contract


def progress_to_wire(p) -> Dict:
    """Serialize a :class:`RequestProgress` (committed tokens only —
    see the class docstring for why that is complete)."""
    return {
        "kind": "request_progress",
        "v": WIRE_VERSION,
        "rid": int(p.rid),
        "prompt": _enc_array(np.asarray(p.prompt, np.int32)),
        "generated": [int(t) for t in p.generated],
        "key_data": _enc_array(None if p.key_data is None
                               else np.asarray(p.key_data)),
        "max_new_tokens": int(p.max_new_tokens),
        "priority": int(p.priority),
        "preemptions": int(p.preemptions),
        "adapter_id": p.adapter_id,
        "deadline_s": (None if p.deadline_s is None
                       else float(p.deadline_s)),
        "prefilled": int(p.prefilled),
        # observability identity (quintnet_tpu/obs/): carried so the
        # destination replica's spans continue the source's timeline.
        # Optional and inert — absent on pre-obs payloads, never
        # touches the resume math — so WIRE_VERSION stays unchanged.
        "trace_id": p.trace_id,
    }


def progress_from_wire(payload: Dict):
    from quintnet_tpu.serve.scheduler import RequestProgress

    _check_header(payload, "request_progress")
    _require(payload, "request_progress", "rid", "prompt", "generated",
             "key_data", "max_new_tokens")
    return RequestProgress(
        rid=int(payload["rid"]),
        prompt=_dec_array(payload["prompt"]),
        generated=[int(t) for t in payload["generated"]],
        key_data=_dec_array(payload["key_data"]),
        max_new_tokens=int(payload["max_new_tokens"]),
        priority=int(payload.get("priority", 0)),
        preemptions=int(payload.get("preemptions", 0)),
        adapter_id=payload.get("adapter_id"),
        deadline_s=payload.get("deadline_s"),
        # chunked-prefill high-water mark (serve/longctx.py) —
        # informational; absent on pre-longctx payloads
        prefilled=int(payload.get("prefilled", 0)),
        trace_id=payload.get("trace_id"))


# ---------------------------------------------------------------------------
# Request — the submit payload


def request_to_wire(req, *, deadline_s: Optional[float] = None) -> Dict:
    """Serialize a :class:`~quintnet_tpu.serve.scheduler.Request`
    submit payload (the callback and engine-runtime fields stay local;
    ``deadline_s`` is the REMAINING budget — absolute clock times do
    not survive a process boundary)."""
    return {
        "kind": "request",
        "v": WIRE_VERSION,
        "rid": int(req.rid),
        "prompt": _enc_array(np.asarray(req.prompt, np.int32)),
        "max_new_tokens": int(req.max_new_tokens),
        "priority": int(req.priority),
        "key_data": _enc_array(None if req.key_data is None
                               else np.asarray(req.key_data)),
        "generated": [int(t) for t in req.generated],
        "adapter_id": req.adapter_id,
        "deadline_s": None if deadline_s is None else float(deadline_s),
    }


def request_from_wire(payload: Dict):
    from quintnet_tpu.serve.scheduler import Request

    _check_header(payload, "request")
    _require(payload, "request", "rid", "prompt", "max_new_tokens")
    req = Request(
        rid=int(payload["rid"]),
        prompt=_dec_array(payload["prompt"]),
        max_new_tokens=int(payload["max_new_tokens"]),
        priority=int(payload.get("priority", 0)),
        adapter_id=payload.get("adapter_id"))
    req.key_data = _dec_array(payload.get("key_data"))
    req.generated = [int(t) for t in payload.get("generated", [])]
    return req, payload.get("deadline_s")


# ---------------------------------------------------------------------------
# typed errors (shed / deadline / request-scoped rejections)


def error_to_wire(e: BaseException) -> Dict:
    from quintnet_tpu.fleet.admission import Overloaded
    from quintnet_tpu.serve.scheduler import DeadlineExceeded

    out = {"kind": "error", "v": WIRE_VERSION, "message": str(e)}
    if isinstance(e, Overloaded):
        out["type"] = "overloaded"
        out["reason"] = e.reason
    elif isinstance(e, DeadlineExceeded):
        out["type"] = "deadline_exceeded"
        out["rid"] = getattr(e, "rid", None)
        out["generated"] = getattr(e, "generated", 0)
    elif isinstance(e, KeyError):
        out["type"] = "key_error"
    else:
        # ValueError and anything else request-scoped: the receiving
        # side re-raises a ValueError with the original message — the
        # TYPE of an arbitrary exception does not cross the wire
        out["type"] = "value_error"
    return out


def error_from_wire(payload: Dict) -> BaseException:
    from quintnet_tpu.fleet.admission import Overloaded
    from quintnet_tpu.serve.scheduler import DeadlineExceeded

    _check_header(payload, "error")
    _require(payload, "error", "type", "message")
    t, msg = payload["type"], payload["message"]
    if t == "overloaded":
        return Overloaded(payload.get("reason", "shutdown"), msg)
    if t == "deadline_exceeded":
        return DeadlineExceeded(msg, rid=payload.get("rid"),
                                generated=int(payload.get("generated", 0)))
    if t == "key_error":
        return KeyError(msg)
    return ValueError(msg)


# ---------------------------------------------------------------------------
# framing


def send_frame(sock, obj: Dict) -> None:
    """One length-prefixed JSON frame. The caller serializes access —
    two threads interleaving sendall() would corrupt the stream."""
    data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionClosed(
                f"peer closed the connection mid-frame "
                f"({len(buf)}/{n} bytes received)")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock) -> Dict:
    """Blocking read of one frame; raises :class:`ConnectionClosed` on
    EOF (a SIGKILL'd peer looks like EOF after the kernel flushes
    whatever it had buffered — the dispatcher drains those frames
    first, which is what keeps the token journal complete)."""
    head = sock.recv(_LEN.size)
    if not head:
        raise ConnectionClosed("peer closed the connection")
    if len(head) < _LEN.size:
        head += _recv_exact(sock, _LEN.size - len(head))
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise WireError(
            f"frame length {n} exceeds MAX_FRAME_BYTES "
            f"({MAX_FRAME_BYTES}) — corrupt length prefix or a "
            f"desynchronized stream")
    try:
        return json.loads(_recv_exact(sock, n).decode("utf-8"))
    except json.JSONDecodeError as e:
        raise WireError(f"frame is not valid JSON: {e}") from e

"""Versioned wire serialization + length-prefixed framing for the
cross-process fleet.

Everything that crosses a process boundary in ``fleet/proc.py`` (and
anything a future remote dispatcher would persist) goes through here:

- **payloads** — ``progress_to_wire``/``progress_from_wire`` for
  :class:`~quintnet_tpu.serve.scheduler.RequestProgress` (THE migration
  contract: prompt + committed tokens + evolved PRNG key + adapter
  binding + remaining deadline), ``request_to_wire``/``request_from_wire``
  for :class:`~quintnet_tpu.serve.scheduler.Request` submit payloads,
  and ``error_to_wire``/``error_from_wire`` for the typed rejection
  types (:class:`~quintnet_tpu.fleet.admission.Overloaded`,
  :class:`~quintnet_tpu.serve.scheduler.DeadlineExceeded`, plus plain
  ``ValueError``/``KeyError`` request-scoped rejections). Every payload
  carries ``{"kind": ..., "v": N}``; a payload whose version this
  build does not speak is rejected with an actionable
  :class:`WireVersionError` naming both versions — never a KeyError
  three fields deep.
- **KV-block frames** — ``kv_chain_to_wire``/``kv_chain_from_wire``
  for the disaggregated fleet's prefill→decode handoff: a published
  prefix chain exported from one replica's :class:`KVPool` (int8
  blocks + per-block scales when the pool is quantized — PR 10's
  layout makes the transfer ~4x smaller at equal positions) framed
  with a **per-frame CRC32 over the canonical payload** so a
  corrupted or truncated transfer is detected at the importer as a
  typed :class:`WireError`, never silently admitted as wrong KV. The
  chain is pure CACHE: an importer that rejects (or never receives)
  the frame falls back to local re-prefill — slower, never wrong —
  which is what makes checksum-reject a safe answer.
- **framing** — ``send_frame``/``recv_frame``: 4-byte big-endian
  length prefix + UTF-8 JSON over any stream socket. JSON, not pickle:
  a replica process must never be able to execute code in the
  dispatcher by crafting a payload, and the frames stay inspectable
  with tcpdump. Arrays ride as base64 raw bytes + dtype + shape, so a
  PRNG key round-trips bit-exactly (a float/list round-trip would not
  be bit-exact for every dtype and the resume contract IS bit-exactness).
  ``recv_frame(..., peer=...)`` names the counterparty in every
  framing error — a dispatcher watching three pools of replicas must
  know WHICH socket desynchronized without correlating stack traces.

The committed-tokens-only discipline of ``RequestProgress``
(speculative drafts never reach an export, serve/scheduler.py) is what
makes this wire format complete: there is no engine-internal state —
spec drafts, prefix-cache chains, tentative blocks — that needs to
cross the wire for a resume to be token-identical. The restoring
engine rebuilds all of it from ``prompt + generated + key_data``.
"""

from __future__ import annotations

import base64
import json
import struct
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

# Bump when a payload's schema changes shape. Readers accept exactly
# the versions they know how to decode; unknown versions fail with an
# actionable error instead of silently mis-parsing.
WIRE_VERSION = 1

_LEN = struct.Struct(">I")
MAX_FRAME_BYTES = 64 * 1024 * 1024  # a corrupt length prefix must not
#                                     allocate gigabytes


class WireError(ValueError):
    """Malformed wire payload (bad kind, missing field, bad frame)."""


class WireVersionError(WireError):
    """Payload version this build does not speak."""


class ConnectionClosed(ConnectionError):
    """The peer closed the stream mid-protocol (or before a frame)."""


# ---------------------------------------------------------------------------
# primitives


def _enc_array(a: Optional[np.ndarray]) -> Optional[Dict]:
    if a is None:
        return None
    a = np.ascontiguousarray(a)
    return {"dtype": str(a.dtype), "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii")}


def _dec_array(d: Optional[Dict]) -> Optional[np.ndarray]:
    if d is None:
        return None
    try:
        raw = base64.b64decode(d["b64"])
        a = np.frombuffer(raw, dtype=np.dtype(d["dtype"]))
        return a.reshape(d["shape"]).copy()
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"malformed array payload {d!r}: {e}") from e


def _check_header(payload: Dict, kind: str,
                  known_versions: Tuple[int, ...] = (WIRE_VERSION,)):
    if not isinstance(payload, dict):
        raise WireError(
            f"expected a {kind!r} payload dict, got {type(payload).__name__}")
    got_kind = payload.get("kind")
    if got_kind != kind:
        raise WireError(
            f"expected payload kind {kind!r}, got {got_kind!r} — the "
            f"frame was routed to the wrong decoder")
    v = payload.get("v")
    if v not in known_versions:
        raise WireVersionError(
            f"{kind} payload version {v!r} is not supported by this "
            f"build (speaks {list(known_versions)}); upgrade the older "
            f"side of the connection — dispatcher and replicas must "
            f"deserialize each other's payloads")


def _require(payload: Dict, kind: str, *fields: str):
    missing = [f for f in fields if f not in payload]
    if missing:
        raise WireError(
            f"{kind} payload (v{payload.get('v')}) is missing required "
            f"field(s) {missing}: {sorted(payload)} present")


# ---------------------------------------------------------------------------
# RequestProgress — the migration contract


def progress_to_wire(p) -> Dict:
    """Serialize a :class:`RequestProgress` (committed tokens only —
    see the class docstring for why that is complete)."""
    return {
        "kind": "request_progress",
        "v": WIRE_VERSION,
        "rid": int(p.rid),
        "prompt": _enc_array(np.asarray(p.prompt, np.int32)),
        "generated": [int(t) for t in p.generated],
        "key_data": _enc_array(None if p.key_data is None
                               else np.asarray(p.key_data)),
        "max_new_tokens": int(p.max_new_tokens),
        "priority": int(p.priority),
        "preemptions": int(p.preemptions),
        "adapter_id": p.adapter_id,
        "deadline_s": (None if p.deadline_s is None
                       else float(p.deadline_s)),
        "prefilled": int(p.prefilled),
        # observability identity (quintnet_tpu/obs/): carried so the
        # destination replica's spans continue the source's timeline.
        # Optional and inert — absent on pre-obs payloads, never
        # touches the resume math — so WIRE_VERSION stays unchanged.
        "trace_id": p.trace_id,
    }


def progress_from_wire(payload: Dict):
    from quintnet_tpu.serve.scheduler import RequestProgress

    _check_header(payload, "request_progress")
    _require(payload, "request_progress", "rid", "prompt", "generated",
             "key_data", "max_new_tokens")
    return RequestProgress(
        rid=int(payload["rid"]),
        prompt=_dec_array(payload["prompt"]),
        generated=[int(t) for t in payload["generated"]],
        key_data=_dec_array(payload["key_data"]),
        max_new_tokens=int(payload["max_new_tokens"]),
        priority=int(payload.get("priority", 0)),
        preemptions=int(payload.get("preemptions", 0)),
        adapter_id=payload.get("adapter_id"),
        deadline_s=payload.get("deadline_s"),
        # chunked-prefill high-water mark (serve/longctx.py) —
        # informational; absent on pre-longctx payloads
        prefilled=int(payload.get("prefilled", 0)),
        trace_id=payload.get("trace_id"))


# ---------------------------------------------------------------------------
# Request — the submit payload


def request_to_wire(req, *, deadline_s: Optional[float] = None) -> Dict:
    """Serialize a :class:`~quintnet_tpu.serve.scheduler.Request`
    submit payload (the callback and engine-runtime fields stay local;
    ``deadline_s`` is the REMAINING budget — absolute clock times do
    not survive a process boundary)."""
    return {
        "kind": "request",
        "v": WIRE_VERSION,
        "rid": int(req.rid),
        "prompt": _enc_array(np.asarray(req.prompt, np.int32)),
        "max_new_tokens": int(req.max_new_tokens),
        "priority": int(req.priority),
        "key_data": _enc_array(None if req.key_data is None
                               else np.asarray(req.key_data)),
        "generated": [int(t) for t in req.generated],
        "adapter_id": req.adapter_id,
        "deadline_s": None if deadline_s is None else float(deadline_s),
    }


def request_from_wire(payload: Dict):
    from quintnet_tpu.serve.scheduler import Request

    _check_header(payload, "request")
    _require(payload, "request", "rid", "prompt", "max_new_tokens")
    req = Request(
        rid=int(payload["rid"]),
        prompt=_dec_array(payload["prompt"]),
        max_new_tokens=int(payload["max_new_tokens"]),
        priority=int(payload.get("priority", 0)),
        adapter_id=payload.get("adapter_id"))
    req.key_data = _dec_array(payload.get("key_data"))
    req.generated = [int(t) for t in payload.get("generated", [])]
    return req, payload.get("deadline_s")


# ---------------------------------------------------------------------------
# KV-block chain — the disaggregated prefill→decode handoff payload


# geometry fields a KV frame must agree on with the importing pool —
# a mismatch is a deployment error (mixed engine specs in one fleet),
# surfaced as a typed WireError at import, never a shape crash inside
# a jitted program
KV_GEOMETRY_FIELDS = ("policy", "block_size", "n_layers", "n_kv_heads",
                      "head_dim")


def kv_chain_checksum(payload: Dict,
                      _decoded: Optional[List] = None,
                      _raw: Optional[List[Dict]] = None) -> int:
    """CRC32 over the frame's header (canonical JSON, minus the
    checksum and the block list) chained with every block's RAW bytes
    — dtype/shape descriptors and decoded array data, not their
    base64/JSON spelling. Hashing the raw bytes keeps the checksum
    O(chain bytes) with no re-serialization of megabyte payloads (the
    decode replica verifies this between decode steps), while still
    catching any flip in geometry, fill counts, array metadata or
    payload bits. Two internal hooks keep each hot path to ONE pass
    over the chain bytes: ``_decoded`` (:func:`kv_chain_from_wire`)
    collects each block's arrays as they are base64-decoded for
    hashing, and ``_raw`` (:func:`kv_chain_to_wire`) supplies the
    per-block raw bytes the encoder just serialized so the export
    side never base64-decodes what it just encoded."""
    head = {k: v for k, v in payload.items()
            if k not in ("crc32", "blocks")}
    crc = zlib.crc32(json.dumps(head, sort_keys=True,
                                separators=(",", ":")).encode("utf-8"))
    for i, b in enumerate(payload.get("blocks", ())):
        if not isinstance(b, dict):
            raise WireError(
                f"kv_chain block entry is {type(b).__name__}, "
                f"expected a dict — cannot checksum the frame")
        try:
            fill = int(b.get("fill", -1))
        except (TypeError, ValueError) as e:
            # null / non-numeric fill from a buggy or corrupted peer:
            # a TYPED error the import handler maps to a failed
            # transfer — never a TypeError that escapes replica_main
            # and reads as a replica death
            raise WireError(
                f"kv_chain block field 'fill' is malformed ({e}) — "
                f"cannot checksum the frame") from e
        crc = zlib.crc32(str(fill).encode("ascii"), crc)
        rec = {"fill": fill} if _decoded is not None else None
        raws = _raw[i] if _raw is not None else None
        for key in ("k", "v", "k_scale", "v_scale"):
            d = b.get(key)
            if d is None:
                crc = zlib.crc32(b"\x00none", crc)
                if rec is not None:
                    rec[key] = None
                continue
            try:
                meta = json.dumps({"dtype": d["dtype"],
                                   "shape": d["shape"]},
                                  sort_keys=True,
                                  separators=(",", ":"))
                raw = (raws[key] if raws is not None
                       else base64.b64decode(d["b64"]))
                if rec is not None:
                    rec[key] = np.frombuffer(
                        raw, dtype=np.dtype(d["dtype"])).reshape(
                            d["shape"]).copy()
            except (KeyError, TypeError, ValueError) as e:
                raise WireError(
                    f"kv_chain block field {key!r} is malformed "
                    f"({e}) — cannot checksum the frame") from e
            crc = zlib.crc32(meta.encode("utf-8"), crc)
            crc = zlib.crc32(raw, crc)
        if rec is not None:
            _decoded.append(rec)
    return crc & 0xFFFFFFFF


def kv_chain_wire_size(payload: Dict) -> int:
    """Conservative OVER-estimate of the framed byte size of a
    KV-chain payload without serializing it (the b64 strings dominate;
    keys, digits and punctuation ride in the per-field slack)."""
    size = 4096
    tokens = payload.get("tokens")
    if isinstance(tokens, dict):
        size += len(tokens.get("b64", "")) + 256
    for b in payload.get("blocks", ()):
        size += 512
        for key in ("k", "v", "k_scale", "v_scale"):
            d = b.get(key)
            if isinstance(d, dict):
                size += len(d.get("b64", "")) + 256
    return size


def kv_chain_fits(payload: Dict) -> bool:
    """Would this KV-chain frame fit under :data:`MAX_FRAME_BYTES`?
    The EXPORTER must check before shipping: an oversized frame would
    trip the receiver's length guard, which reads as a desynchronized
    stream and kills the CONNECTION — turning a healthy replica into
    a declared death. Declining the transfer instead lets the handoff
    take its documented fallback (local re-prefill on the decode
    side: slower, never wrong)."""
    return kv_chain_wire_size(payload) <= MAX_FRAME_BYTES


def kv_chain_to_wire(chain: Dict, *,
                     namespace: Optional[str] = None) -> Dict:
    """Serialize one exported prefix chain
    (:meth:`~quintnet_tpu.serve.kv_pool.KVPool.export_chain`): the
    covered token prefix, the pool geometry the blocks were laid out
    under, and each block's slot data (+ per-block-per-head scales for
    scaled policies) as raw bytes — int8 blocks ship as int8, which is
    what makes a quantized handoff ~4x smaller than f32. The frame
    carries a CRC32 so the importer can refuse a corrupted transfer
    with a typed error instead of caching wrong KV."""
    def enc(a):
        """(encoded dict, raw bytes): the same bytes the b64 field
        spells, kept so the checksum hashes them directly instead of
        base64-decoding what this function just encoded."""
        if a is None:
            return None, None
        a = np.ascontiguousarray(a)
        raw = a.tobytes()
        return {"dtype": str(a.dtype), "shape": list(a.shape),
                "b64": base64.b64encode(raw).decode("ascii")}, raw

    blocks, raw_blocks = [], []
    for b in chain["blocks"]:
        rec, raws = {"fill": int(b["fill"])}, {}
        for key in ("k", "v", "k_scale", "v_scale"):
            # k/v are mandatory in an exported chain; scales only
            # exist under scaled layout policies
            a = b[key] if key in ("k", "v") else b.get(key)
            rec[key], raws[key] = enc(a)
        blocks.append(rec)
        raw_blocks.append(raws)
    payload = {
        "kind": "kv_chain",
        "v": WIRE_VERSION,
        "namespace": namespace,
        "n_tokens": int(chain["n_tokens"]),
        "tokens": _enc_array(np.asarray(chain["tokens"], np.int32)),
        "policy": str(chain["policy"]),
        "block_size": int(chain["block_size"]),
        "n_layers": int(chain["n_layers"]),
        "n_kv_heads": int(chain["n_kv_heads"]),
        "head_dim": int(chain["head_dim"]),
        "blocks": blocks,
    }
    payload["crc32"] = kv_chain_checksum(payload, _raw=raw_blocks)
    return payload


def kv_chain_from_wire(payload: Dict) -> Tuple[Dict, Optional[str]]:
    """Decode + VERIFY one KV-chain frame; returns ``(chain,
    namespace)`` in :meth:`KVPool.import_chain` shape. A checksum
    mismatch — a flipped bit, a truncated block, any corruption the
    transport let through — is a typed :class:`WireError`: the
    importer discards the frame and the handoff either retries or
    falls back to local re-prefill (correct because the chain is just
    cache). Never raises a raw ``KeyError``/``struct.error``."""
    _check_header(payload, "kv_chain")
    _require(payload, "kv_chain", "crc32", "tokens", "n_tokens",
             "blocks", *KV_GEOMETRY_FIELDS)
    if not isinstance(payload["blocks"], list) or not payload["blocks"]:
        raise WireError("kv_chain payload carries no blocks")
    for b in payload["blocks"]:
        if not isinstance(b, dict):
            raise WireError(
                f"kv_chain block entry is {type(b).__name__}, "
                f"expected a dict")
        _require(b, "kv_chain block", "fill", "k", "v")
    want = payload["crc32"]
    # the checksum walk base64-decodes every array to hash its raw
    # bytes; collect them as it goes so the hot path (decode replica,
    # between decode steps) never decodes a megabyte chain twice
    blocks: List = []
    got = kv_chain_checksum(payload, _decoded=blocks)
    if got != want:
        raise WireError(
            f"kv_chain checksum mismatch (frame says {want:#010x}, "
            f"payload hashes to {got:#010x}) — the KV transfer was "
            f"corrupted in flight; discarding the frame (the handoff "
            f"retries or the decode replica re-prefills locally)")
    try:
        chain = {
            "n_tokens": int(payload["n_tokens"]),
            "tokens": _dec_array(payload["tokens"]),
            "policy": payload["policy"],
            "block_size": int(payload["block_size"]),
            "n_layers": int(payload["n_layers"]),
            "n_kv_heads": int(payload["n_kv_heads"]),
            "head_dim": int(payload["head_dim"]),
            "blocks": blocks,
        }
    except WireError:
        raise               # _dec_array already typed it precisely
    except (TypeError, ValueError) as e:
        # null / non-numeric geometry from a buggy peer checksums
        # consistently (the peer hashed the same nulls), so it reaches
        # here — surface it typed, never a TypeError that escapes the
        # import handler and reads as a replica death
        raise WireError(
            f"kv_chain geometry field is malformed ({e}); "
            f"discarding the frame") from e
    return chain, payload.get("namespace")


# ---------------------------------------------------------------------------
# typed errors (shed / deadline / request-scoped rejections)


def error_to_wire(e: BaseException) -> Dict:
    from quintnet_tpu.fleet.admission import Overloaded
    from quintnet_tpu.serve.scheduler import DeadlineExceeded

    out = {"kind": "error", "v": WIRE_VERSION, "message": str(e)}
    if isinstance(e, Overloaded):
        out["type"] = "overloaded"
        out["reason"] = e.reason
    elif isinstance(e, DeadlineExceeded):
        out["type"] = "deadline_exceeded"
        out["rid"] = getattr(e, "rid", None)
        out["generated"] = getattr(e, "generated", 0)
    elif isinstance(e, WireError):
        # distinct from a plain ValueError ON PURPOSE: a WireError is
        # a damaged/mis-framed payload — TRANSIENT, the handoff retry
        # loop re-exports — while a plain ValueError (geometry
        # mismatch, evicted chain) is permanent and goes straight to
        # the fallback
        out["type"] = "wire_error"
    elif isinstance(e, KeyError):
        out["type"] = "key_error"
    else:
        # ValueError and anything else request-scoped: the receiving
        # side re-raises a ValueError with the original message — the
        # TYPE of an arbitrary exception does not cross the wire
        out["type"] = "value_error"
    return out


def error_from_wire(payload: Dict) -> BaseException:
    from quintnet_tpu.fleet.admission import Overloaded
    from quintnet_tpu.serve.scheduler import DeadlineExceeded

    _check_header(payload, "error")
    _require(payload, "error", "type", "message")
    t, msg = payload["type"], payload["message"]
    if t == "overloaded":
        return Overloaded(payload.get("reason", "shutdown"), msg)
    if t == "deadline_exceeded":
        return DeadlineExceeded(msg, rid=payload.get("rid"),
                                generated=int(payload.get("generated", 0)))
    if t == "wire_error":
        return WireError(msg)
    if t == "key_error":
        return KeyError(msg)
    return ValueError(msg)


# ---------------------------------------------------------------------------
# framing


def send_frame(sock, obj: Dict) -> None:
    """One length-prefixed JSON frame. The caller serializes access —
    two threads interleaving sendall() would corrupt the stream."""
    data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


def _peer_name(peer: Optional[str]) -> str:
    return repr(peer) if peer else "peer"


def _recv_exact(sock, n: int, *, peer: Optional[str] = None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionClosed(
                f"{_peer_name(peer)} closed the connection mid-frame "
                f"({len(buf)}/{n} bytes received)")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock, *, peer: Optional[str] = None) -> Dict:
    """Blocking read of one frame; raises :class:`ConnectionClosed` on
    EOF (a SIGKILL'd peer looks like EOF after the kernel flushes
    whatever it had buffered — the dispatcher drains those frames
    first, which is what keeps the token journal complete). ``peer``
    names the counterparty in every error — a truncated frame, a
    corrupt length prefix or non-JSON bytes all surface as typed
    :class:`ConnectionClosed`/:class:`WireError` naming WHO
    desynchronized, never a raw ``struct.error`` (``_LEN.unpack``
    only ever sees exactly 4 bytes) or a bare ``JSONDecodeError``."""
    head = sock.recv(_LEN.size)
    if not head:
        raise ConnectionClosed(
            f"{_peer_name(peer)} closed the connection")
    if len(head) < _LEN.size:
        head += _recv_exact(sock, _LEN.size - len(head), peer=peer)
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise WireError(
            f"frame length {n} from {_peer_name(peer)} exceeds "
            f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES}) — corrupt length "
            f"prefix or a desynchronized stream")
    try:
        return json.loads(_recv_exact(sock, n, peer=peer)
                          .decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise WireError(
            f"frame from {_peer_name(peer)} is not valid JSON "
            f"(flipped bits or a desynchronized stream): {e}") from e

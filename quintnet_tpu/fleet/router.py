"""Replica selection policies.

``least_work`` is the fleet default: route to the replica with the
fewest OUTSTANDING TOKENS — the sum over its dispatched-but-unfinished
requests of the tokens still to be prefilled plus the tokens still to
be decoded. Token count, not request count, is the right load proxy
for continuous batching: one 500-token prompt occupies a slot for as
long as ten 50-token ones, and AlpaServe's result is precisely that
statistical multiplexing on actual work keeps tail latency down under
bursty traffic. ``round_robin`` is the deterministic baseline the
bench compares against (and what tests use when they need to know
exactly which replica got which request).

Adapter affinity (multi-tenant LoRA, serve/adapters.py): a request
bound to an adapter PREFERS replicas whose registry holds the adapter
resident — serving it there skips a safetensors (re)load and keeps
each tenant's working set warm on few replicas instead of thrashing
every LRU. The affinity is a cheap candidate PRE-FILTER ahead of the
load policy, never a hard constraint: when no candidate is warm (a
brand-new tenant, or its replicas are busy/dead) the full candidate
list stands and the chosen replica loads the adapter on demand — the
same path fleet migration relies on.

The router is pure policy: the fleet hands it the CANDIDATE list
(healthy, unpaused, below their dispatch window) under the fleet lock
and it picks one. Ties break on replica name so the choice is
reproducible.
"""

from __future__ import annotations

from typing import List, Optional

from quintnet_tpu.fleet.health import HEALTHY

POLICIES = ("least_work", "round_robin")

# a replica without a pool assignment serves every phase (colocated
# fleets, and the thread fleet's Replica which predates pools)
ANY_POOL = "any"


def eligible(replicas: List, *, pool: Optional[str] = None) -> List:
    """The dispatch-candidate predicate both fleets share (threads:
    fleet/fleet.py; processes: fleet/proc.py): serving state, not
    paused, below its dispatch window. STARTING (process still
    building its engine) and STALLED (missed heartbeats) replicas fail
    the state test exactly like DEAD ones — a stalled replica is
    routed AROUND, never at.

    ``pool`` narrows to one pool of a disaggregated fleet
    (fleet/proc.py): a candidate matches when it belongs to that pool
    or carries no pool assignment (``"any"`` — colocated replicas
    serve every phase). ``pool=None`` keeps the colocated behavior
    byte-identical."""
    return [r for r in replicas
            if r.state == HEALTHY and not r.paused
            and r.in_flight < r.max_dispatch
            and (pool is None
                 or getattr(r, "pool", ANY_POOL) in (pool, ANY_POOL))]


class Router:
    def __init__(self, policy: str = "least_work"):
        if policy not in POLICIES:
            raise ValueError(f"unknown routing policy {policy!r}; "
                             f"expected one of {POLICIES}")
        self.policy = policy
        self._rr = 0

    def pick(self, candidates: List, *,
             adapter_id: Optional[str] = None) -> "object":
        """Choose one replica from a non-empty candidate list. Each
        candidate exposes ``outstanding_tokens``, ``name`` and
        ``adapter_resident(adapter_id)``. ``adapter_id``: narrow to
        the adapter-warm candidates first when any exist (see module
        docstring), then apply the policy unchanged."""
        if not candidates:
            raise ValueError("pick() needs at least one candidate")
        if adapter_id is not None:
            warm = [r for r in candidates
                    if r.adapter_resident(adapter_id)]
            if warm:
                candidates = warm
        if self.policy == "round_robin":
            choice = candidates[self._rr % len(candidates)]
            self._rr += 1
            return choice
        return min(candidates,
                   key=lambda r: (r.outstanding_tokens, r.name))

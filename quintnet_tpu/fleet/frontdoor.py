"""The network front door: an asyncio HTTP/SSE server (stdlib only)
in front of ``fleet.submit``/stream.

Everything upstream of this module speaks Python; everything
downstream speaks HTTP — this is where the fleet's typed backpressure
becomes a protocol a load balancer or client library can act on,
instead of a queue silently converting overload into latency:

====================================  =======================================
fleet signal                          HTTP response
====================================  =======================================
``Overloaded('queue_full')``          **429 Too Many Requests** + Retry-After
``Overloaded('shutdown')``            **503 Service Unavailable** + Retry-After
``Overloaded('deadline')``            **503 Service Unavailable** + Retry-After
``Overloaded('pool_down')``           **503 Service Unavailable** + Retry-After
``serve.DeadlineExceeded``            **504 Gateway Timeout** (typed body)
request timeout / unmet result        **504 Gateway Timeout**
malformed request / never admissible  **400 Bad Request**
====================================  =======================================

``GET /healthz`` on a disaggregated fleet (fleet/proc.py pools) is
three-valued: 200 ``"ok"`` (every pool live), 200 ``"degraded"`` (one
pool down, the fallback ladder still serves — the body's ``"pools"``
map says which), 503 ``"unavailable"`` + Retry-After (nothing can
serve).

The degradation ladder under trouble is explicit and this is its
first rung: **shed new work** (the typed 429/503 above, the queue
stays bounded) → **pause admissions** → **drain** → **migrate** — the
later rungs live in the fleet itself (``pause_all``/``drain`` and the
journal migration of fleet/proc.py). Retry-with-jittered-backoff on
replica connection failure is likewise the fleet dispatcher's job
(re-queue at the front + breaker-gated backoff restarts); the front
door's contract is that a client NEVER sees a replica death — only
tokens, a typed rejection, or its own deadline.

Endpoints:

- ``POST /v1/generate`` — body ``{"prompt": [ints],
  "max_new_tokens": N, "stream": bool, "priority": int,
  "deadline_s": float, "adapter_id": str, "seed": int}``.
  Non-streaming: one JSON response ``{"fid", "output"}``. Streaming
  (``"stream": true``): ``text/event-stream`` with one
  ``data: {"token": t, "last": bool}`` event per generated token —
  across migrations, each token exactly once — then an ``event: done``
  carrying the full output (or ``event: error`` with the typed
  rejection; tokens already streamed stand).
- ``GET /healthz`` — cheap liveness snapshot (``fleet.health()``);
  200 while any replica serves, 503 when none can.
- ``GET /v1/metrics`` — JSON (explicit ``application/json``): the
  fleet's front-door counters (``FleetMetrics.summary()``) under
  ``"frontdoor"`` plus each replica engine's
  ``ServeMetrics.summary()`` under ``"engine_summary"`` (shipped over
  the process fleet's existing stats frame — no second accounting
  path).
- ``GET /metrics`` — the SAME ledgers in Prometheus text exposition
  format (``text/plain; version=0.0.4``; quintnet_tpu/obs/prom.py):
  ``quintnet_fleet_*`` counters, ``quintnet_engine_*{replica="..."}``
  per-replica series, ``quintnet_replica_up`` liveness plus
  heartbeat-staleness and breaker-state gauges — and, when the SLO
  engine / signal plane is armed (obs/slo.py, obs/signals.py), the
  ``quintnet_slo_*`` burn-rate families and
  ``quintnet_pool_pressure_*`` per-pool gauges. Every existing
  counter scrapeable as a time series. Kept separate from
  ``/v1/metrics``: one path per format, both read-only.

With an armed SLO engine, ``GET /healthz`` additionally carries
``"slo": {"breaching": [...], "objectives": {...}}`` and a breach
downgrades 200 ``"ok"`` to 200 ``"degraded"`` — the degraded body
NAMES the breaching objectives; 429/503 ``Retry-After`` is raised to
the admission queue's oldest-wait age when that exceeds the
configured floor.

Works identically over a thread :class:`ServeFleet` and a process
:class:`ProcessFleet` — both expose submit/result/health with the
same typed errors, which is the point of the shared contract.
"""

from __future__ import annotations

import asyncio
import json
import math
import threading
from typing import Dict, Optional, Tuple

from quintnet_tpu.fleet.admission import Overloaded
from quintnet_tpu.fleet.health import HEALTHY
from quintnet_tpu.serve.scheduler import DeadlineExceeded

_REASONS = {400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 413: "Payload Too Large",
            429: "Too Many Requests", 500: "Internal Server Error",
            503: "Service Unavailable", 504: "Gateway Timeout",
            200: "OK"}


class FrontDoor:
    """See module docstring. ``request_timeout_s`` bounds how long one
    HTTP request may wait on the fleet end to end (a deadline the
    CLIENT did not set; ``deadline_s`` in the body is the client's own
    and is enforced by the engines mid-decode). ``retry_after_s``
    seeds the Retry-After header on 429/503 — the client-visible half
    of backpressure."""

    def __init__(self, fleet, *, host: str = "127.0.0.1", port: int = 0,
                 retry_after_s: float = 1.0,
                 request_timeout_s: float = 300.0,
                 max_body_bytes: int = 8 * 1024 * 1024):
        self.fleet = fleet
        self.host = host
        self.port = port          # 0 = ephemeral; real port after start
        self.retry_after_s = float(retry_after_s)
        self.request_timeout_s = float(request_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> Tuple[str, int]:
        """Bind + serve on a background thread; returns (host, port)."""
        if self._thread is not None:
            return self.host, self.port
        started = threading.Event()
        boot_err: Dict = {}

        def run() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            try:
                self._server = loop.run_until_complete(
                    asyncio.start_server(self._handle, self.host,
                                         self.port))
                self.port = self._server.sockets[0].getsockname()[1]
            except OSError as e:
                boot_err["e"] = e
                started.set()
                return
            started.set()
            try:
                loop.run_forever()
            finally:
                self._server.close()
                loop.run_until_complete(self._server.wait_closed())
                loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="fleet-frontdoor")
        self._thread.start()
        started.wait(10.0)
        if "e" in boot_err:
            self._thread = None
            raise boot_err["e"]
        return self.host, self.port

    def close(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def __enter__(self) -> "FrontDoor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------------
    # HTTP plumbing
    # ------------------------------------------------------------------
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                method, path, body = await asyncio.wait_for(
                    self._read_request(reader), timeout=30.0)
            except (asyncio.TimeoutError, ValueError,
                    asyncio.IncompleteReadError) as e:
                await self._respond(writer, 400,
                                    {"error": "bad_request",
                                     "message": str(e)})
                return
            if path == "/healthz" and method == "GET":
                await self._healthz(writer)
            elif path == "/v1/metrics" and method == "GET":
                await self._v1_metrics(writer)
            elif path == "/metrics" and method == "GET":
                await self._prometheus(writer)
            elif path == "/v1/generate":
                if method != "POST":
                    await self._respond(
                        writer, 405, {"error": "method_not_allowed",
                                      "message": "POST /v1/generate"})
                else:
                    await self._generate(writer, body)
            else:
                await self._respond(writer, 404,
                                    {"error": "not_found",
                                     "message": f"no route {path!r}"})
        except (ConnectionResetError, BrokenPipeError):
            pass            # client went away mid-response
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    async def _read_request(self, reader) -> Tuple[str, str, bytes]:
        line = (await reader.readline()).decode("latin-1").strip()
        if not line:
            raise ValueError("empty request line")
        parts = line.split()
        if len(parts) != 3:
            raise ValueError(f"malformed request line {line!r}")
        method, path = parts[0].upper(), parts[1]
        headers = {}
        while True:
            h = (await reader.readline()).decode("latin-1")
            if h in ("\r\n", "\n", ""):
                break
            k, _, v = h.partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length", "0") or "0")
        if n > self.max_body_bytes:
            raise ValueError(
                f"body of {n} bytes exceeds the {self.max_body_bytes} "
                f"byte limit")
        body = await reader.readexactly(n) if n else b""
        return method, path, body

    async def _respond(self, writer, status: int, obj: Dict,
                       headers: Optional[Dict[str, str]] = None) -> None:
        data = json.dumps(obj).encode("utf-8")
        head = [f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}",
                "Content-Type: application/json",
                f"Content-Length: {len(data)}",
                "Connection: close"]
        for k, v in (headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
                     + data)
        await writer.drain()

    # ------------------------------------------------------------------
    # routes
    # ------------------------------------------------------------------
    async def _healthz(self, writer) -> None:
        """POOL-AWARE liveness: a disaggregated fleet (fleet/proc.py)
        reports per-pool membership, and the status encodes the
        degradation ladder rather than a binary —

        - ``ok`` (200): every pool has a live replica;
        - ``degraded`` (200): a pool is not serving but the node is
          still making progress — the ``pools`` map says HOW: state
          ``"down"`` means the fallback ladder is engaged (prefill
          down -> the decode pool absorbs prefill work; decode down
          -> admitted work requeues behind the breaker-gated
          restart), state ``"recovering"`` means a restart is in
          flight and that pool's work is HELD for it rather than
          absorbed — 200 either way because a load balancer must NOT
          pull a node that is still making progress;
        - ``unavailable`` (503 + Retry-After): nothing can serve (no
          live replica anywhere, or draining).

        Colocated fleets (and the thread fleet, which reports no
        pools) keep the original any-replica-serving mapping."""
        h = self.fleet.health()
        pools = h.get("pools") or {}
        if len(pools) > 1:
            n_up = sum(1 for p in pools.values()
                       if p.get("state") == "up")
            if h["draining"] or n_up == 0:
                h["status"] = "unavailable"
            elif n_up < len(pools):
                h["status"] = "degraded"
            else:
                h["status"] = "ok"
        else:
            serving = any(r["state"] == HEALTHY
                          for r in h["replicas"].values())
            h["status"] = ("ok" if serving and not h["draining"]
                           else "unavailable")
        # SLO status (obs/slo.py, fleets with the engine armed): the
        # body always names the breaching objectives and their burns,
        # and a breach downgrades "ok" to "degraded" — the node still
        # serves (a load balancer must NOT pull it for a latency
        # contract slip), but the body says exactly which promise is
        # burning budget and which pool to blame
        slo = getattr(self.fleet, "slo", None)
        if slo is not None:
            status = slo.status()
            h["slo"] = {"breaching": status["breaching"],
                        "objectives": status["objectives"]}
            if status["breaching"] and h["status"] == "ok":
                h["status"] = "degraded"
        unavailable = h["status"] == "unavailable"
        await self._respond(
            writer, 503 if unavailable else 200, h,
            headers=({"Retry-After": self._retry_after()}
                     if unavailable else None))

    def _retry_after(self) -> str:
        """Retry-After seconds: at least the configured floor, raised
        to the oldest queued request's wait age when the fleet exposes
        it — a client told to come back sooner than the queue is
        already waiting would only bounce off the same 429."""
        hint = self.retry_after_s
        probe = getattr(self.fleet, "queue_oldest_wait_s", None)
        if callable(probe):
            hint = max(hint, probe())
        return str(int(math.ceil(hint)))

    def _engine_summaries(self) -> Dict:
        """Per-replica engine summaries. For the process fleet this is
        an RPC fan-out over the stats frames, so callers run it in an
        executor — the event loop must keep streaming tokens while a
        slow replica answers (or times out)."""
        getter = getattr(self.fleet, "engine_summaries", None)
        return getter() if getter is not None else {}

    async def _v1_metrics(self, writer) -> None:
        loop = asyncio.get_running_loop()
        engines = await loop.run_in_executor(None,
                                             self._engine_summaries)
        await self._respond(writer, 200,
                            {"frontdoor": self.fleet.metrics.summary(),
                             "engine_summary": engines})

    async def _prometheus(self, writer) -> None:
        """Prometheus text exposition over the existing ledgers
        (obs/prom.py renders; nothing new is counted here)."""
        from quintnet_tpu.obs.prom import render_exposition

        loop = asyncio.get_running_loop()
        engines = await loop.run_in_executor(None,
                                             self._engine_summaries)
        slo = getattr(self.fleet, "slo", None)
        signals = getattr(self.fleet, "signals", None)
        audit = getattr(self.fleet, "lock_audit", None)
        text = render_exposition(
            self.fleet.metrics.summary(), engines,
            health=self.fleet.health(),
            slo=slo.status() if slo is not None else None,
            pressure=signals.gauges() if signals is not None else None,
            locks=audit.summary() if audit is not None else None)
        data = text.encode("utf-8")
        head = ["HTTP/1.1 200 OK",
                "Content-Type: text/plain; version=0.0.4; "
                "charset=utf-8",
                f"Content-Length: {len(data)}",
                "Connection: close"]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
                     + data)
        await writer.drain()

    def _error_response(self, e: BaseException) -> Tuple[int, Dict,
                                                         Dict]:
        """(status, body, headers) for a typed fleet error — THE
        mapping table in the module docstring."""
        if isinstance(e, Overloaded):
            status = 429 if e.reason == "queue_full" else 503
            return status, {"error": "overloaded", "reason": e.reason,
                            "message": str(e)}, \
                {"Retry-After": self._retry_after()}
        if isinstance(e, DeadlineExceeded):
            return 504, {"error": "deadline_exceeded",
                         "generated": e.generated,
                         "message": str(e)}, {}
        if isinstance(e, TimeoutError):
            return 504, {"error": "timeout", "message": str(e)}, {}
        if isinstance(e, (ValueError, KeyError, TypeError)):
            # TypeError included: a wrong-typed JSON field (e.g.
            # "max_new_tokens": null) is the client's error, and a 500
            # would make load balancers blame the server
            return 400, {"error": "bad_request", "message": str(e)}, {}
        return 500, {"error": "internal",
                     "message": f"{type(e).__name__}: {e}"}, {}

    def _submit(self, spec: Dict, on_token=None) -> int:
        """Parse + submit (runs on the event loop thread — fleet.submit
        only takes the fleet lock briefly)."""
        prompt = spec.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            raise ValueError(
                "'prompt' must be a non-empty list of token ids")
        if "max_new_tokens" not in spec:
            raise ValueError("'max_new_tokens' is required")
        key = None
        if spec.get("seed") is not None:
            import jax

            key = jax.random.key(int(spec["seed"]))
        return self.fleet.submit(
            prompt, int(spec["max_new_tokens"]), key=key,
            priority=int(spec.get("priority", 0)),
            deadline_s=spec.get("deadline_s"),
            adapter_id=spec.get("adapter_id"),
            on_token=on_token)

    async def _generate(self, writer, body: bytes) -> None:
        try:
            spec = json.loads(body.decode("utf-8") or "{}")
            if not isinstance(spec, dict):
                raise ValueError("body must be a JSON object")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            await self._respond(writer, 400,
                                {"error": "bad_request",
                                 "message": f"invalid JSON body: {e}"})
            return
        if spec.get("stream"):
            await self._generate_stream(writer, spec)
            return
        loop = asyncio.get_running_loop()
        try:
            fid = self._submit(spec)
        except BaseException as e:  # noqa: BLE001 — typed mapping
            status, payload, headers = self._error_response(e)
            await self._respond(writer, status, payload, headers)
            return
        try:
            out = await loop.run_in_executor(
                None, lambda: self.fleet.result(
                    fid, timeout=self.request_timeout_s))
        except BaseException as e:  # noqa: BLE001
            status, payload, headers = self._error_response(e)
            payload["fid"] = fid
            await self._respond(writer, status, payload, headers)
            return
        await self._respond(writer, 200,
                            {"fid": fid,
                             "output": [int(t) for t in out]})

    async def _generate_stream(self, writer, spec: Dict) -> None:
        """SSE: one event per token as replicas produce them (exactly
        once each, across migrations — the fleet's stream contract),
        a final ``done`` event with the full output, or an ``error``
        event carrying the typed rejection."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def on_token(fid, token, last):
            if loop.is_closed():
                return      # server shut down mid-stream; the fleet
                #             finishes the request, nobody is watching
            try:
                loop.call_soon_threadsafe(q.put_nowait,
                                          ("tok", int(token),
                                           bool(last)))
            except RuntimeError:
                pass        # loop closed between the check and call

        try:
            fid = self._submit(spec, on_token=on_token)
        except BaseException as e:  # noqa: BLE001
            status, payload, headers = self._error_response(e)
            await self._respond(writer, status, payload, headers)
            return

        def watch():
            try:
                out = self.fleet.result(fid,
                                        timeout=self.request_timeout_s)
                item = ("done", [int(t) for t in out], None)
            except BaseException as e:  # noqa: BLE001
                item = ("error", e, None)
            try:
                if not loop.is_closed():
                    loop.call_soon_threadsafe(q.put_nowait, item)
            except RuntimeError:
                pass        # server shut down while we waited

        threading.Thread(target=watch, daemon=True,
                         name=f"frontdoor-watch-{fid}").start()

        writer.write((f"HTTP/1.1 200 OK\r\n"
                      f"Content-Type: text/event-stream\r\n"
                      f"Cache-Control: no-cache\r\n"
                      f"Connection: close\r\n"
                      f"X-Fleet-Fid: {fid}\r\n\r\n").encode("latin-1"))
        await writer.drain()
        while True:
            kind, a, b = await q.get()
            if kind == "tok":
                writer.write(
                    f"data: {json.dumps({'token': a, 'last': b})}"
                    f"\n\n".encode("utf-8"))
                await writer.drain()
                continue
            if kind == "done":
                writer.write(
                    f"event: done\ndata: "
                    f"{json.dumps({'fid': fid, 'output': a})}"
                    f"\n\n".encode("utf-8"))
            else:
                status, payload, _h = self._error_response(a)
                payload["fid"] = fid
                payload["status"] = status
                writer.write(
                    f"event: error\ndata: {json.dumps(payload)}"
                    f"\n\n".encode("utf-8"))
            await writer.drain()
            return

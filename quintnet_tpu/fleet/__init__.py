"""Multi-replica serving: load balancing, load shedding, kill-safe
request migration.

One :class:`~quintnet_tpu.serve.engine.ServeEngine` is a single
continuous-batching process; this package runs N of them on worker
threads behind one submit/stream API and makes the resulting fleet
operable under the two things production traffic guarantees — bursts
and failures:

- :mod:`router`    — least-outstanding-work routing (token-count load
  proxy) or deterministic round_robin, with an adapter-affinity
  pre-filter for LoRA-bound requests (serve/adapters.py);
- :mod:`admission` — bounded fleet-wide queue; overload and expired
  deadlines shed with a typed :class:`Overloaded` instead of queueing
  forever;
- :mod:`health`    — per-replica circuit breaker (consecutive-failure
  trip, timed half-open probe) gating restarts of dead replicas;
- :mod:`replica`   — the ServeEngine worker thread: inbox, chaos
  polling (``ft.ChaosMonkey`` mode='raise'), and the death export of
  every unfinished request's host-side progress;
- :mod:`fleet`     — :class:`ServeFleet`: submit/result/generate,
  dispatcher, **exact migration** (a killed replica's in-flight
  requests resume on healthy replicas token-identically, via the same
  prompt+generated+key resume contract the engine's preemption path
  already guarantees), graceful drain, fleet metrics + per-replica
  compile-count enforcement.

tools/fleet_bench.py replays a trace against the fleet per routing
policy — with a mid-trace replica kill and an over-capacity burst —
and emits one JSON record per policy (artifacts/fleet_r08.json).
"""

from quintnet_tpu.fleet.admission import AdmissionQueue, Overloaded
from quintnet_tpu.fleet.fleet import FleetMetrics, FleetRequest, ServeFleet
from quintnet_tpu.fleet.health import (CLOSED, DEAD, HALF_OPEN, HEALTHY,
                                       OPEN, STOPPED, CircuitBreaker)
from quintnet_tpu.fleet.replica import Replica
from quintnet_tpu.fleet.router import POLICIES, Router

__all__ = [
    "AdmissionQueue",
    "CircuitBreaker",
    "FleetMetrics",
    "FleetRequest",
    "Overloaded",
    "POLICIES",
    "Replica",
    "Router",
    "ServeFleet",
    "HEALTHY",
    "DEAD",
    "STOPPED",
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
]

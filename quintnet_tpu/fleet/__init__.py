"""Multi-replica serving: load balancing, load shedding, kill-safe
request migration.

One :class:`~quintnet_tpu.serve.engine.ServeEngine` is a single
continuous-batching process; this package runs N of them on worker
threads behind one submit/stream API and makes the resulting fleet
operable under the two things production traffic guarantees — bursts
and failures:

- :mod:`router`    — least-outstanding-work routing (token-count load
  proxy) or deterministic round_robin, with an adapter-affinity
  pre-filter for LoRA-bound requests (serve/adapters.py);
- :mod:`admission` — bounded fleet-wide queue; overload and expired
  deadlines shed with a typed :class:`Overloaded` instead of queueing
  forever;
- :mod:`health`    — per-replica circuit breaker (consecutive-failure
  trip, timed half-open probe) gating restarts of dead replicas;
- :mod:`replica`   — the ServeEngine worker thread: inbox, chaos
  polling (``ft.ChaosMonkey`` mode='raise'), and the death export of
  every unfinished request's host-side progress;
- :mod:`fleet`     — :class:`ServeFleet`: submit/result/generate,
  dispatcher, **exact migration** (a killed replica's in-flight
  requests resume on healthy replicas token-identically, via the same
  prompt+generated+key resume contract the engine's preemption path
  already guarantees), graceful drain, fleet metrics + per-replica
  compile-count enforcement.

Scaling past one address space (fleet/proc.py + fleet/frontdoor.py +
fleet/wire.py): :class:`ProcessFleet` runs each replica engine in its
OWN OS process behind the same submit/stream API — a length-prefixed
JSON wire protocol, heartbeat-supervised children restarted with
jittered backoff, and CRASH-SAFE migration from the dispatcher's
write-ahead token journal (a SIGKILL'd replica's in-flight requests
resume elsewhere token-identically with zero cooperation from the
corpse). :class:`FrontDoor` is the asyncio HTTP/SSE server in front of
either fleet, mapping the typed ``Overloaded`` shedding onto
429/503 + Retry-After.

Disaggregated serving (``ProcessFleet(pools={"prefill": P,
"decode": D})``, fleet/proc.py): the two regimes run on dedicated
replica pools — prefill replicas commit a request's first token and
ship its KV chain to a decode replica over a checksummed wire frame
(fleet/wire.py), retried under the shared
:class:`~quintnet_tpu.fleet.retry.RetryPolicy` with local re-prefill
as the always-correct fallback, and pool loss walks an explicit
degradation ladder surfaced at /healthz.

tools/fleet_bench.py replays a trace against the fleet per routing
policy — with a mid-trace replica kill and an over-capacity burst —
and emits one JSON record per policy (threads:
artifacts/fleet_r08.json; ``--process``: artifacts/fleet_r12.json;
``--disagg``: the TTFT-vs-ITL interference A/B of
artifacts/fleet_r16.json).
"""

from quintnet_tpu.fleet.admission import AdmissionQueue, Overloaded
from quintnet_tpu.fleet.fleet import FleetMetrics, FleetRequest, ServeFleet
from quintnet_tpu.fleet.frontdoor import FrontDoor
from quintnet_tpu.fleet.health import (CLOSED, DEAD, HALF_OPEN, HEALTHY,
                                       OPEN, STALLED, STARTING, STOPPED,
                                       Backoff, CircuitBreaker,
                                       HeartbeatMonitor)
from quintnet_tpu.fleet.proc import (POOLS, ProcessFleet, ProcReplica,
                                     replica_main)
from quintnet_tpu.fleet.replica import Replica
from quintnet_tpu.fleet.retry import RetryPolicy
from quintnet_tpu.fleet.router import ANY_POOL, POLICIES, Router, eligible

__all__ = [
    "AdmissionQueue",
    "Backoff",
    "CircuitBreaker",
    "FleetMetrics",
    "FleetRequest",
    "FrontDoor",
    "HeartbeatMonitor",
    "Overloaded",
    "ANY_POOL",
    "POLICIES",
    "POOLS",
    "ProcReplica",
    "ProcessFleet",
    "Replica",
    "RetryPolicy",
    "Router",
    "ServeFleet",
    "eligible",
    "replica_main",
    "HEALTHY",
    "DEAD",
    "STOPPED",
    "STARTING",
    "STALLED",
    "CLOSED",
    "OPEN",
    "HALF_OPEN",
]

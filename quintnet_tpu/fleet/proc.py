"""Process-isolated fleet: each ServeEngine replica is its own OS
process, and the dispatcher survives any of them dying at any
instruction.

The thread fleet (fleet/fleet.py) proves the MIGRATION math — exact
resume from ``prompt + committed tokens + evolved PRNG key`` — but all
its replicas share one address space: a real SIGKILL, OOM kill, or
wedged runtime takes out the dispatcher with them, which is precisely
the failure a production serving tier must absorb (Llumnix-style live
migration between instances; the tools/ft_run.py supervisor story
applied to serving). This module promotes replicas to crash domains:

- **process replicas** — :func:`replica_main` runs one engine per
  spawned process, speaking a small length-prefixed JSON protocol
  (fleet/wire.py) over a localhost TCP socket: submit, token stream,
  pause/resume, export, stats, warmup, arm-chaos, stop, heartbeat.
  JSON + sockets, not pickles + shared memory: a replica can corrupt
  only itself.
- **write-ahead token journal** — the dispatcher records every
  streamed token in :attr:`FleetRequest.committed` BEFORE the client
  callback sees it. Because the engine's key discipline advances the
  PRNG chain exactly one split per committed token
  (serve/engine.py), ``prompt + journal + n-split(submit key, n)`` IS
  the dead replica's :class:`RequestProgress` — migration needs no
  cooperation from the corpse. Tokens the victim committed but never
  flushed are simply regenerated (same key chain ⇒ same tokens), so
  the client stream stays token-identical with ``is_last`` delivered
  exactly once.
- **supervision** — heartbeats from a dedicated child thread (they
  keep beating through long XLA compiles); a replica whose beat age
  exceeds ``heartbeat_budget_s`` is declared STALLED (distinct from
  death: its socket is still open), routed around, its work migrated,
  and the zombie SIGKILLed. Restarts are gated by the same
  :class:`CircuitBreaker` the thread fleet uses, spaced by jittered
  exponential :class:`~quintnet_tpu.fleet.health.Backoff` so a
  poisoned fleet does not crash-loop in lockstep.

Degradation order under trouble is explicit and monotone: shed new
work (typed ``Overloaded`` at the bounded queue) → pause admissions →
drain → migrate. The HTTP front door (fleet/frontdoor.py) maps the
first rung onto 429/503 + Retry-After.

Engine factories cross the process boundary as a picklable SPEC —
``{"file": "/abs/builder.py", "func": "build_engine", "kwargs":
{...}}`` (or ``"module": "pkg.mod"``) — never as closures: the spawn
child imports the builder and constructs its own engine, which is also
what guarantees every replica is built from the same (family, params)
the migration contract requires.
"""

from __future__ import annotations

import os
import socket
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from quintnet_tpu.fleet import wire
from quintnet_tpu.fleet.admission import AdmissionQueue, Overloaded
from quintnet_tpu.fleet.fleet import FleetMetrics, FleetRequest
from quintnet_tpu.fleet.health import (CLOSED, DEAD, HEALTHY, STALLED,
                                       STARTING, STOPPED, Backoff,
                                       CircuitBreaker, HeartbeatMonitor)
from quintnet_tpu.fleet.retry import RetryPolicy
from quintnet_tpu.fleet.router import ANY_POOL, Router
from quintnet_tpu.fleet.router import eligible as router_eligible

# the two serving regimes a disaggregated fleet splits apart
# (DistServe/Splitwise): prefill is compute-bound and bursty, decode
# memory-bound and steady — see PAPERS.md and docs/serving.md
POOLS = ("prefill", "decode")


# ---------------------------------------------------------------------------
# the child: one engine, one process
# ---------------------------------------------------------------------------


def _load_builder(spec: Dict) -> Callable:
    """Resolve an engine-builder spec in THIS process. ``file`` loads a
    module by path (tests and tools need no installable package);
    ``module`` imports by dotted name."""
    func = spec["func"]
    if "file" in spec:
        import importlib.util

        s = importlib.util.spec_from_file_location(
            "_qt_engine_builder", spec["file"])
        mod = importlib.util.module_from_spec(s)
        s.loader.exec_module(mod)
    elif "module" in spec:
        import importlib

        mod = importlib.import_module(spec["module"])
    else:
        raise ValueError(
            f"engine spec needs 'file' or 'module', got {sorted(spec)}")
    return getattr(mod, func)


def replica_main(name: str, host: str, port: int, token: str,
                 engine_spec: Dict, *, heartbeat_s: float = 0.1,
                 chaos_spec: Optional[Dict] = None,
                 platform: Optional[str] = None,
                 poll_s: float = 0.005, obs: bool = False,
                 ring_capacity: int = 512) -> None:
    """Entry point of a replica process (multiprocessing 'spawn'
    target). Builds the engine from ``engine_spec``, connects back to
    the dispatcher at ``(host, port)``, identifies itself with
    ``token`` in its hello (so concurrent restarts cannot cross-wire),
    then serves frames until told to stop — or until chaos/a real
    fault kills it, which is the point of being a process."""
    import queue as _queue

    import jax

    if platform:
        jax.config.update("jax_platforms", platform)

    from quintnet_tpu.ft.chaos import CHAOS_KILL_EXIT_CODE, ChaosMonkey

    engine = _load_builder(engine_spec)(**engine_spec.get("kwargs", {}))
    if obs:
        # flight recorder + tracer attached AFTER the builder ran (the
        # spec is user code that predates obs); both are inert — the
        # ring's fresh records piggyback on heartbeat frames so the
        # dispatcher's mirror is this replica's black box when a
        # SIGKILL leaves no one to ask (quintnet_tpu/obs/)
        from quintnet_tpu.obs import StepRecorder, Tracer

        if engine.recorder is None:
            engine.recorder = StepRecorder(capacity=ring_capacity,
                                           clock=engine.clock)
        if engine.tracer is None:
            engine.tracer = Tracer(clock=engine.clock)
    chaos = ChaosMonkey(**chaos_spec) if chaos_spec else None

    sock = socket.create_connection((host, port), timeout=30.0)
    sock.settimeout(None)
    send_lock = threading.Lock()
    stop_ev = threading.Event()

    def send(frame: Dict) -> None:
        with send_lock:
            wire.send_frame(sock, frame)

    send({"t": "hello", "name": name, "token": token,
          "pid": os.getpid(), "limits": engine.limits(),
          "v": wire.WIRE_VERSION})

    cmds: "_queue.Queue" = _queue.Queue()

    def reader() -> None:
        try:
            while True:
                cmds.put(wire.recv_frame(sock, peer="dispatcher"))
        except (wire.ConnectionClosed, wire.WireError, OSError):
            cmds.put(None)      # dispatcher went away -> shut down

    def heartbeat() -> None:
        # a dedicated thread so heartbeats keep flowing through long
        # engine.step() calls (first-touch XLA compiles take seconds);
        # only a genuine wedge — or the stall injector — silences them.
        # Fresh flight-recorder records ride along: the dispatcher's
        # ring mirror stays as current as the last beat, which is what
        # "last-known" means when this process is later SIGKILLed.
        while not stop_ev.wait(heartbeat_s):
            if chaos is not None and chaos.stalled:
                continue
            frame = {"t": "hb", "steps": steps[0]}
            if engine.recorder is not None:
                recs = engine.recorder.drain_new()
                if recs:
                    frame["rec"] = recs
            try:
                send(frame)
            except OSError:
                return

    steps = [0]
    rid2fid: Dict[int, int] = {}
    paused = False
    threading.Thread(target=reader, daemon=True,
                     name=f"{name}-reader").start()
    threading.Thread(target=heartbeat, daemon=True,
                     name=f"{name}-hb").start()

    def deliver(rid: int, tok: int, last: bool) -> None:
        send({"t": "tok", "fid": rid2fid[rid], "tok": int(tok),
              "last": bool(last)})

    def handle(cmd: Dict) -> bool:
        nonlocal paused, chaos
        t = cmd["t"]
        if t == "submit":
            fid = cmd["fid"]
            try:
                prog = wire.progress_from_wire(cmd["progress"])
                rid = engine.restore_progress(
                    prog, on_token=deliver,
                    prefill_only=bool(cmd.get("prefill_only", False)))
                # registered BEFORE any token can flow: restore only
                # queues — tokens appear at the next step()
                rid2fid[rid] = fid
            except (ValueError, KeyError, wire.WireError) as e:
                send({"t": "reject", "fid": fid,
                      "error": wire.error_to_wire(e)})
        elif t == "kv_export":
            # the disaggregated handoff, sending side: ship the
            # published chain for a prefix as a checksummed KV frame.
            # Chaos hooks HERE model the transfer's failure modes:
            # 'kill' = the exporter vanishes mid-transfer, 'corrupt' =
            # the frame is damaged AFTER its checksum (the importer
            # must catch it), 'stall' = the reply outwaits the
            # dispatcher's handoff timeout. 'corrupt' fires separately
            # below, only once a frame actually exists to damage —
            # a declined transfer must not consume the arming.
            fault = (chaos.fire_handoff(kinds=("kill", "stall"))
                     if chaos is not None else None)
            if fault == "kill":
                os._exit(CHAOS_KILL_EXIT_CODE)
            tokens = np.asarray(cmd.get("tokens", []), np.int32)
            chain = engine.export_kv_chain(
                tokens, namespace=cmd.get("namespace"),
                trace_id=cmd.get("trace_id"))
            kv, reason = None, None
            if chain is None:
                reason = ("prefill replica no longer holds the chain "
                          "(evicted before the transfer, or the "
                          "prefix cache is off)")
            else:
                kv = wire.kv_chain_to_wire(chain,
                                           namespace=cmd.get("namespace"))
                if not wire.kv_chain_fits(kv):
                    # shipping it would trip the receiver's frame
                    # guard and read as a DEAD connection — decline
                    # instead, so the dispatcher takes the documented
                    # local-re-prefill fallback on a healthy fleet
                    reason = (f"chain frame (~{wire.kv_chain_wire_size(kv)}"
                              f" bytes) exceeds MAX_FRAME_BYTES "
                              f"({wire.MAX_FRAME_BYTES}) — decode "
                              f"replica re-prefills locally")
                    kv = None
                elif (chaos is not None
                      and chaos.fire_handoff(kinds=("corrupt",))):
                    b64 = kv["blocks"][0]["k"]["b64"]
                    kv["blocks"][0]["k"]["b64"] = (
                        ("A" if b64[:1] != "A" else "B") + b64[1:])
            if fault == "stall":
                time.sleep(chaos.handoff_stall_s)
            send({"t": "kv", "id": cmd["id"], "kv": kv,
                  "reason": reason})
        elif t == "kv_peek":
            # tier peer lookup, probe side: how many token positions
            # this replica could serve warm (device chain + host-tier
            # extension) for a prefix. Read-only and cheap — no data
            # moves, nothing pins — so the dispatcher can fan it out
            # to every replica before choosing whom to kv_export from.
            tokens = np.asarray(cmd.get("tokens", []), np.int32)
            send({"t": "kv_n", "id": cmd["id"],
                  "n_tokens": int(engine.peek_kv_chain(
                      tokens, namespace=cmd.get("namespace")))})
        elif t == "kv_import":
            # receiving side: verify the checksum, admit the chain as
            # a warm prefix hit. A corrupt/mismatched frame is a TYPED
            # error reply — the dispatcher retries or falls back to
            # local re-prefill; this replica never caches wrong KV.
            # Only kill/stall are injectable here ('corrupt' is an
            # export-side fault: this handler never builds a frame, so
            # firing it would consume the arming without injecting).
            fault = (chaos.fire_handoff(kinds=("kill", "stall"))
                     if chaos is not None else None)
            if fault == "kill":
                os._exit(CHAOS_KILL_EXIT_CODE)
            if fault == "stall":
                # the receiving socket goes quiet past the handoff
                # timeout (heartbeats keep flowing from their own
                # thread — this is a TRANSFER stall, not a replica
                # stall, and must be handled by the retry policy, not
                # the stall detector)
                time.sleep(chaos.handoff_stall_s)
            try:
                chain, ns = wire.kv_chain_from_wire(cmd["kv"])
                n = engine.import_kv_chain(
                    chain, namespace=ns, trace_id=cmd.get("trace_id"))
                send({"t": "kv_ok", "id": cmd["id"],
                      "imported": int(n)})
            except (ValueError, KeyError, wire.WireError) as e:
                send({"t": "kv_ok", "id": cmd["id"], "imported": 0,
                      "error": wire.error_to_wire(e)})
        elif t == "pause":
            paused = True
        elif t == "resume":
            paused = False
        elif t == "export":
            send({"t": "export", "id": cmd["id"],
                  "progress": [wire.progress_to_wire(p)
                               for p in engine.export_progress()]})
        elif t == "stats":
            send({"t": "stats", "id": cmd["id"], "steps": steps[0],
                  "compile": engine.compile_counts(),
                  "metrics": engine.metrics.summary(),
                  "admitted": engine.metrics.admitted})
        elif t == "trace":
            # the replica's span log (obs/trace.py), optionally
            # restricted to specific trace ids — how the dispatcher
            # shows a migrated request's spans CONTINUING on the
            # destination replica under the same id
            ids = cmd.get("trace_ids")
            send({"t": "trace", "id": cmd["id"],
                  "traces": (engine.tracer.snapshot(ids)
                             if engine.tracer is not None else {}),
                  "ring": (engine.recorder.snapshot()
                           if engine.recorder is not None else [])})
        elif t == "warmup":
            engine.warmup()
            send({"t": "ack", "id": cmd["id"]})
        elif t == "reset":
            engine.metrics = type(engine.metrics)(clock=engine.clock)
            steps[0] = 0
            send({"t": "ack", "id": cmd["id"]})
        elif t == "arm_chaos":
            chaos = ChaosMonkey(**cmd["spec"])
            send({"t": "ack", "id": cmd["id"]})
        elif t == "stop":
            return False
        return True

    try:
        running = True
        while running:
            # block on the inbox only when idle — a busy engine steps
            # back-to-back and just peeks for commands between steps
            idle = (paused or not engine.has_work
                    or (chaos is not None and chaos.stalled))
            try:
                cmd = (cmds.get(timeout=poll_s) if idle
                       else cmds.get_nowait())
            except _queue.Empty:
                cmd = False
            if cmd is None:
                return              # dispatcher hung up
            if cmd is not False:
                running = handle(cmd)
                continue            # drain all pending commands first
            if chaos is not None and chaos.stalled:
                continue            # wedged: alive, silent, useless
            if paused or not engine.has_work:
                continue
            finished = engine.step()
            steps[0] += 1
            for rid in finished:
                fid = rid2fid.pop(rid)
                req = engine.request(rid)
                if req.error is not None:
                    send({"t": "failed", "fid": fid,
                          "error": wire.error_to_wire(req.error)})
                elif req.handed_off:
                    # prefill-phase retirement (disaggregated fleet):
                    # the first token streamed with its real last
                    # flag, the blocks are published — tell the
                    # dispatcher this is a HANDOFF, not a completion
                    send({"t": "fin", "fid": fid, "handoff": True})
                else:
                    send({"t": "fin", "fid": fid})
            if chaos is not None:
                chaos.on_step_end(steps[0])
        send({"t": "bye"})
    except Exception as e:  # noqa: BLE001 — cooperative death export
        # a mode='raise' chaos kill or a real engine fault: export
        # best-effort (the dispatcher's journal makes this OPTIONAL —
        # it reconstructs the same payloads if this frame never lands)
        try:
            send({"t": "death", "error": wire.error_to_wire(e),
                  "progress": [wire.progress_to_wire(p)
                               for p in engine.export_progress()]})
        except Exception:   # noqa: BLE001
            pass
        os._exit(1)
    finally:
        stop_ev.set()
        try:
            sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# the parent: one socket + one supervisor record per replica
# ---------------------------------------------------------------------------


class ProcReplica:
    """Dispatcher-side handle for one replica process: the spawn
    record, the socket (once the hello lands), the reader thread, and
    the routing counters the fleet lock owns. Exposes the same
    candidate surface the thread :class:`Replica` does
    (``state``/``paused``/``in_flight``/``max_dispatch``/
    ``outstanding_tokens``/``adapter_resident``) so
    :func:`router.eligible` and the :class:`Router` policies apply
    unchanged."""

    def __init__(self, name: str, fleet: "ProcessFleet",
                 chaos_spec: Optional[Dict], *,
                 pool: str = ANY_POOL):
        self.name = name
        self.fleet = fleet
        self.chaos_spec = chaos_spec
        self.token = uuid.uuid4().hex
        # which serving pool this replica belongs to: "prefill" /
        # "decode" for a disaggregated fleet, "any" (serves every
        # phase) for colocated ones — router.eligible filters on it
        self.pool = pool
        self.state = STARTING
        self.paused = False
        self.in_flight = 0
        self.outstanding_tokens = 0
        self.max_dispatch = fleet._max_dispatch or 0  # sized at hello
        self.steps = 0
        self.pid: Optional[int] = None
        self.limits: Optional[Dict] = None
        self.sock: Optional[socket.socket] = None
        self.hb = HeartbeatMonitor(fleet.heartbeat_budget_s,
                                   clock=fleet.clock)
        self.spawned_at = fleet.clock()
        self.restart_at: Optional[float] = None   # set on death/stall
        self.migrated = False     # this incarnation's work already moved
        self.error: Optional[BaseException] = None
        # the dispatcher-side flight-recorder MIRROR: step records the
        # child piggybacked on its heartbeats (obs/recorder.py). When
        # the child is SIGKILLed this is its last-known ring — the
        # crash dump's black box, no cooperation from the corpse.
        # Its own lock, NOT the fleet lock: the reader thread appends
        # on every heartbeat while the dispatcher snapshots at death —
        # iterating a deque another thread is appending to raises
        # RuntimeError, so both sides go through the lock below.
        from collections import deque

        self.ring = deque(maxlen=fleet._ring_capacity)
        # audited fleets fold both replica locks into the fleet-wide
        # order graph (same-name re-mint across restarts returns the
        # SAME lock, so a respawned incarnation keeps its node)
        self._ring_lock = (
            fleet.lock_audit.lock(f"proc.{name}._ring_lock")
            if fleet.lock_audit is not None else threading.Lock())
        self._fid2freq: Dict[int, FleetRequest] = {}
        # adapters this incarnation has been sent (affinity heuristic:
        # the child's registry loaded them on first use; its own LRU
        # may have evicted — affinity is a preference, never a promise)
        self._adapters_seen: set = set()
        self._send_lock = (
            fleet.lock_audit.lock(f"proc.{name}._send_lock")
            if fleet.lock_audit is not None else threading.Lock())
        self._pending: Dict[int, tuple] = {}
        self._rpc_counter = 0

        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self.proc = ctx.Process(
            target=replica_main,
            args=(name, *fleet._address, self.token, fleet.engine_spec),
            kwargs={"heartbeat_s": fleet.heartbeat_s,
                    "chaos_spec": chaos_spec,
                    "platform": fleet.platform,
                    "obs": fleet._obs,
                    "ring_capacity": fleet._ring_capacity},
            name=f"fleet-{name}", daemon=True)
        self.proc.start()

    # ---- wire ---------------------------------------------------------
    def send(self, frame: Dict) -> None:
        if self.sock is None:
            raise OSError(f"replica {self.name} has no connection")
        with self._send_lock:
            wire.send_frame(self.sock, frame)

    def rpc(self, frame: Dict, *, timeout: float = 60.0) -> Dict:
        """Request/response over the frame stream (stats, export,
        warmup, reset, arm_chaos). The reader thread completes it; a
        connection loss aborts every outstanding RPC immediately
        instead of letting callers sit out their full timeout against
        a corpse."""
        if self.sock is None:
            raise OSError(f"replica {self.name} has no connection "
                          f"(state={self.state})")
        ev = threading.Event()
        slot: Dict = {}
        with self._send_lock:
            self._rpc_counter += 1
            rid = self._rpc_counter
            self._pending[rid] = (ev, slot)
            frame = dict(frame, id=rid)
            wire.send_frame(self.sock, frame)
        if not ev.wait(timeout):
            with self._send_lock:
                self._pending.pop(rid, None)
            raise TimeoutError(
                f"replica {self.name}: no reply to {frame['t']!r} "
                f"within {timeout}s (state={self.state})")
        if "frame" not in slot:
            raise OSError(
                f"replica {self.name}: connection lost before the "
                f"{frame['t']!r} reply")
        return slot["frame"]

    def _abort_pending(self) -> None:
        """Wake every in-flight RPC with no reply (connection gone)."""
        with self._send_lock:
            pending, self._pending = self._pending, {}
        for ev, _slot in pending.values():
            ev.set()

    def ring_extend(self, recs) -> None:
        with self._ring_lock:
            self.ring.extend(recs)

    def ring_snapshot(self) -> List[Dict]:
        with self._ring_lock:
            return list(self.ring)

    def adapter_resident(self, adapter_id: str) -> bool:
        return adapter_id in self._adapters_seen

    def unfinished(self) -> List[FleetRequest]:
        return list(self._fid2freq.values())

    def kill(self) -> None:
        """SIGKILL the child — no cleanup, no cooperation; the journal
        migration path owes it nothing. Goes through the Process
        handle, NOT the hello-reported pid: a replica hung while still
        STARTING (engine build wedged, hello never sent) has no pid
        yet but must be killable all the same."""
        try:
            if self.proc.is_alive():
                self.proc.kill()
        except (OSError, ProcessLookupError, ValueError):
            pass

    # ---- reader -------------------------------------------------------
    def attach(self, sock: socket.socket, hello: Dict) -> None:
        """Complete the handshake (fleet lock held by the caller)."""
        import struct as _struct

        # sends time out at the SOCKET level (SO_SNDTIMEO hits send()
        # only — the reader thread's blocking recv is untouched): a
        # replica so wedged it stops draining its socket must fail the
        # dispatcher's send with OSError (-> death + migration), never
        # block it inside the fleet lock, where a stuck sendall would
        # freeze dispatch, stall detection and result delivery alike
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                        _struct.pack("ll", 10, 0))
        self.sock = sock
        self.pid = hello.get("pid")
        self.limits = hello.get("limits")
        if not self.max_dispatch:
            self.max_dispatch = 2 * int(self.limits["max_slots"])
        self.hb.beat()
        self.state = HEALTHY
        threading.Thread(target=self._read_loop, daemon=True,
                         name=f"fleet-{self.name}-reader").start()

    def _read_loop(self) -> None:
        # WireError (corrupt length prefix, flipped-bit JSON, a frame
        # truncated mid-body) is caught EXACTLY like ConnectionClosed/
        # OSError below: a replica whose stream desynchronized is a
        # dead replica — its work migrates off the journal — never a
        # dispatcher crash (a replica can corrupt only itself)
        try:
            while True:
                frame = wire.recv_frame(self.sock, peer=self.name)
                rid = frame.get("id")
                if rid is not None:
                    # the pop shares _send_lock with rpc() registration
                    # and _abort_pending's swap: a timeout-side pop and
                    # this reply-side pop racing the swap must agree on
                    # ONE dict (qtcheck-threads QT202 caught the bare
                    # read here)
                    with self._send_lock:
                        pend = self._pending.pop(rid, None)
                    if pend is not None:
                        pend[1]["frame"] = frame
                        pend[0].set()
                    continue
                self.fleet._on_frame(self, frame)
        except (wire.ConnectionClosed, wire.WireError, OSError):
            pass
        # EOF only after every buffered frame was processed — the
        # journal is as complete as the kernel's view of the stream
        self._abort_pending()
        self.fleet._on_conn_lost(self)


class ProcessFleet:
    """N replica PROCESSES behind one submit/stream API — the
    :class:`~quintnet_tpu.fleet.fleet.ServeFleet` surface with real
    crash domains. See the module docstring for the design; the
    operational deltas vs the thread fleet:

    - replicas are spawned from ``engine_spec`` (picklable builder
      spec), handshake over localhost TCP, and are dispatch candidates
      only after their hello (state STARTING until then);
    - migration is journal-driven: a SIGKILL'd or stalled replica's
      in-flight requests are reconstructed from the dispatcher's
      write-ahead token journal and resumed elsewhere,
      token-identically, without any cooperation from the victim;
    - a stalled replica (heartbeat age > ``heartbeat_budget_s``) is
      routed around within the budget, its work migrated, the zombie
      SIGKILLed — the breaker records it exactly like a death, but
      ``metrics.stalls`` counts it separately;
    - restarts are breaker-gated AND backoff-spaced (jittered
      exponential, :class:`~quintnet_tpu.fleet.health.Backoff`);
    - dispatch-side connection failure = death: the send's requests
      (and everything in flight there) re-queue at the front and the
      next healthy replica takes them — the retry-with-backoff story
      for replica connection failures;
    - ``pools={"prefill": P, "decode": D}`` DISAGGREGATES the fleet
      (DistServe/Splitwise): prefill replicas run a prompt's prefill
      and commit the first token (``prefill_only`` dispatch), the KV
      chain ships to a decode replica as a checksummed wire frame
      (``fleet/wire.kv_chain_to_wire``), and the decode replica
      admits it as a warm prefix hit — the continuation is the
      PROVEN journal-resume path, so disaggregated output is
      token-identical to colocated. The handoff retries under a
      jittered :class:`~quintnet_tpu.fleet.retry.RetryPolicy` and
      falls back to local re-prefill on exhaustion; pool loss
      degrades along an explicit ladder (prefill down -> decode
      absorbs prefill work; decode down -> requeue behind the
      breaker-gated restart, then shed typed
      ``Overloaded('pool_down')`` once every breaker is tripped).
    """

    def __init__(self, engine_spec: Dict, *, n_replicas: int = 2,
                 pools: Optional[Dict[str, int]] = None,
                 policy: str = "least_work", max_pending: int = 64,
                 max_dispatch: Optional[int] = None,
                 trip_after: int = 3, breaker_reset_s: float = 30.0,
                 heartbeat_s: float = 0.1,
                 heartbeat_budget_s: Optional[float] = None,
                 backoff: Optional[Backoff] = None,
                 handoff_retry: Optional[RetryPolicy] = None,
                 handoff_timeout_s: float = 60.0,
                 tier_peer_lookup: Optional[bool] = None,
                 chaos: Optional[Sequence[Dict]] = None,
                 platform: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 name_prefix: str = "p", poll_s: float = 0.02,
                 spawn_timeout_s: float = 300.0,
                 obs: bool = False, crash_dir: Optional[str] = None,
                 ring_capacity: int = 512,
                 slo=None, planner: Optional[Dict] = None,
                 lock_audit: bool = False):
        # disaggregated prefill/decode pools (DistServe/Splitwise):
        # ``pools={"prefill": P, "decode": D}`` splits the replicas
        # onto dedicated pools — prefill replicas run a prompt's
        # prefill, commit the first token, then ship the KV chain to a
        # decode replica over a checksummed wire frame; pools=None is
        # the colocated fleet, byte-identical to the pre-pool surface
        if pools is not None:
            if set(pools) != set(POOLS):
                unknown = sorted(set(pools) - set(POOLS))
                missing = sorted(set(POOLS) - set(pools))
                detail = "; ".join(
                    [f"unknown: {unknown}"] * bool(unknown)
                    + [f"missing: {missing}"] * bool(missing))
                raise ValueError(
                    f"pools must name exactly {POOLS}, got "
                    f"{sorted(pools)} ({detail})")
            if any(int(n) < 1 for n in pools.values()):
                raise ValueError(
                    f"each pool needs >= 1 replica, got {pools} — a "
                    f"pool born empty has no degradation ladder to "
                    f"climb, it just never serves")
            n_replicas = sum(int(n) for n in pools.values())
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self._disagg = pools is not None
        self._pools_spec = None if pools is None else {
            k: int(v) for k, v in pools.items()}
        # KV-handoff fault tolerance: bounded jittered-exponential
        # retries on the transfer, then fall back to local re-prefill
        # on the decode replica (correct because the chain is cache)
        self._handoff_retry = handoff_retry or RetryPolicy(
            base_s=0.05, cap_s=1.0, jitter=0.25, max_attempts=3)
        self._handoff_timeout_s = float(handoff_timeout_s)
        # tiered-KV peer lookup (serve/kv_tier.py): before a fresh
        # dispatch, probe every replica's combined device+host chain
        # for the prompt (kv_peek) and ship the best peer's chain into
        # the target (kv_export -> kv_import) when it beats the
        # target's own by >= 1 block — a host-hit on ANY replica beats
        # a re-prefill. None = auto: on when the engines report a host
        # tier in their limits AND the fleet has >= 2 replicas.
        self._tier_peer_lookup = tier_peer_lookup
        self._pool_down_seen: Dict[str, bool] = {}
        self.engine_spec = dict(engine_spec)
        self.platform = platform
        self.clock = clock
        # observability (quintnet_tpu/obs/): ``obs=True`` arms a
        # PARENT-side tracer (queue/dispatch/migration spans — child
        # engines keep their own, fetched via the ``trace`` RPC or
        # merged into crash dumps), the typed EventLog, and the
        # heartbeat-mirrored per-replica flight-recorder ring that
        # makes a SIGKILL'd child's last-known steps dumpable with
        # zero cooperation from the corpse.
        # the SLO engine + pool-pressure signal plane (obs/slo.py,
        # obs/signals.py) need the heartbeat-mirrored rings and the
        # typed event log, so ``slo=`` implies ``obs=True``
        self._obs = bool(obs) or slo is not None
        self.crash_dir = crash_dir
        self._ring_capacity = int(ring_capacity)
        # lock-discipline runtime (analysis/lockrt.py): lock_audit=True
        # swaps every parent-side lock — the fleet Condition, each
        # replica's ring + send locks, the obs primitives' mutexes —
        # for InstrumentedLocks sharing ONE order graph, so an
        # inversion raises a typed LockOrderError instead of
        # deadlocking and /metrics grows quintnet_lock_*. Off (the
        # default) the stock primitives are constructed verbatim.
        self.lock_audit = None
        if lock_audit:
            from quintnet_tpu.analysis.lockrt import LockAudit

            self.lock_audit = LockAudit(
                clock=clock,
                on_violation=lambda info: self._emit(
                    "lock_order_violation", **info))
        self.tracer = None
        self.events = None
        self.slo = None            # obs.SLOEngine once armed
        self.signals = None        # obs.SignalBus once armed
        self.planner = None        # obs.PoolRebalancePlanner (disagg)
        self._planner_kwargs = dict(planner or {})
        self._signal_next_t = 0.0
        if self._obs:
            from quintnet_tpu.obs import EventLog, Tracer

            self.tracer = Tracer(clock=clock,
                                 lock=self._audit_lock("obs.tracer"))
            self.events = EventLog(clock=clock,
                                   lock=self._audit_lock("obs.events"))
        self.crash_dumps: List[str] = []
        self.last_crash: Optional[Dict] = None
        self._pending_dumps: List[Dict] = []  # snapshotted under the
        #   lock at death; WRITTEN by the dispatcher outside it — a
        #   disk write must never stall token delivery
        self._breaker_seen: Dict[str, str] = {}
        self.heartbeat_s = float(heartbeat_s)
        # default budget: generous vs the beat period (the beat thread
        # is immune to compiles, so 10 periods of silence means wedged,
        # not busy), floored for scheduler-noise robustness
        self.heartbeat_budget_s = float(
            heartbeat_budget_s if heartbeat_budget_s is not None
            else max(10 * heartbeat_s, 1.0))
        self.backoff = backoff or Backoff()
        self.metrics = FleetMetrics()
        self._router = Router(policy)
        # threading.Condition()'s default lock IS an RLock — the
        # audited swap must preserve reentrancy (audit.condition)
        self._cv = (self.lock_audit.condition("fleet._cv")
                    if self.lock_audit is not None
                    else threading.Condition())
        self._queue = AdmissionQueue(max_pending, clock=clock)
        self.metrics._queue_probe = self._queue_gauges
        if slo is not None:
            self.arm_slo(slo, **self._planner_kwargs)
        self._requests: Dict[int, FleetRequest] = {}
        self._fid_counter = 0
        self._open = 0
        self._draining = False
        self._closed = False
        self._max_dispatch = max_dispatch
        self._poll_s = poll_s
        self._spawn_timeout_s = float(spawn_timeout_s)
        self._tokens_delivered = 0   # running journal total: O(1)
        #                              reads, survives replica deaths
        # fleet-level limits, cached at the FIRST hello (all replicas
        # share one spec): submit validation must keep working while
        # every replica happens to be mid-restart — the thread fleet
        # just queues in that window, and so must we
        self._limits: Optional[Dict] = None

        chaos_list = [] if chaos is None else (
            list(chaos) if isinstance(chaos, (list, tuple)) else [chaos])
        if self._disagg:
            # pool-named replicas: prefill0.., decode0.. — chaos
            # targets, breakers, events and /healthz all speak these
            pool_of = {f"{pool}{i}": pool
                       for pool in POOLS
                       for i in range(self._pools_spec[pool])}
            names = list(pool_of)
        else:
            names = [f"{name_prefix}{i}" for i in range(n_replicas)]
            pool_of = {name: ANY_POOL for name in names}
        by_target: Dict[str, Dict] = {}
        for spec in chaos_list:
            spec = dict(spec)
            target = spec.pop("target", None) or names[0]
            if target not in names:
                raise ValueError(
                    f"chaos target {target!r} names no replica "
                    f"(have {names})")
            by_target[target] = spec

        self._breakers = {
            name: CircuitBreaker(trip_after=trip_after,
                                 reset_s=breaker_reset_s, clock=clock)
            for name in names}

        # the listener children dial back into; accept thread matches
        # hello tokens to replicas so concurrent (re)spawns can't
        # cross-wire
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self._address = self._listener.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-accept", daemon=True)
        self._accept_thread.start()

        self._replicas: List[ProcReplica] = [
            ProcReplica(name, self, by_target.get(name),
                        pool=pool_of[name])
            for name in names]
        self._await_hellos()

        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="fleet-dispatch",
            daemon=True)
        self._dispatcher.start()

    # ------------------------------------------------------------------
    # handshake
    # ------------------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                conn.settimeout(30.0)
                hello = wire.recv_frame(conn, peer="handshake")
                conn.settimeout(None)
                if hello.get("t") != "hello":
                    conn.close()
                    continue
            except (wire.ConnectionClosed, wire.WireError, OSError):
                conn.close()
                continue
            with self._cv:
                rep = next((r for r in self._replicas
                            if r.token == hello.get("token")
                            and r.state == STARTING), None)
                if rep is None or self._closed:
                    conn.close()
                    continue
                rep.attach(conn, hello)
                if self._limits is None:
                    self._limits = rep.limits
                self._cv.notify_all()

    def _await_hellos(self) -> None:
        deadline = self.clock() + self._spawn_timeout_s
        with self._cv:
            while True:
                missing = [r.name for r in self._replicas
                           if r.state == STARTING]
                if not missing:
                    if (self._disagg and self._limits is not None
                            and not self._limits.get("prefix_cache",
                                                     True)):
                        # fail fast instead of silently burning the
                        # handoff retry budget on EVERY request: the
                        # KV handoff exports the PUBLISHED chain, and
                        # a cache-off engine never publishes — every
                        # transfer would fall back to local re-prefill
                        self._closed = True
                        for rep in self._replicas:
                            rep.kill()
                        try:
                            self._listener.close()
                        except OSError:
                            pass
                        raise ValueError(
                            "disaggregated pools need "
                            "prefix_cache=True engines: the "
                            "prefill->decode KV handoff ships the "
                            "published prefix chain, which a "
                            "cache-off engine never produces — build "
                            "the engine spec with prefix_cache=True "
                            "or run colocated (pools=None)")
                    return
                dead = [r.name for r in self._replicas
                        if r.state == STARTING and not r.proc.is_alive()]
                if dead or self.clock() >= deadline:
                    self._closed = True
                    for rep in self._replicas:
                        rep.kill()
                    try:
                        self._listener.close()
                    except OSError:
                        pass
                    raise RuntimeError(
                        f"replica process(es) failed to start: "
                        f"{dead or missing} (exited early: {dead}; "
                        f"spawn timeout {self._spawn_timeout_s}s) — "
                        f"check the engine builder spec "
                        f"{self.engine_spec.get('file') or self.engine_spec.get('module')}")
                self._cv.wait(0.05)

    def _audit_lock(self, name: str):
        """An instrumented Lock under ``lock_audit=True``, else None
        (the primitive constructors fall back to a stock Lock — the
        off path constructs exactly what it always did)."""
        return (self.lock_audit.lock(name)
                if self.lock_audit is not None else None)

    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(kind, **fields)

    def _note_breaker(self, name: str) -> None:
        """Typed event on a breaker state CHANGE (edge-detected here —
        transitions are driven from failure/success/restart sites)."""
        if self.events is None:
            return
        st = self._breakers[name].state
        if self._breaker_seen.get(name, "closed") != st:
            self._breaker_seen[name] = st
            self.events.emit("breaker", replica=name, state=st)

    @property
    def limits(self) -> Dict:
        """The shared engine limits (all replicas are built from one
        spec; the first hello ever received speaks for the fleet —
        cached, so submit keeps validating while every replica is
        mid-restart). The constructor's hello barrier guarantees this
        is set before any submit can run."""
        if self._limits is not None:
            return self._limits
        raise RuntimeError("no replica has completed its handshake")

    # ------------------------------------------------------------------
    # submission / results
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *, key=None,
               priority: int = 0, deadline_s: Optional[float] = None,
               on_token=None, adapter_id: Optional[str] = None) -> int:
        """Queue one request fleet-wide; returns its fleet id. The
        contract is :meth:`ServeFleet.submit`'s — typed
        :class:`Overloaded` instead of unbounded queueing, fleet-level
        default keys, end-to-end deadlines — with admissibility checked
        against the replicas' hello-reported ``limits`` (no engine
        lives in this process)."""
        import jax

        from quintnet_tpu.serve.engine import check_admissible

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        check_admissible(prompt.size, int(max_new_tokens),
                         **self.limits)
        with self._cv:
            self.metrics.submitted += 1
            if self._draining or self._closed:
                self.metrics.shed_shutdown += 1
                self._slo_observe("shed", 1.0)
                raise Overloaded(
                    "shutdown", "fleet is draining; not accepting work")
            now = self.clock()
            if deadline_s is not None and deadline_s <= 0:
                self.metrics.shed_deadline += 1
                self._slo_observe("shed", 1.0)
                raise Overloaded(
                    "deadline", f"deadline_s={deadline_s} already "
                    f"expired at submit")
            if self._disagg and self._pool_hard_down_locked("decode"):
                # the last rung of the decode-pool ladder: requests
                # already admitted requeue behind the breaker-gated
                # restart, but NEW work is shed typed — queueing it
                # would hide an outage every breaker says is not
                # about to heal (prefill-pool loss never sheds: the
                # decode pool absorbs prefill work instead)
                self.metrics.shed_pool_down += 1
                self._slo_observe("shed", 1.0)
                self._emit("shed", fid=None, reason="pool_down")
                raise Overloaded(
                    "pool_down",
                    "decode pool has no live replica and every "
                    "breaker is tripped; shedding instead of queueing "
                    "behind a breaker that cannot act — retry with "
                    "backoff against another fleet")
            fid = self._fid_counter
            self._fid_counter += 1
            if key is None:
                key = jax.random.fold_in(jax.random.key(0), fid)
            freq = FleetRequest(
                fid, prompt, int(max_new_tokens), key=key,
                priority=int(priority),
                deadline=(None if deadline_s is None
                          else now + float(deadline_s)),
                on_token=on_token, submit_time=now, clock=self.clock,
                adapter_id=adapter_id, trace_id=f"f{fid}")
            freq.slo = self.slo    # TTFT/ITL observed at delivery
            #   (FleetRequest.deliver — fired from the reader thread
            #   under the fleet lock, the client-visible point; the
            #   anchor is reset across handoff/migration so a cross-
            #   replica gap never reads as a decode-cadence violation)
            if self.tracer is not None:
                self.tracer.event(freq.trace_id, "fleet_submit",
                                  fid=fid, prompt_len=int(prompt.size),
                                  max_new_tokens=int(max_new_tokens),
                                  adapter_id=adapter_id)
            # the journal's key anchor: the submit key as raw data —
            # advancing it one split per journaled token reconstructs
            # any later chain state host-side (no device in the child
            # needed, no cooperation from a dead one possible)
            freq.key_data0 = np.asarray(jax.random.key_data(key))
            try:
                self._queue.push(freq)
            except Overloaded:
                self.metrics.shed_queue_full += 1
                self._slo_observe("shed", 1.0)
                raise
            self._requests[fid] = freq
            self._open += 1
            self.metrics.accepted += 1
            self._slo_observe("shed", 0.0)
            self._cv.notify_all()
            return fid

    def result(self, fid: int, *,
               timeout: Optional[float] = None) -> np.ndarray:
        freq = self._requests[fid]
        if not freq.event.wait(timeout):
            raise TimeoutError(
                f"fleet request {fid} unfinished after {timeout}s "
                f"(replica={freq.replica_name}, "
                f"migrations={freq.migrations})")
        if freq.error is not None:
            raise freq.error
        return freq.output

    def request(self, fid: int) -> FleetRequest:
        return self._requests[fid]

    def generate(self, prompts: Sequence, *, max_new_tokens, keys=None,
                 priorities=None,
                 timeout: Optional[float] = None) -> List[np.ndarray]:
        n = len(prompts)
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * n
        keys = [None] * n if keys is None else keys
        priorities = [0] * n if priorities is None else priorities
        if not (len(max_new_tokens) == len(keys) == len(priorities) == n):
            raise ValueError(
                "per-prompt argument lengths must match prompts")
        fids = [self.submit(p, m, key=k, priority=pr)
                for p, m, k, pr in zip(prompts, max_new_tokens, keys,
                                       priorities)]
        return [self.result(f, timeout=timeout) for f in fids]

    # ------------------------------------------------------------------
    # journal reconstruction — the crash-safe migration payload
    # ------------------------------------------------------------------
    @staticmethod
    def _advance_key_data(key_data: np.ndarray, n: int) -> np.ndarray:
        """The engine's key discipline, replayed host-side: every
        committed token advances the per-request chain by exactly one
        ``split -> take the carry`` (prefill, decode and verify all
        share it — serve/engine.py). ``n`` journaled tokens after the
        submit key is therefore ``n`` splits, and the result is
        BIT-equal to the key_data a cooperative export would have
        carried."""
        import jax

        key = jax.random.wrap_key_data(np.asarray(key_data))
        for _ in range(int(n)):
            key = jax.random.split(key, 2)[0]
        return np.asarray(jax.random.key_data(key))

    def _progress_for(self, freq: FleetRequest):
        """The request's RequestProgress as witnessed by the JOURNAL —
        what gets (re)dispatched, fresh or migrated. Needs nothing from
        the replica that was serving it."""
        from quintnet_tpu.serve.scheduler import RequestProgress

        return RequestProgress(
            rid=freq.fid, prompt=np.asarray(freq.prompt, np.int32),
            generated=list(freq.committed),
            key_data=self._advance_key_data(freq.key_data0,
                                            len(freq.committed)),
            max_new_tokens=freq.max_new_tokens,
            priority=freq.priority,
            preemptions=0, adapter_id=freq.adapter_id,
            deadline_s=freq.remaining_deadline(),
            trace_id=freq.trace_id)

    # ------------------------------------------------------------------
    # frame handling (replica reader threads)
    # ------------------------------------------------------------------
    def _on_frame(self, rep: ProcReplica, frame: Dict) -> None:
        t = frame.get("t")
        if t == "tok":
            tok, last = int(frame["tok"]), bool(frame["last"])
            with self._cv:
                # journal AND deliver under the fleet lock: migration
                # reads the journal under the same lock, so a late
                # token racing a stall-triggered migration is either
                # journaled-and-delivered before the reconstruction
                # (included in the resumed progress, never repeated)
                # or dropped here (ownership gone) and regenerated by
                # the survivor — exactly once, in order, either way.
                # Delivering outside the lock would open a window
                # where the survivor's token n+1 beats the victim's
                # token n to the client. Callbacks are contractually
                # quick (the thread fleet fires them from its engine
                # worker for the same reason).
                freq = rep._fid2freq.get(frame["fid"])
                if freq is None:
                    return
                self._tokens_delivered += 1
                first = freq.first_token_time is None
                # deliver() is THE journal-then-forward discipline
                # (fleet/fleet.py), client-callback faults isolated
                # there — one implementation for both fleets
                freq.deliver(tok, last)
                if first and self.tracer is not None:
                    self.tracer.event(freq.trace_id, "first_token",
                                      replica=rep.name)
        elif t == "fin":
            self._finish(rep, frame["fid"],
                         handoff=bool(frame.get("handoff")))
        elif t in ("failed", "reject"):
            self._reject(rep, frame["fid"],
                         wire.error_from_wire(frame["error"]))
        elif t == "hb":
            rep.hb.beat()
            rep.steps = int(frame.get("steps", rep.steps))
            # flight-recorder mirror: the child's fresh step records
            # ride its heartbeats (ring-lock-guarded — the dump path
            # snapshots from the dispatcher thread concurrently)
            recs = frame.get("rec")
            if recs:
                rep.ring_extend(recs)
        elif t == "death":
            # cooperative death (an in-child raise): same handling as
            # a connection loss; the export rides along but the
            # journal supersedes it (one reconstruction path, not two)
            self._handle_death(rep, stalled=False)
        elif t == "bye":
            with self._cv:
                rep.state = STOPPED

    def _finish(self, rep: ProcReplica, fid: int, *,
                handoff: bool = False) -> None:
        with self._cv:
            freq = rep._fid2freq.pop(fid, None)
            if freq is None:
                return
            incomplete = (not freq.last_seen
                          and len(freq.committed) < freq.max_new_tokens)
            if handoff and incomplete:
                # prefill-phase retirement: the first token is
                # journaled+delivered, the chain is published on the
                # prefill replica — release the replica's counters
                # (this dispatch is DONE for it) and move the request
                # to the decode pool through the KV-transfer thread
                rep.in_flight -= 1
                rep.outstanding_tokens -= freq.cost
                self._breakers[rep.name].record_success()
                self._note_breaker(rep.name)
                if self._closed:
                    self._shed_locked(freq, "shutdown",
                                      "fleet closed mid-handoff")
                    return
                self.metrics.handoffs += 1
                threading.Thread(
                    target=self._run_handoff, args=(rep, freq),
                    daemon=True,
                    name=f"handoff-{freq.fid}").start()
                return
            self._finalize_locked(rep, freq)

    def _finalize_locked(self, rep: Optional[ProcReplica],
                         freq: FleetRequest) -> None:
        if freq.event.is_set():
            return      # already shed/finalized (close-path races)
        if rep is not None:
            rep.in_flight -= 1
            rep.outstanding_tokens -= freq.cost
            self._breakers[rep.name].record_success()
            self._note_breaker(rep.name)
        # the journal IS the output: prompt + every streamed token
        freq.output = np.concatenate(
            [freq.prompt, np.asarray(freq.committed, np.int32)])
        freq.finish_time = self.clock()
        self.metrics.finished += 1
        self._slo_observe("error", 0.0)
        if freq.first_token_time is not None:
            self.metrics.ttfts.append(
                freq.first_token_time - freq.submit_time)
        self.metrics.latencies.append(
            freq.finish_time - freq.submit_time)
        self._open -= 1
        freq.event.set()
        self._cv.notify_all()

    def _run_handoff(self, src: ProcReplica,
                     freq: FleetRequest) -> None:
        """Move one prefilled request's KV chain from ``src`` (its
        prefill replica) to a decode replica, then requeue the request
        for decode dispatch — on its OWN thread, outside the fleet
        lock: the transfer is a pair of RPCs (export from the source,
        import into the destination) that may block, retry and sleep,
        none of which must stall token delivery or stall detection.

        Fault-tolerant BY CONSTRUCTION, not by luck:

        - every attempt runs under the shared jittered-exponential
          :class:`~quintnet_tpu.fleet.retry.RetryPolicy` with a
          per-RPC timeout — a stalled receiver costs one timeout, not
          a wedged dispatcher;
        - a SIGKILL'd source, a checksum-corrupt frame, a full
          destination pool and a vanished destination are all just
          failed attempts;
        - exhaustion falls back to LOCAL RE-PREFILL on whichever
          decode replica the request lands on: the chain is pure
          cache, so the fallback is slower but token-identical — the
          request is requeued either way, and ``close()`` racing the
          transfer sheds it typed instead of stranding it."""
        tokens = [int(t) for t in np.asarray(freq.prompt).reshape(-1)]
        ns = freq.adapter_id
        # the exported frame is cached ACROSS attempts: a
        # destination-side failure (busy receiver, timeout) must not
        # re-gather and re-ship a multi-megabyte chain the source
        # already produced. A checksum-rejected frame (WireError from
        # the importer) drops the cache — that frame is damaged and a
        # fresh export is the whole point of the retry.
        cached = {"kv": None}

        def rpc_timeout_s() -> float:
            # a deadline-bound request must not spend more wall clock
            # in a single transfer RPC than it has left to live
            rem = freq.remaining_deadline()
            if rem is None:
                return self._handoff_timeout_s
            return min(self._handoff_timeout_s, max(rem, 0.05))

        def attempt(n: int):
            with self._cv:
                cands = router_eligible(self._replicas, pool="decode")
                # the SAME router pick the dispatch path uses —
                # adapter affinity included, so a tenant's chain lands
                # on a replica already holding its adapter instead of
                # pinning the request (via warm_replica) to one that
                # must load it
                dst = (self._router.pick(cands, adapter_id=ns)
                       if cands else None)
            if dst is None:
                raise OSError(
                    "no decode replica is accepting a KV transfer")
            if cached["kv"] is None:
                f = src.rpc({"t": "kv_export", "tokens": tokens,
                             "namespace": ns,
                             "trace_id": freq.trace_id},
                            timeout=rpc_timeout_s())
                kv = f.get("kv")
                if kv is None:
                    # permanent (evicted chain, cache off, oversized
                    # frame): a plain ValueError is NOT in retry_on —
                    # straight to the local-re-prefill fallback
                    raise ValueError(
                        f.get("reason")
                        or "prefill replica declined the KV export")
                cached["kv"] = kv
            f2 = dst.rpc({"t": "kv_import", "kv": cached["kv"],
                          "trace_id": freq.trace_id},
                         timeout=rpc_timeout_s())
            if f2.get("error") is not None:
                err = wire.error_from_wire(f2["error"])
                if isinstance(err, wire.WireError):
                    cached["kv"] = None   # frame damaged: re-export
                raise err
            return dst, int(f2.get("imported", 0))

        def on_retry(attempt_no: int, error: BaseException) -> None:
            with self._cv:
                self.metrics.handoff_retries += 1
            self._emit("handoff_retry", fid=freq.fid,
                       trace_id=freq.trace_id, attempt=attempt_no,
                       error=f"{type(error).__name__}: {error}")

        imported, dst = 0, None
        handoff_t0 = self.clock()
        # the request's remaining deadline bounds the WHOLE transfer:
        # retrying past it wastes RPCs on a request that can only be
        # shed as expired at its next dispatch — fall back (a no-op
        # requeue; the expired request never decodes) instead of
        # out-waiting the client by attempts x handoff_timeout_s
        remaining = freq.remaining_deadline()
        policy = (self._handoff_retry if remaining is None
                  else self._handoff_retry.bounded(remaining))
        try:
            if remaining is not None and remaining <= 0:
                raise TimeoutError(
                    f"deadline budget already spent "
                    f"({remaining:.3f}s remaining) — skipping the KV "
                    f"transfer")
            # retry TRANSIENT faults only: connection loss/timeouts
            # (OSError covers ConnectionClosed) and damaged frames
            # (WireError). Plain ValueError/KeyError are permanent —
            # geometry mismatch, evicted chain, declined export — and
            # fall through to the fallback immediately instead of
            # burning the budget re-confirming a misconfiguration.
            dst, imported = policy.run(
                attempt,
                retry_on=(OSError, TimeoutError, wire.WireError),
                on_retry=on_retry)
        except Exception as e:  # noqa: BLE001 — the fallback is total
            self._emit("handoff_fallback", fid=freq.fid,
                       trace_id=freq.trace_id,
                       error=f"{type(e).__name__}: {e}")
            if self.tracer is not None:
                self.tracer.event(freq.trace_id, "handoff",
                                  fallback=True,
                                  error=type(e).__name__)
            with self._cv:
                self.metrics.handoff_fallbacks += 1
        else:
            if imported > 0:
                freq.warm_replica = dst.name
                self._emit("handoff", fid=freq.fid,
                           trace_id=freq.trace_id,
                           from_replica=src.name, to_replica=dst.name,
                           transferred_tokens=imported)
                if self.tracer is not None:
                    self.tracer.event(freq.trace_id, "handoff",
                                      to_replica=dst.name,
                                      transferred_tokens=imported)
                with self._cv:
                    self.metrics.handoff_transfers += 1
            else:
                # the frame landed but nothing was cached (destination
                # pool full, or its cache off): not a wire fault, and
                # retrying would not change it — local re-prefill
                self._emit("handoff_fallback", fid=freq.fid,
                           trace_id=freq.trace_id,
                           error="import cached 0 tokens "
                                 "(destination pool full or cache off)")
                with self._cv:
                    self.metrics.handoff_fallbacks += 1
        finally:
            if self.signals is not None:
                # the transfer's realized wall (success or fallback) —
                # a TTFT-class cost the pressure plane watches
                self.signals.sample("handoff_latency_s",
                                    self.clock() - handoff_t0)
            with self._cv:
                # re-anchor the SLO engine's ITL chain: the gap from
                # the prefill replica's first token to the decode
                # replica's second spans the handoff, not the decode
                # cadence
                freq.last_token_time = None
                if self._closed:
                    self._shed_locked(
                        freq, "shutdown",
                        "fleet closed during the KV handoff")
                else:
                    self._queue.push_front([freq])
                    self._cv.notify_all()

    def _reject(self, rep: ProcReplica, fid: int,
                error: BaseException) -> None:
        from quintnet_tpu.serve.scheduler import DeadlineExceeded

        with self._cv:
            freq = rep._fid2freq.pop(fid, None)
            if freq is None:
                return
            rep.in_flight -= 1
            rep.outstanding_tokens -= freq.cost
            if isinstance(error, DeadlineExceeded):
                self.metrics.deadline_exceeded += 1
                self._emit("deadline_exceeded", fid=freq.fid,
                           trace_id=freq.trace_id, replica=rep.name,
                           generated=error.generated)
            elif (isinstance(error, Overloaded)
                    and error.reason == "deadline"):
                self.metrics.shed_deadline += 1
            freq.error = error
            self._slo_observe("error", 1.0)
            self._open -= 1
            freq.event.set()
            self._cv.notify_all()

    # ------------------------------------------------------------------
    # death / stall / restart supervision
    # ------------------------------------------------------------------
    def _on_conn_lost(self, rep: ProcReplica) -> None:
        """Reader-thread EOF: every frame the kernel had buffered has
        been processed (the journal is complete up to the last byte
        the victim flushed) — anything beyond it is regenerated
        deterministically on the survivor."""
        self._handle_death(rep, stalled=False)

    def _handle_death(self, rep: ProcReplica, *, stalled: bool) -> None:
        with self._cv:
            self._handle_death_locked(rep, stalled=stalled)

    def _handle_death_locked(self, rep: ProcReplica, *,
                             stalled: bool) -> None:
        """The one death path (fleet lock held): conn-lost EOF, stall
        detection, cooperative death frames and dispatch-send failures
        all land here — one body, so a fix applies once."""
        if rep.state == STOPPED:
            self._cv.notify_all()
            return
        if rep.migrated or (self._closed and not rep.unfinished()):
            # work already moved (stall handler beat the EOF) or
            # nothing to move — just make the replica restartable
            rep.state = DEAD
            self._cv.notify_all()
            return
        rep.state = STALLED if stalled else DEAD
        rep.migrated = True
        if stalled:
            self.metrics.stalls += 1
        else:
            self.metrics.replica_deaths += 1
        self._emit("replica_stall" if stalled else "replica_death",
                   replica=rep.name, pid=rep.pid,
                   steps=rep.steps, in_flight=len(rep._fid2freq),
                   error=(None if rep.error is None
                          else f"{type(rep.error).__name__}: "
                               f"{rep.error}"))
        self._record_crash_locked(rep,
                                  reason="stall" if stalled
                                  else "death")
        breaker = self._breakers[rep.name]
        breaker.record_failure()
        self._note_breaker(rep.name)
        rep.restart_at = (self.clock()
                          + self.backoff.delay_s(
                              breaker.consecutive_failures))
        self._migrate_locked(rep)
        self._cv.notify_all()

    def _record_crash_locked(self, rep: ProcReplica, *,
                             reason: str) -> None:
        """The black box, process-fleet flavor (fleet lock held, rep's
        ``_fid2freq`` not yet cleared): everything here is
        DISPATCHER-side state — the heartbeat-mirrored ring, the
        parent tracer's spans for the in-flight requests, the
        journal's per-request account — because the corpse cannot be
        asked for anything. The payload is QUEUED under the lock and
        written by the dispatch loop OUTSIDE it
        (:meth:`_write_dumps`): file IO must never stall token
        delivery."""
        if not self._obs:
            return
        affected = sorted(rep._fid2freq.values(), key=lambda f: f.fid)
        ring = rep.ring_snapshot()
        tids = [f.trace_id for f in affected if f.trace_id]
        traces = (self.tracer.snapshot(tids)
                  if self.tracer is not None else {})
        requests = [{"fid": f.fid, "trace_id": f.trace_id,
                     "committed": len(f.committed),
                     "migrations": f.migrations,
                     "adapter_id": f.adapter_id} for f in affected]
        err = (None if rep.error is None
               else f"{type(rep.error).__name__}: {rep.error}")
        self.last_crash = {
            "replica": rep.name, "reason": reason, "error": err,
            "ring": ring, "traces": traces, "requests": requests,
            # the last pool-pressure snapshot rides the black box:
            # "was the pool already saturated when p1 died" is a
            # question the corpse cannot answer but the bus can
            "signals": (self.signals.snapshot()
                        if self.signals is not None else {}),
            # the lock-audit ledgers ride the black box under
            # lock_audit=True: "who held what, for how long" at death
            "locks": (self.lock_audit.summary()
                      if self.lock_audit is not None else {}),
        }
        if self.crash_dir is not None:
            self._pending_dumps.append(dict(
                self.last_crash,
                events=(self.events.snapshot(last=64)
                        if self.events is not None else []),
                extra={"pid": rep.pid, "steps": rep.steps}))

    def _write_dumps(self, pending: List[Dict]) -> None:
        """Write queued crash dumps (called WITHOUT the fleet lock)."""
        from quintnet_tpu.obs import write_crash_dump

        for spec in pending:
            path = write_crash_dump(self.crash_dir, **spec)
            self.crash_dumps.append(path)
            # the writer keeps only the newest N files — drop ledger
            # entries whose file was pruned so every path here loads
            self.crash_dumps = [p for p in self.crash_dumps
                                if os.path.exists(p)]
            self._emit("crash_dump", replica=spec["replica"],
                       path=path)

    def _migrate_locked(self, rep: ProcReplica) -> None:
        exports = sorted(rep._fid2freq.items())
        rep._fid2freq = {}
        rep.in_flight = 0
        rep.outstanding_tokens = 0
        migrated: List[FleetRequest] = []
        for _fid, freq in exports:
            if freq.last_seen:
                # the final token (is_last) was journaled and already
                # delivered — only the bookkeeping frame died with the
                # replica; the request is COMPLETE, finalize it here
                self._finalize_locked(None, freq)
                continue
            if self._closed:
                self._shed_locked(freq, "shutdown",
                                  "replica died during close")
                continue
            freq.migrations += 1
            freq.last_token_time = None   # ITL re-anchors on the
            #                               survivor (see fleet.py)
            self.metrics.migrations += 1
            self._emit("migration", fid=freq.fid,
                       trace_id=freq.trace_id,
                       from_replica=rep.name,
                       committed=len(freq.committed))
            if self.tracer is not None:
                self.tracer.event(freq.trace_id, "migration",
                                  from_replica=rep.name,
                                  committed=len(freq.committed))
            migrated.append(freq)
        self._queue.push_front(migrated)

    def _pool_members(self, pool: str) -> List["ProcReplica"]:
        return [r for r in self._replicas if r.pool == pool]

    def _pool_alive_locked(self, pool: str) -> bool:
        """Does the pool have a member that serves now or is coming up
        (STARTING = a restart already in flight)? The degradation
        ladder keys on this: prefill down -> decode absorbs prefill
        work; decode down -> requests requeue behind the breaker."""
        return any(r.state in (HEALTHY, STARTING)
                   for r in self._pool_members(pool))

    def _pool_hard_down_locked(self, pool: str) -> bool:
        """No live member AND no breaker that could grant a restart
        (all tripped inside their cool-down): queueing new work would
        hide an outage the client should route around — the shed rung
        of the ladder (typed ``Overloaded('pool_down')``)."""
        members = self._pool_members(pool)
        if any(r.state in (HEALTHY, STARTING) for r in members):
            return False
        return all(not self._breakers[r.name].restart_conceivable
                   for r in members)

    def _tend_pools_locked(self) -> None:
        """Edge-detected pool health events: a pool losing its last
        live replica emits ``pool_degraded`` once (and
        ``pool_recovered`` when it serves again) — the obs trail of
        the fallback ladder."""
        if not self._disagg:
            return
        for pool in POOLS:
            down = not self._pool_alive_locked(pool)
            if down != self._pool_down_seen.get(pool, False):
                self._pool_down_seen[pool] = down
                self._emit("pool_degraded" if down else "pool_recovered",
                           pool=pool)

    # ------------------------------------------------------------------
    # SLO engine + pool-pressure signal plane (obs/slo.py, obs/signals.py)
    # ------------------------------------------------------------------
    def arm_slo(self, config, **planner_kwargs) -> None:
        """Arm the SLO engine, the signal bus and (disaggregated
        fleets only) the observe-only rebalance planner against this
        fleet's dispatcher. ``config`` is an
        :class:`~quintnet_tpu.obs.slo.SLOConfig`; ``planner_kwargs``
        go to :class:`~quintnet_tpu.obs.signals.PoolRebalancePlanner`
        (cooldown, donor-occupancy gate). Can be called after
        construction — the bench measures a baseline first and derives
        its targets from it — but the fleet must have been built with
        ``obs=True`` (or ``slo=`` at the constructor) for the
        heartbeat-mirrored rings the occupancy signals read."""
        from quintnet_tpu.obs import EventLog
        from quintnet_tpu.obs.signals import (PoolRebalancePlanner,
                                              SignalBus)
        from quintnet_tpu.obs.slo import SLOEngine
        if not self._obs:
            # silently arming would sample permanently-zero occupancy
            # and KV pressure (children only piggyback ring records
            # when spawned with obs on) and the planner's donor gate
            # would trivially pass — judgment over dead gauges
            raise ValueError(
                "arm_slo requires a fleet built with obs=True (or "
                "slo= at the constructor): the occupancy/KV signals "
                "read the heartbeat-mirrored step rings")
        with self._cv:
            if self.events is None:
                self.events = EventLog(
                    clock=self.clock,
                    lock=self._audit_lock("obs.events"))
            self.slo = SLOEngine(config, clock=self.clock,
                                 events=self.events)
            self.signals = SignalBus(
                clock=self.clock,
                lock=self._audit_lock("obs.signals"))
            self.planner = (PoolRebalancePlanner(
                clock=self.clock, events=self.events, **planner_kwargs)
                if self._disagg else None)
            self._signal_next_t = 0.0

    def _slo_observe(self, stream: str, value: float) -> None:
        if self.slo is not None:
            self.slo.observe(stream, value)

    def _queue_gauges(self):
        """(depth, oldest wait age) — FleetMetrics' probe and the
        front door's Retry-After hint; snapshot reads, lock-free."""
        return len(self._queue), self._queue.oldest_wait_s()

    def queue_oldest_wait_s(self) -> float:
        """Wait age of the oldest queued request (0.0 when empty)."""
        return self._queue.oldest_wait_s()

    def _tend_signals_locked(self, now: float) -> None:
        """One signal-plane tick on the dispatcher thread (fleet lock
        held): sample per-pool pressure onto the bus from state the
        dispatcher ALREADY holds — the admission queue, the
        heartbeat-mirrored step rings, breaker/heartbeat records, the
        handoff ledger — then re-evaluate the SLO engine and let the
        planner judge. Everything is host-side floats; nothing here
        blocks, syncs a device, or mutates routing state (the planner
        is observe-only by construction)."""
        if self.slo is None:
            return
        if now < self._signal_next_t:
            return
        self._signal_next_t = now + self.slo.config.eval_interval_s
        bus = self.signals
        items = self._queue.items()

        def oldest(its):
            # per-pool SUBSETS only; the fleet-wide age reuses the
            # queue's own accessor (getattr-tolerant where this is not)
            if not its:
                return 0.0
            return max(0.0, now - min(i.submit_time for i in its))

        bus.sample("queue_depth", float(len(items)))
        bus.sample("queue_oldest_wait_s",
                   self._queue.oldest_wait_s(now))
        limits = self._limits or {}
        max_slots = int(limits.get("max_slots") or 0)
        budget = limits.get("prefill_chunk_budget")
        for pool in sorted({r.pool for r in self._replicas}):
            members = [r for r in self._replicas if r.pool == pool]
            if self._disagg:
                # phase-aware queue attribution: a request with no
                # committed token waits on the prefill pool, one with
                # a journal waits on decode
                pending = [i for i in items
                           if bool(i.committed) == (pool == "decode")]
                bus.sample("queue_depth", float(len(pending)),
                           pool=pool)
                bus.sample("queue_oldest_wait_s", oldest(pending),
                           pool=pool)
            running = slots = kv_used = kv_total = 0
            chunk_spent = chunk_steps = 0
            hb_age = 0.0
            open_breakers = 0
            for r in members:
                if self._breakers[r.name].state != CLOSED:
                    open_breakers += 1
                if r.state != HEALTHY:
                    # a corpse's last-known ring record is forensics
                    # (crash dumps), not live pressure: counting its
                    # slots/running/KV would double-count work that
                    # already migrated to a survivor and skew the
                    # planner's donor-occupancy gate mid-outage
                    continue
                hb_age = max(hb_age, r.hb.age_s)
                if max_slots:
                    slots += max_slots
                with r._ring_lock:
                    last = r.ring[-1] if r.ring else None
                if last is None:
                    continue
                running += int(last.get("running", 0))
                kv_used += int(last.get("kv_blocks_used", 0))
                kv_total += int(last.get("kv_blocks_total", 0))
                if budget and last.get("prefill_chunks", 0) > 0:
                    chunk_spent += int(last.get("prefill_tokens", 0))
                    chunk_steps += 1
            bus.sample("occupancy",
                       running / slots if slots else 0.0, pool=pool)
            bus.sample("kv_pressure",
                       kv_used / kv_total if kv_total else 0.0,
                       pool=pool)
            if budget:
                bus.sample("chunk_budget_saturation",
                           chunk_spent / (chunk_steps * budget)
                           if chunk_steps else 0.0, pool=pool)
            bus.sample("heartbeat_age_s", hb_age, pool=pool)
            bus.sample("breakers_open", float(open_breakers),
                       pool=pool)
        m = self.metrics
        bus.sample("handoff_fallback_rate",
                   m.handoff_fallbacks / m.handoffs if m.handoffs
                   else 0.0)
        status = self.slo.evaluate(now)
        if self.planner is not None:
            self.planner.plan(status, bus)

    def _tend_locked(self) -> None:
        now = self.clock()
        self._tend_pools_locked()
        self._tend_signals_locked(now)
        for i, rep in enumerate(self._replicas):
            if rep.state == STARTING:
                if not rep.proc.is_alive():
                    # died building its engine: a failure like any
                    # other, breaker + backoff decide the retry
                    self._handle_death(rep, stalled=False)
                elif now - rep.spawned_at > self._spawn_timeout_s:
                    rep.kill()
                    self._handle_death(rep, stalled=True)
                continue
            if rep.state == HEALTHY and rep.hb.expired:
                # the wedge path: alive socket, silent process — route
                # around it within the heartbeat budget, move its work
                # via the journal, and put the zombie down
                self._handle_death(rep, stalled=True)
                rep.kill()
                continue
            if rep.state == STALLED and not rep.proc.is_alive():
                rep.state = DEAD
            if rep.state != DEAD:
                continue
            if rep.restart_at is not None and now < rep.restart_at:
                continue
            allowed = self._breakers[rep.name].allow_restart()
            self._note_breaker(rep.name)
            if not allowed:
                continue
            chaos_spec = rep.chaos_spec
            if not (chaos_spec or {}).get("rearm", False):
                chaos_spec = None   # one-shot faults do not respawn
            self._replicas[i] = ProcReplica(rep.name, self, chaos_spec,
                                            pool=rep.pool)
            self.metrics.restarts += 1
            self._emit("replica_restart", replica=rep.name)

    # ------------------------------------------------------------------
    # dispatcher
    # ------------------------------------------------------------------
    def _shed_locked(self, freq: FleetRequest, reason: str,
                     message: str) -> None:
        if freq.event.is_set():
            # already finalized (close() sheds unfinished() while a
            # racing EOF handler migrates the same map — whoever is
            # second must not double-decrement _open)
            return
        if reason == "deadline":
            self.metrics.shed_deadline += 1
        else:
            self.metrics.shed_shutdown += 1
        self._slo_observe("shed", 1.0)
        self._emit("shed", fid=freq.fid, trace_id=freq.trace_id,
                   reason=reason)
        freq.error = Overloaded(reason, message)
        self._open -= 1
        freq.event.set()
        self._cv.notify_all()

    def _route_disagg_locked(self, freq: FleetRequest):
        """Pool routing for one queued request (fleet lock held).
        Returns ``(replica, mode)`` — mode ``"prefill"`` dispatches
        prefill-only (first token + published chain, then handoff),
        ``"full"`` runs to completion — or ``(None, None)`` when
        nothing can take it NOW (it stays queued: the requeue rung).

        The degradation ladder, encoded:

        - prefill phase, prefill pool has candidates -> prefill pool;
        - prefill phase, prefill pool DOWN (no live/starting member)
          -> the decode pool absorbs the whole request, colocated
          style (mode "full", no handoff) — slower for decode tails,
          but the fleet keeps serving;
        - prefill pool merely BUSY (live but at its dispatch window)
          -> wait; absorbing would defeat the isolation the pools buy;
        - decode phase -> decode pool only, preferring the replica a
          successful KV handoff warmed; decode pool empty -> the
          request requeues behind the breaker-gated restart (new
          submits shed typed once every breaker is tripped —
          :meth:`submit`)."""
        if not freq.committed:
            cands = router_eligible(self._replicas, pool="prefill")
            if cands:
                return (self._router.pick(
                    cands, adapter_id=freq.adapter_id), "prefill")
            if not self._pool_alive_locked("prefill"):
                cands = router_eligible(self._replicas, pool="decode")
                if cands:
                    return (self._router.pick(
                        cands, adapter_id=freq.adapter_id), "full")
            return None, None
        cands = router_eligible(self._replicas, pool="decode")
        if not cands:
            return None, None
        if freq.warm_replica is not None:
            warm = next((r for r in cands
                         if r.name == freq.warm_replica), None)
            if warm is not None:
                return warm, "full"
        return self._router.pick(cands,
                                 adapter_id=freq.adapter_id), "full"

    def _reserve_dispatch_locked(self):
        """Pick a replica and claim a queued request for it (fleet lock
        held): ownership — ``rep._fid2freq`` and the routing counters —
        is established HERE, so the payload construction and the
        socket write can happen OUTSIDE the lock without racing the
        journal or a migration. Returns (rep, freq) or None.

        Colocated fleets dispatch the queue head. Disaggregated fleets
        dispatch the FIRST DISPATCHABLE request in queue order — a
        decode-phase request waiting for its pool must not block a
        prefill-phase request behind it (head-of-line isolation
        between the two regimes is half the point of the split)."""
        for freq in self._queue.shed_expired():
            self._shed_locked(
                freq, "deadline",
                f"request {freq.fid} still queued at its deadline")
        if not len(self._queue):
            return None
        if not self._disagg:
            cands = router_eligible(self._replicas)
            if not cands:
                return None
            rep = self._router.pick(
                cands, adapter_id=self._queue.peek_adapter_id())
            freq = self._queue.pop()
            freq.dispatched_phase = "full"
        else:
            rep = freq = None
            for cand in self._queue.items():
                got, mode = self._route_disagg_locked(cand)
                if got is not None:
                    rep, freq = got, cand
                    freq.dispatched_phase = mode
                    break
            if freq is None:
                return None
            self._queue.remove(freq)
        freq.cost = freq.outstanding_cost()
        freq.replica_name = rep.name
        rep._fid2freq[freq.fid] = freq
        rep.in_flight += 1
        rep.outstanding_tokens += freq.cost
        if freq.adapter_id is not None:
            rep._adapters_seen.add(freq.adapter_id)
        if self.tracer is not None:
            self.tracer.add(freq.trace_id, "fleet_queue",
                            t0=freq.submit_time, t1=self.clock(),
                            migrations=freq.migrations)
            self.tracer.event(freq.trace_id, "dispatch",
                              replica=rep.name)
        return rep, freq

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                self._tend_locked()
                pending, self._pending_dumps = self._pending_dumps, []
                job = self._reserve_dispatch_locked()
                if job is None and not pending:
                    self._cv.wait(self._poll_s)
                    continue
            if pending:
                self._write_dumps(pending)
            if job is None:
                continue
            rep, freq = job
            # payload construction OUTSIDE the lock: the key replay is
            # one jax split per journaled token — a long-lived
            # migrated request must not stall token delivery and
            # stall detection while its key is advanced
            payload = wire.progress_to_wire(self._progress_for(freq))
            frame = {"t": "submit", "fid": freq.fid,
                     "progress": payload,
                     "prefill_only":
                         freq.dispatched_phase == "prefill"}
            if self._tier_lookup_applies(rep, freq):
                # warm the target from the best peer's tier BEFORE the
                # submit lands — on its own thread (the _run_handoff
                # discipline): the probe + transfer RPCs may block and
                # must stall neither token delivery nor stall
                # detection. The thread sends the submit afterward,
                # warm or not.
                threading.Thread(
                    target=self._run_peer_fetch,
                    args=(rep, freq, frame), daemon=True,
                    name=f"tierfetch-{freq.fid}").start()
                continue
            try:
                rep.send(frame)
            except OSError:
                # connection failure AT dispatch (dead socket, or a
                # send timed out against a wedged peer): the replica
                # is done — this request (and everything else parked
                # there, via its fid2freq ownership) re-queues at the
                # front and restarts follow the breaker + jittered
                # backoff; the retry is free. Idempotent with a
                # concurrent stall-handler migration (migrated flag).
                with self._cv:
                    self._handle_death_locked(rep, stalled=False)

    # ------------------------------------------------------------------
    # tiered-KV peer lookup (serve/kv_tier.py)
    # ------------------------------------------------------------------
    def _tier_lookup_applies(self, rep: ProcReplica,
                             freq: FleetRequest) -> bool:
        """Should this dispatch run the kv_peek fan-out first? Only
        for FRESH requests (no journaled tokens — a migration's
        re-prefill path already benefits from whatever the target
        holds) whose prompt spans at least one full block beyond the
        admission cap, on a multi-replica fleet whose engines carry a
        host tier (auto mode) or when explicitly forced on."""
        # called from the dispatch loop OUTSIDE the fleet lock (the
        # payload-construction window): snapshot the dispatcher-owned
        # fields under it — _limits is cached at first hello and
        # _closed flips at close(), both under _cv (QT202)
        with self._cv:
            limits = self._limits or {}
            closed = self._closed
            n_live = len(self._replicas)
        if self._tier_peer_lookup is None:
            enabled = bool(limits.get("kv_tier")) and n_live >= 2
        else:
            enabled = bool(self._tier_peer_lookup)
        if not enabled or closed or freq.committed:
            return False
        bs = int(limits.get("block_size", 0) or 0)
        return bs > 0 and len(freq.prompt) > bs

    def _run_peer_fetch(self, rep: ProcReplica, freq: FleetRequest,
                        frame: Dict) -> None:
        """Probe peers' tiers and warm ``rep`` before its submit frame
        lands. OPPORTUNISTIC, single attempt, total fallback: any
        fault — peer death, timeout, corrupt frame, declined export —
        just dispatches without warm peer KV (the chain is cache, so
        re-prefill is token-identical; that is the whole failure
        semantics). The submit is sent from THIS thread afterward
        either way, with the dispatcher's own dead-socket
        discipline."""
        try:
            self._peer_fetch(rep, freq)
        except Exception as e:
            with self._cv:
                self.metrics.tier_peer_fallbacks += 1
            self._emit("tier_peer_miss", fid=freq.fid,
                       replica=rep.name, reason=repr(e))
        try:
            rep.send(frame)
        except OSError:
            with self._cv:
                self._handle_death_locked(rep, stalled=False)

    def _peer_fetch(self, rep: ProcReplica,
                    freq: FleetRequest) -> None:
        timeout = self._handoff_timeout_s
        tokens = [int(x) for x in np.asarray(freq.prompt).reshape(-1)]
        ns = freq.adapter_id
        with self._cv:
            self.metrics.tier_probes += 1
            peers = [r for r in self._replicas
                     if r is not rep and r.state == HEALTHY]
            # _limits is dispatcher-owned state: read it under the
            # same lock as the peer snapshot, not after it (QT202)
            bs = max(int((self._limits or {}).get("block_size", 1)
                         or 1), 1)
        # the target's own coverage is the bar a peer must clear — by
        # a full block, or the transfer costs more than it saves
        local = int(rep.rpc({"t": "kv_peek", "tokens": tokens,
                             "namespace": ns},
                            timeout=timeout).get("n_tokens", 0))
        best, best_n = None, local
        for peer in peers:
            try:
                n = int(peer.rpc({"t": "kv_peek", "tokens": tokens,
                                  "namespace": ns},
                                 timeout=timeout).get("n_tokens", 0))
            except (OSError, TimeoutError, wire.WireError):
                continue      # a dead peer is just a peer with no hit
            if n > best_n:
                best, best_n = peer, n
        if best is None or best_n < local + bs:
            self._emit("tier_peer_miss", fid=freq.fid,
                       replica=rep.name, reason="no_better_peer",
                       local_tokens=local, best_tokens=best_n)
            return
        f = best.rpc({"t": "kv_export", "tokens": tokens,
                      "namespace": ns, "trace_id": freq.trace_id},
                     timeout=timeout)
        kv = f.get("kv")
        if kv is None:
            with self._cv:
                self.metrics.tier_peer_fallbacks += 1
            self._emit("tier_peer_miss", fid=freq.fid,
                       replica=rep.name,
                       reason=str(f.get("reason") or "export_declined"))
            return
        f2 = rep.rpc({"t": "kv_import", "kv": kv,
                      "trace_id": freq.trace_id}, timeout=timeout)
        imported = int(f2.get("imported", 0))
        if imported <= 0:
            with self._cv:
                self.metrics.tier_peer_fallbacks += 1
            self._emit("tier_peer_miss", fid=freq.fid,
                       replica=rep.name,
                       reason=str(f2.get("error") or "import_declined"))
            return
        with self._cv:
            self.metrics.tier_peer_transfers += 1
        self._emit("tier_peer_hit", fid=freq.fid,
                   from_replica=best.name, to_replica=rep.name,
                   tokens=imported)

    # ------------------------------------------------------------------
    # lifecycle / operations
    # ------------------------------------------------------------------
    def pause_all(self) -> None:
        # symmetric with resume_all: rep.paused is routing state the
        # dispatcher reads under the fleet lock, so it is written
        # under it too (the bare writes here predated the auditor)
        with self._cv:
            for rep in self._replicas:
                rep.paused = True
                if rep.state == HEALTHY:
                    try:
                        rep.send({"t": "pause"})
                    except OSError:
                        pass

    def resume_all(self) -> None:
        with self._cv:
            for rep in self._replicas:
                rep.paused = False
                if rep.state == HEALTHY:
                    try:
                        rep.send({"t": "resume"})
                    except OSError:
                        pass
            self._cv.notify_all()

    def pause_replica(self, name: str, paused: bool = True) -> None:
        rep = self.replica(name)
        rep.paused = paused
        if rep.state == HEALTHY:
            rep.send({"t": "pause" if paused else "resume"})
        with self._cv:
            self._cv.notify_all()

    def warmup(self) -> None:
        """Compile every replica's full program set (prefill buckets +
        decode [+ verify]) outside any timed window — the bench calls
        this instead of routing sacrificial requests. Replicas compile
        CONCURRENTLY (independent processes; serializing the RPCs
        would multiply warmup wall time by the replica count); the
        first failure propagates."""
        errs: List[BaseException] = []

        def one(rep: ProcReplica) -> None:
            try:
                rep.rpc({"t": "warmup"}, timeout=600.0)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errs.append(e)

        threads = [threading.Thread(target=one, args=(rep,),
                                    name=f"warmup-{rep.name}")
                   for rep in self._replicas if rep.state == HEALTHY]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def arm_chaos(self, target: str, spec: Dict) -> None:
        """Arm a ChaosMonkey spec dict (kill_at_step/mode/rearm) inside
        the RUNNING replica process — the bench arms after warmup so
        kill_at_step counts replay steps only. The spec also sticks to
        the parent-side handle so ``rearm=True`` faults re-arm on
        restart, matching the thread fleet's semantics."""
        rep = self.replica(target)
        spec = {k: v for k, v in dict(spec).items() if k != "target"}
        rep.chaos_spec = dict(spec, target=target)
        rep.rpc({"t": "arm_chaos", "spec": spec}, timeout=60.0)

    def export_progress(self, name: str) -> List:
        """A LIVE replica's own view of its unfinished work (graceful
        ops; the crash path never needs it)."""
        frames = self.replica(name).rpc({"t": "export"}, timeout=60.0)
        return [wire.progress_from_wire(p) for p in frames["progress"]]

    def replica_traces(self, name: str, trace_ids=None) -> Dict:
        """A LIVE replica's span log over the wire (obs/trace.py
        snapshot, optionally restricted to ``trace_ids``) — how the
        dispatcher verifies a migrated request's spans CONTINUE on the
        destination under the trace id the journal carried. Dead
        replicas' engine-side spans died with their process — their
        black box is the heartbeat-mirrored ring in the crash dump."""
        f = self.replica(name).rpc(
            {"t": "trace",
             "trace_ids": (None if trace_ids is None
                           else list(trace_ids))}, timeout=60.0)
        return f["traces"]

    def replica_ring(self, name: str) -> List[Dict]:
        """A LIVE replica's own flight-recorder ring over the wire
        (the authoritative copy; the parent mirror lags one beat)."""
        f = self.replica(name).rpc({"t": "trace", "trace_ids": []},
                                   timeout=60.0)
        return f["ring"]

    def engine_summaries(self) -> Dict[str, Dict]:
        """Per-LIVE-replica ``ServeMetrics.summary()`` dicts — the
        front door's /metrics and /v1/metrics surface
        (frontdoor.py); the same stats frame replica_stats reads."""
        return {name: s["metrics"]
                for name, s in self.replica_stats().items()}

    def drain(self, *, timeout: Optional[float] = None) -> None:
        """Graceful shutdown, the last rungs of the degradation ladder:
        refuse new work (shed typed), let everything accepted finish —
        migrations included — then stop the processes."""
        deadline = None if timeout is None else self.clock() + timeout
        with self._cv:
            self._draining = True
            self._emit("drain", open_requests=self._open)
            self._cv.notify_all()
            while self._open > 0:
                if deadline is not None and self.clock() >= deadline:
                    raise TimeoutError(
                        f"drain: {self._open} request(s) still open "
                        f"after {timeout}s")
                self._cv.wait(self._poll_s)
        self.close()

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._draining = True
            self._closed = True
            self._emit("close", open_requests=self._open)
            for freq in self._queue.drain_all():
                self._shed_locked(freq, "shutdown",
                                  "fleet closed before dispatch")
            self._cv.notify_all()
        self._dispatcher.join(timeout=10.0)
        for rep in self._replicas:
            try:
                if rep.state == HEALTHY:
                    rep.send({"t": "stop"})
            except OSError:
                pass
        for rep in self._replicas:
            rep.proc.join(timeout=5.0)
            if rep.proc.is_alive():
                rep.kill()
                rep.proc.join(timeout=5.0)
        try:
            self._listener.close()
        except OSError:
            pass
        with self._cv:
            for rep in self._replicas:
                for freq in rep.unfinished():
                    self._shed_locked(
                        freq, "shutdown",
                        "fleet closed with the request in flight")
                # emptied so a trailing EOF handler sees nothing left
                # to migrate or re-shed
                rep._fid2freq = {}
            pending, self._pending_dumps = self._pending_dumps, []
        self._write_dumps(pending)   # dumps a closing race queued
        if self.lock_audit is not None:
            self.lock_audit.close()

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def replicas(self) -> List[ProcReplica]:
        return list(self._replicas)

    def replica(self, name: str) -> ProcReplica:
        reps = {r.name: r for r in self._replicas}
        if name not in reps:
            raise ValueError(f"no replica named {name!r} "
                             f"(have {sorted(reps)})")
        return reps[name]

    def breaker(self, name: str) -> CircuitBreaker:
        return self._breakers[name]

    def health(self) -> Dict:
        """Cheap liveness snapshot (no RPCs) — what the HTTP front
        door's /healthz serves. ``pools`` reports each pool's live
        membership so the front door can distinguish DEGRADED (one
        pool down, the fallback ladder still serves) from
        unavailable (nothing can serve); colocated fleets report one
        ``"any"`` pool."""
        with self._cv:
            pools: Dict[str, Dict] = {}
            for r in self._replicas:
                p = pools.setdefault(r.pool, {"replicas": [],
                                              "healthy": 0,
                                              "starting": 0})
                p["replicas"].append(r.name)
                if r.state == HEALTHY:
                    p["healthy"] += 1
                elif r.state == STARTING:
                    p["starting"] += 1
            # three-valued, mirroring the routing ladder's aliveness
            # (_pool_alive_locked counts STARTING too): "recovering"
            # = no member serves NOW but a restart is in flight, so
            # the dispatcher HOLDS that pool's work instead of
            # engaging the fallback ladder — an operator reading
            # "down" would expect the ladder (absorb/requeue/shed) to
            # be serving, which it is not during the spawn window
            for p in pools.values():
                p["state"] = ("up" if p["healthy"] > 0
                              else "recovering" if p["starting"] > 0
                              else "down")
            return {
                "replicas": {r.name: {"state": r.state,
                                      "pool": r.pool,
                                      "pid": r.pid,
                                      "steps": r.steps,
                                      "in_flight": r.in_flight,
                                      "heartbeat_age_s": round(
                                          r.hb.age_s, 3),
                                      "breaker":
                                          self._breakers[r.name].state}
                             for r in self._replicas},
                "pools": pools,
                "disaggregated": self._disagg,
                "queue_depth": len(self._queue),
                "queue_oldest_wait_s": round(
                    self._queue.oldest_wait_s(), 4),
                "open_requests": self._open,
                "draining": self._draining,
            }

    def reset_metrics(self) -> None:
        """Fresh ledgers fleet-wide (bench warmup boundary), including
        each child engine's ServeMetrics and step counter."""
        with self._cv:
            self.metrics = FleetMetrics()
            self.metrics._queue_probe = self._queue_gauges
            self._tokens_delivered = 0
        for rep in self._replicas:
            if rep.state == HEALTHY:
                rep.rpc({"t": "reset"}, timeout=60.0)
                rep.steps = 0

    def tokens_delivered(self) -> int:
        """Fleet-wide generated-token count from the dispatcher's own
        journal — exact even when replicas died mid-run (their
        engines' ledgers died with them; the journal did not). A
        running counter, not a scan: summary() must not slow down
        linearly with requests ever served."""
        with self._cv:
            return self._tokens_delivered

    def replica_stats(self) -> Dict[str, Dict]:
        """Per-LIVE-replica engine stats over the wire ({compile,
        metrics, steps, admitted}). Dead replicas' engine ledgers died
        with their process — by design; the parent-side journal and
        FleetMetrics carry everything the fleet promises to keep."""
        out: Dict[str, Dict] = {}
        for rep in self._replicas:
            if rep.state != HEALTHY:
                continue
            try:
                f = rep.rpc({"t": "stats"}, timeout=60.0)
            except (TimeoutError, OSError):
                continue
            out[rep.name] = {"compile": f["compile"],
                             "metrics": f["metrics"],
                             "steps": f["steps"],
                             "admitted": f["admitted"]}
        return out

    def summary(self) -> Dict:
        stats = self.replica_stats()
        with self._cv:
            per_replica = {
                rep.name: {
                    "state": rep.state,
                    "pool": rep.pool,
                    "pid": rep.pid,
                    "steps": rep.steps,
                    "in_flight": rep.in_flight,
                    "outstanding_tokens": rep.outstanding_tokens,
                    "breaker": self._breakers[rep.name].state,
                    "compile_counts": stats.get(rep.name, {}).get(
                        "compile"),
                } for rep in self._replicas}
        out = self.metrics.summary()
        out["policy"] = self._router.policy
        out["disaggregated"] = self._disagg
        out["replicas"] = per_replica
        out["tokens_delivered"] = self.tokens_delivered()
        out["engines"] = {name: s["metrics"]
                          for name, s in stats.items()}
        if self.slo is not None:
            out["slo"] = self.slo.status()
        return out

    def assert_compile_count(self, prefill: Optional[int] = None,
                             decode: int = 1) -> None:
        """The bounded-compile promise, accounted PER PROCESS: each
        live replica that admitted work reports its sentinel counts
        over the wire ({program: compiles}) and
        analysis.check_serving_compile_counts validates the same rules
        the thread fleet enforces on in-process sentinels."""
        from quintnet_tpu.analysis import check_serving_compile_counts

        for name, s in self.replica_stats().items():
            if s["admitted"] == 0:
                continue
            expect_decode = decode
            if self.replica(name).pool == "prefill":
                # a prefill-pool replica legitimately never runs the
                # decode program (its requests retire at the first
                # token) — but warmup() compiles it, so accept 0 OR
                # the fleet-wide expectation, never more
                observed = int(s["compile"].get("decode", 0))
                if observed in (0, decode):
                    expect_decode = observed
            check_serving_compile_counts(
                f"replica {name}", s["compile"],
                max_prefill=prefill, decode=expect_decode)

"""Bounded fleet-wide admission queue + typed load shedding.

A serving front-end that queues without bound converts overload into
unbounded latency: every request eventually "succeeds" seconds or
minutes late, which for an interactive workload is indistinguishable
from failure — except the client got no signal to back off or retry
elsewhere. The fleet therefore sheds: :class:`Overloaded` is a TYPED
rejection carrying a machine-readable ``reason``, raised

- at submit time when the pending queue is at ``max_pending``
  (``reason='queue_full'`` — the >capacity-burst signal), or when the
  fleet is draining/closed (``reason='shutdown'``);
- at dispatch time when a queued request's deadline has already
  passed (``reason='deadline'`` — serving it late would waste replica
  work the client will discard; shedding it is strictly better for
  everyone behind it in the queue).

Migration re-queues (:meth:`AdmissionQueue.push_front`) bypass the
bound: that work was already admitted once and its tokens are already
partially delivered — shedding it on re-entry would turn one replica
death into client-visible failures, which is exactly what migration
exists to prevent.

The queue is NOT internally locked: the fleet serialises all access
under its own condition lock; this class owns only the policy.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

SHED_REASONS = ("queue_full", "deadline", "shutdown", "pool_down")


class Overloaded(RuntimeError):
    """Typed rejection: the fleet refused (or abandoned) a request
    instead of queueing it forever. ``reason`` is one of
    ``queue_full`` / ``deadline`` / ``shutdown`` / ``pool_down``
    (disaggregated fleets only: the decode pool has no live member
    and every breaker is tripped — queueing would hide an outage the
    client should route around; fleet/proc.py)."""

    def __init__(self, reason: str, message: str):
        assert reason in SHED_REASONS, reason
        super().__init__(message)
        self.reason = reason


class AdmissionQueue:
    """Bounded FIFO of pending fleet requests with deadline shedding.

    Items must expose a ``deadline`` attribute (absolute fleet-clock
    time, or ``None``)."""

    def __init__(self, max_pending: int,
                 clock: Callable[[], float] = time.monotonic):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.max_pending = int(max_pending)
        self.clock = clock
        self._items: List = []

    def __len__(self) -> int:
        return len(self._items)

    @property
    def full(self) -> bool:
        return len(self._items) >= self.max_pending

    def push(self, item) -> None:
        """Append, or raise ``Overloaded('queue_full')`` at the bound."""
        if self.full:
            raise Overloaded(
                "queue_full",
                f"admission queue full ({self.max_pending} pending); "
                f"shedding instead of queueing unboundedly — retry with "
                f"backoff or raise max_pending/replicas")
        self._items.append(item)

    def push_front(self, items: List) -> None:
        """Re-queue migrated work at the head of the line (it keeps its
        place — it was admitted before anything currently pending).
        Deliberately bypasses ``max_pending``; see module docstring."""
        self._items[:0] = items

    def shed_expired(self, now: Optional[float] = None) -> List:
        """Remove and return every queued item whose deadline has
        passed (the caller rejects them with ``Overloaded('deadline')``)."""
        now = self.clock() if now is None else now
        expired = [i for i in self._items
                   if i.deadline is not None and now >= i.deadline]
        if expired:
            self._items = [i for i in self._items if i not in expired]
        return expired

    def oldest_wait_s(self, now: Optional[float] = None) -> float:
        """Wait age of the OLDEST queued item (0.0 when empty). Not
        necessarily the head: migration re-queues push_front younger
        work past older arrivals, so this scans ``submit_time`` across
        the queue. The overload signal the pressure plane samples and
        the front door's 429 Retry-After hints with — queue DEPTH says
        how much is waiting, wait AGE says how badly."""
        items = list(self._items)
        if not items:
            return 0.0
        now = self.clock() if now is None else now
        oldest = min(getattr(i, "submit_time", now) for i in items)
        return max(now - oldest, 0.0)

    def peek_adapter_id(self) -> Optional[str]:
        """The queue head's LoRA binding (or None) — the dispatcher
        reads it before :meth:`pop` so the router can apply adapter
        affinity to the request it is about to place."""
        if not self._items:
            return None
        return getattr(self._items[0], "adapter_id", None)

    def pop(self):
        """Head of the line, or None."""
        return self._items.pop(0) if self._items else None

    def items(self) -> List:
        """Queue contents in order (a read-only view for the
        disaggregated dispatcher, which must skip past a head it has
        no pool for — a decode-phase request waiting on its pool must
        not block a prefill-phase request behind it)."""
        return list(self._items)

    def remove(self, item) -> None:
        """Take one specific item out of line (the disaggregated
        dispatcher claims the first DISPATCHABLE item, not
        necessarily the head)."""
        self._items.remove(item)

    def drain_all(self) -> List:
        """Empty the queue (shutdown path); returns what was pending."""
        items, self._items = self._items, []
        return items

"""Per-replica health state + circuit breaker + heartbeat/backoff
policy.

A replica is either serving (``HEALTHY``), dead with its worker thread
exited on an error (``DEAD``), or cleanly shut down (``STOPPED``).
Process replicas (fleet/proc.py) add two states a thread can't be in:
``STARTING`` (spawned, engine still building — not a dispatch
candidate until its hello lands) and ``STALLED`` (the process is alive
and its socket open, but heartbeats stopped — a wedge, detected by
:class:`HeartbeatMonitor`, handled like a death EXCEPT the supervisor
must also kill the zombie before restarting).
Whether a DEAD replica gets restarted is the :class:`CircuitBreaker`'s
call — the classic three-state breaker (Nygard, *Release It!*):

- **closed**: failures below the trip threshold; every death is
  followed by an immediate restart (transient faults are expected —
  a preempted core, an injected chaos kill);
- **open**: ``trip_after`` CONSECUTIVE failures tripped the breaker;
  no restarts until ``reset_s`` has elapsed, so a hard-broken replica
  (bad device, poisoned params) cannot crash-loop and drag the fleet's
  dispatcher into endless migration churn;
- **half-open**: the cool-down elapsed; exactly ONE probe restart is
  allowed. The probe replica completing a request closes the breaker
  (fleet calls :meth:`record_success` on every finish); dying again
  re-opens it for another full ``reset_s``.

The breaker never touches threads itself — it is pure policy, driven
by the fleet's dispatcher under the fleet lock, with an injectable
clock so tests advance time without sleeping.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from quintnet_tpu.fleet.retry import RetryPolicy

# replica lifecycle states (Replica.state / ProcReplica.state)
HEALTHY = "healthy"
DEAD = "dead"
STOPPED = "stopped"
STARTING = "starting"   # process spawned, hello not yet received
STALLED = "stalled"     # alive but not heartbeating (wedged process)

# breaker states (CircuitBreaker.state)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure trip with a timed half-open probe."""

    def __init__(self, *, trip_after: int = 3, reset_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if trip_after < 1:
            raise ValueError(f"trip_after must be >= 1, got {trip_after}")
        self.trip_after = int(trip_after)
        self.reset_s = float(reset_s)
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self._opened_at: Optional[float] = None

    def record_failure(self) -> None:
        """One replica death. A half-open probe dying re-opens
        immediately; otherwise the trip threshold decides."""
        self.consecutive_failures += 1
        if (self.state == HALF_OPEN
                or self.consecutive_failures >= self.trip_after):
            self.state = OPEN
            self._opened_at = self.clock()

    def record_success(self) -> None:
        """The replica completed a request: whatever tripped it is
        gone; full reset."""
        self.consecutive_failures = 0
        self.state = CLOSED
        self._opened_at = None

    def allow_restart(self) -> bool:
        """May the fleet restart the dead replica NOW? closed → always;
        open → only once ``reset_s`` has elapsed (transitions to
        half-open and grants the single probe); half-open → no (the
        probe is already out)."""
        if self.state == CLOSED:
            return True
        if self.state == HALF_OPEN:
            return False
        if self.clock() - self._opened_at >= self.reset_s:
            self.state = HALF_OPEN
            return True
        return False

    @property
    def restart_conceivable(self) -> bool:
        """Read-only: could a restart be granted now or soon WITHOUT
        driving the state machine (``allow_restart`` transitions to
        half-open as a side effect — unusable as a pure query)?
        False exactly when the breaker is OPEN inside its cool-down or
        a half-open probe is already out — the window the
        disaggregated fleet's degradation ladder (fleet/proc.py)
        treats a pool as hard-down and sheds typed instead of
        queueing behind a breaker that cannot act."""
        if self.state == CLOSED:
            return True
        if self.state == HALF_OPEN:
            return False
        return self.clock() - self._opened_at >= self.reset_s


class HeartbeatMonitor:
    """Liveness by heartbeat age, the ONLY wedge detector that needs no
    cooperation from the wedged side: a process that SIGKILLs shows an
    EOF on its socket, but a process that merely stops making progress
    (deadlocked GIL, runaway compile, swapped-out host) keeps its
    socket open and looks healthy to everything except the absence of
    heartbeats. ``budget_s`` is the detection SLA: a replica whose last
    beat is older than the budget is declared stalled and routed
    around (fleet/proc.py). The clock is injectable so tests advance
    time without sleeping."""

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        if budget_s <= 0:
            raise ValueError(f"budget_s must be > 0, got {budget_s}")
        self.budget_s = float(budget_s)
        self.clock = clock
        self.last_beat = clock()   # spawn counts as the first beat

    def beat(self) -> None:
        self.last_beat = self.clock()

    @property
    def age_s(self) -> float:
        return self.clock() - self.last_beat

    @property
    def expired(self) -> bool:
        return self.age_s > self.budget_s


class Backoff(RetryPolicy):
    """Jittered exponential restart backoff (the ft_run supervisor's
    relaunch discipline, made policy): attempt ``n`` (1-based) waits
    ``base * 2^(n-1)`` capped at ``cap``, times a jitter factor in
    ``[1, 1+jitter]`` so N replicas felled by one cause do not
    restart — and re-fail — in lockstep. ``rand`` is injectable for
    deterministic tests.

    The math now lives in the shared
    :class:`~quintnet_tpu.fleet.retry.RetryPolicy` (the KV-handoff
    retry loop of the disaggregated fleet uses the same envelope);
    this subclass keeps the restart-flavored name and its original
    delay-only constructor."""

    def __init__(self, *, base_s: float = 0.05, cap_s: float = 5.0,
                 jitter: float = 0.25,
                 rand: Optional[Callable[[], float]] = None):
        super().__init__(base_s=base_s, cap_s=cap_s, jitter=jitter,
                         rand=rand)

"""Per-replica health state + circuit breaker.

A replica is either serving (``HEALTHY``), dead with its worker thread
exited on an error (``DEAD``), or cleanly shut down (``STOPPED``).
Whether a DEAD replica gets restarted is the :class:`CircuitBreaker`'s
call — the classic three-state breaker (Nygard, *Release It!*):

- **closed**: failures below the trip threshold; every death is
  followed by an immediate restart (transient faults are expected —
  a preempted core, an injected chaos kill);
- **open**: ``trip_after`` CONSECUTIVE failures tripped the breaker;
  no restarts until ``reset_s`` has elapsed, so a hard-broken replica
  (bad device, poisoned params) cannot crash-loop and drag the fleet's
  dispatcher into endless migration churn;
- **half-open**: the cool-down elapsed; exactly ONE probe restart is
  allowed. The probe replica completing a request closes the breaker
  (fleet calls :meth:`record_success` on every finish); dying again
  re-opens it for another full ``reset_s``.

The breaker never touches threads itself — it is pure policy, driven
by the fleet's dispatcher under the fleet lock, with an injectable
clock so tests advance time without sleeping.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

# replica lifecycle states (Replica.state)
HEALTHY = "healthy"
DEAD = "dead"
STOPPED = "stopped"

# breaker states (CircuitBreaker.state)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure trip with a timed half-open probe."""

    def __init__(self, *, trip_after: int = 3, reset_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if trip_after < 1:
            raise ValueError(f"trip_after must be >= 1, got {trip_after}")
        self.trip_after = int(trip_after)
        self.reset_s = float(reset_s)
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self._opened_at: Optional[float] = None

    def record_failure(self) -> None:
        """One replica death. A half-open probe dying re-opens
        immediately; otherwise the trip threshold decides."""
        self.consecutive_failures += 1
        if (self.state == HALF_OPEN
                or self.consecutive_failures >= self.trip_after):
            self.state = OPEN
            self._opened_at = self.clock()

    def record_success(self) -> None:
        """The replica completed a request: whatever tripped it is
        gone; full reset."""
        self.consecutive_failures = 0
        self.state = CLOSED
        self._opened_at = None

    def allow_restart(self) -> bool:
        """May the fleet restart the dead replica NOW? closed → always;
        open → only once ``reset_s`` has elapsed (transitions to
        half-open and grants the single probe); half-open → no (the
        probe is already out)."""
        if self.state == CLOSED:
            return True
        if self.state == HALF_OPEN:
            return False
        if self.clock() - self._opened_at >= self.reset_s:
            self.state = HALF_OPEN
            return True
        return False

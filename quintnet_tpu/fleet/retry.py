"""Shared retry policy: jittered exponential backoff with a cap, an
attempt bound, and injectable randomness/clock/sleep.

One discipline, two very different consumers:

- **replica restarts** (fleet/proc.py supervision): the original
  ``Backoff`` (fleet/health.py, now a thin alias over this class) only
  ever needed ``delay_s`` — the fleet's dispatcher owns the schedule
  and the breaker owns the permission;
- **the KV handoff** (disaggregated prefill/decode pools,
  fleet/proc.py): a bounded retry LOOP around an RPC pair that can
  fail transiently (receiver busy, checksum-corrupt frame, socket
  reset) or permanently (the source replica died and its chain with
  it). :meth:`run` owns the loop: call, catch the retryable types,
  sleep the jittered delay, try again — and re-raise the LAST error
  once attempts (or the optional wall-clock ``timeout_s``) are
  exhausted, so the caller's fallback (local re-prefill — slower,
  never wrong) fires with the real cause in hand.

The jitter envelope is pinned: attempt ``n`` (1-based) waits
``min(base_s * 2^(n-1), cap_s) * u`` with ``u`` uniform in
``[1, 1 + jitter]`` — N replicas (or N handoffs) felled by one cause
do not retry, and re-fail, in lockstep. ``rand``, ``clock`` and
``sleep`` are injectable so tests pin the envelope and determinism
without wall time.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Tuple, Type


class RetryPolicy:
    """Jittered exponential retry/backoff policy (see module docstring).

    ``max_attempts`` bounds :meth:`run` (delay-only users ignore it);
    ``timeout_s``, when set, additionally stops retrying once the
    total wall clock spent inside :meth:`run` exceeds it — a handoff
    must not out-wait the request it is trying to accelerate."""

    def __init__(self, *, base_s: float = 0.05, cap_s: float = 5.0,
                 jitter: float = 0.25, max_attempts: int = 3,
                 timeout_s: Optional[float] = None,
                 rand: Optional[Callable[[], float]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        import random

        if base_s < 0 or cap_s < 0:
            raise ValueError(
                f"base_s/cap_s must be >= 0, got {base_s}/{cap_s}")
        if jitter < 0:
            raise ValueError(f"jitter must be >= 0, got {jitter}")
        if max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {max_attempts}")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.jitter = float(jitter)
        self.max_attempts = int(max_attempts)
        self.timeout_s = None if timeout_s is None else float(timeout_s)
        self.rand = rand if rand is not None else random.random
        self.clock = clock
        self.sleep = sleep

    def bounded(self, timeout_s: float) -> "RetryPolicy":
        """A copy of this policy whose wall-clock budget is tightened
        to ``min(self.timeout_s, timeout_s)`` (injected rand/clock/
        sleep shared). The KV handoff derives this from the request's
        REMAINING deadline: a transfer must not out-wait the request
        it is trying to accelerate."""
        cap = (float(timeout_s) if self.timeout_s is None
               else min(self.timeout_s, float(timeout_s)))
        return RetryPolicy(base_s=self.base_s, cap_s=self.cap_s,
                           jitter=self.jitter,
                           max_attempts=self.max_attempts,
                           timeout_s=cap, rand=self.rand,
                           clock=self.clock, sleep=self.sleep)

    def delay_s(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based): raw exponential
        capped at ``cap_s``, times a jitter factor in
        ``[1, 1 + jitter]``."""
        raw = min(self.base_s * (2 ** max(attempt - 1, 0)), self.cap_s)
        return raw * (1.0 + self.jitter * self.rand())

    def run(self, fn: Callable[[int], "object"], *,
            retry_on: Tuple[Type[BaseException], ...] = (Exception,),
            on_retry: Optional[Callable] = None):
        """Call ``fn(attempt)`` up to ``max_attempts`` times, sleeping
        the jittered delay between failures. Only exceptions matching
        ``retry_on`` are retried — anything else propagates
        immediately (a programming error must not be masked by
        retries). ``on_retry(attempt, error)`` fires before each
        re-attempt's sleep (the caller's metrics/obs hook). Exhaustion
        — by attempt count or ``timeout_s`` — re-raises the LAST
        retryable error."""
        deadline = (None if self.timeout_s is None
                    else self.clock() + self.timeout_s)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(attempt)
            except retry_on as e:
                out_of_attempts = attempt >= self.max_attempts
                out_of_time = (deadline is not None
                               and self.clock() >= deadline)
                if out_of_attempts or out_of_time:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self.sleep(self.delay_s(attempt))

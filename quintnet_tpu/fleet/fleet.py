"""ServeFleet: N ServeEngine replicas behind one submit/stream API.

One continuous-batching engine (serve/engine.py) saturates at
``max_slots`` concurrent requests; the fleet multiplexes a request
stream over N replica engines on worker threads — the AlpaServe
observation that replicated capacity with statistical multiplexing,
not one bigger replica, is what holds tail latency under bursty
traffic. The pieces:

- **routing** (fleet/router.py): least-outstanding-work by token count
  (or round_robin), over replicas that are healthy, unpaused, and
  below their dispatch window — with a cheap adapter-affinity
  pre-filter for LoRA-bound requests (prefer replicas whose registry
  already holds the adapter resident, serve/adapters.py);
- **admission** (fleet/admission.py): a bounded fleet-wide queue;
  overload and expired deadlines shed with a typed
  :class:`~quintnet_tpu.fleet.admission.Overloaded` instead of
  queueing forever;
- **health** (fleet/health.py): per-replica circuit breaker —
  consecutive-failure trip, timed half-open probe — deciding whether
  a dead replica is restarted (fresh engine from the factory);
- **migration** (fleet/replica.py + serve/engine.py): a replica that
  dies mid-flight exports every unfinished request's host-side
  progress (prompt, generated, evolved PRNG key — the engine's own
  preemption-resume contract); the fleet re-queues it AT THE FRONT and
  a healthy replica resumes it via ``engine.restore_progress``,
  token-identical to an undisturbed run;
- **drain**: graceful shutdown — refuse new work, finish everything
  accepted, then stop the threads.

All replicas must be built from the SAME (family, params) — the
factory is called once per replica (and per restart); migration
correctness rests on that equivalence.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from quintnet_tpu.analysis import assert_compile_count as _assert_cc
from quintnet_tpu.fleet.admission import AdmissionQueue, Overloaded
from quintnet_tpu.fleet.health import (CLOSED, DEAD, HEALTHY,
                                       CircuitBreaker)
from quintnet_tpu.fleet.replica import Replica
from quintnet_tpu.fleet.router import Router
from quintnet_tpu.fleet.router import eligible as router_eligible
from quintnet_tpu.serve import metrics as serve_metrics


class FleetRequest:
    """One request's fleet-side life: payload, result slot, marks."""

    def __init__(self, fid: int, prompt, max_new_tokens: int, *, key,
                 priority: int, deadline: Optional[float], on_token,
                 submit_time: float, clock, adapter_id=None,
                 trace_id=None):
        self.fid = fid
        # observability identity (quintnet_tpu/obs/): one id per
        # request across the whole fleet — every engine that serves
        # (or resumes) it records spans under this id. Inert metadata.
        self.trace_id = trace_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.key = key
        self.priority = priority
        self.deadline = deadline          # absolute fleet-clock time
        self.on_token = on_token
        self.submit_time = submit_time
        self.adapter_id = adapter_id      # LoRA binding (None = base)
        self._clock = clock

        self.progress = None              # RequestProgress after a death
        self.migrations = 0
        self.cost = 0                     # outstanding-token estimate
        self.replica_name: Optional[str] = None
        # disaggregated-fleet state (fleet/proc.py): what KIND of
        # dispatch this request last got ("prefill" = prefill-pool
        # prefill-only; "full" = run to completion), and — after a
        # successful KV handoff — which decode replica holds the
        # imported chain (a routing PREFERENCE: landing elsewhere
        # re-prefills locally, slower but identical)
        self.dispatched_phase: Optional[str] = None
        self.warm_replica: Optional[str] = None
        self.first_token_time: Optional[float] = None
        # dispatcher-clock timestamp of the LATEST token — the SLO
        # engine's inter-token-latency anchor (fleet/proc.py). Reset
        # to None across a handoff or migration: the cross-replica
        # gap is a TTFT-class cost charged to the handoff signals,
        # not a decode-cadence violation
        self.last_token_time: Optional[float] = None
        # the thread fleet's SLO feed (obs/slo.py): ServeFleet binds
        # its engine here at submit so :meth:`deliver` — which runs on
        # the replica worker, the thread fleet's client-visible
        # delivery point — observes TTFT/ITL. The process fleet leaves
        # it None and observes at ITS delivery point, the dispatcher
        # (fleet/proc.py _deliver_token): one observation per token
        # either way, taken where the client actually sees it
        self.slo = None
        self.finish_time: Optional[float] = None
        self.output: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()
        # the dispatcher-side WRITE-AHEAD token journal: every token a
        # replica streams is recorded here BEFORE the user callback
        # sees it. For the process fleet this journal IS the migration
        # source — a SIGKILL'd replica cannot be asked to export, but
        # prompt + journal + (submit key advanced one split per
        # journaled token) reconstructs its RequestProgress exactly
        # (fleet/proc.py). The thread fleet keeps it for uniformity
        # (its migration path uses the engine's own export).
        self.committed: List[int] = []
        self.last_seen = False            # a token arrived with is_last

    def deliver(self, token: int, last: bool) -> None:
        """Worker-thread token delivery (streaming surface). Journals
        first (write-ahead), then forwards. Tokens survive migration
        without duplication: a resumed request only emits tokens
        generated AFTER its checkpoint."""
        self.committed.append(int(token))
        if last:
            self.last_seen = True
        first = self.first_token_time is None
        if first:
            self.first_token_time = self._clock()
        if self.slo is not None:
            now = self._clock()
            if first:
                self.slo.observe("ttft", now - self.submit_time)
            elif self.last_token_time is not None:
                self.slo.observe("itl", now - self.last_token_time)
            self.last_token_time = now
        if self.on_token is not None:
            try:
                self.on_token(self.fid, token, last)
            except Exception:  # noqa: BLE001
                # a client callback failing (an SSE writer whose event
                # loop closed, a buggy consumer) must never propagate
                # into the replica worker and read as a replica death
                pass

    def remaining_deadline(self) -> Optional[float]:
        """Seconds of deadline budget left on the fleet clock (None =
        no deadline). The dispatcher re-anchors this on a replica
        engine's own clock at ingest — absolute readings do not
        transfer between clocks (or processes)."""
        if self.deadline is None:
            return None
        return self.deadline - self._clock()

    def outstanding_cost(self) -> int:
        """Tokens of work still owed: the (re-)prefill plus remaining
        decode steps — what least_work routing charges the replica.
        Identical for fresh and migrated requests: a migration
        re-prefills prompt+generated, so the generated tokens move
        from the decode column to the prefill column and the total is
        unchanged."""
        return len(self.prompt) + self.max_new_tokens


@dataclass
class FleetMetrics:
    """Fleet-front-door counters + latency marks (fleet clock: queue
    wait INCLUDED, unlike the per-engine ServeMetrics TTFT)."""

    submitted: int = 0                  # all attempts, incl. rejected
    accepted: int = 0
    finished: int = 0
    shed_queue_full: int = 0
    shed_deadline: int = 0
    shed_shutdown: int = 0
    # disaggregated fleets only: decode pool hard-down (no live
    # member, every breaker tripped) — new work shed typed instead of
    # queueing behind a breaker that cannot act (fleet/proc.py)
    shed_pool_down: int = 0
    # admitted requests retired MID-GENERATION at their deadline
    # (typed serve.DeadlineExceeded) — disjoint from shed_deadline,
    # which counts requests still QUEUED at expiry
    deadline_exceeded: int = 0
    migrations: int = 0
    replica_deaths: int = 0
    stalls: int = 0                     # missed-heartbeat detections
    restarts: int = 0
    # disaggregated prefill→decode handoffs (fleet/proc.py):
    # ``handoffs`` counts prefill-phase completions that moved to the
    # decode pool; ``handoff_transfers`` the KV chains that actually
    # landed (wire frame imported, checksum good); ``handoff_retries``
    # every retried transfer attempt; ``handoff_fallbacks`` transfers
    # that exhausted retries and fell back to local re-prefill on the
    # decode side (slower, token-identical — the chain is just cache)
    handoffs: int = 0
    handoff_transfers: int = 0
    handoff_retries: int = 0
    handoff_fallbacks: int = 0
    # tiered-KV peer lookup (serve/kv_tier.py, fleet/proc.py):
    # ``tier_probes`` counts dispatches that ran the kv_peek fan-out;
    # ``tier_peer_transfers`` chains actually shipped peer->target
    # before dispatch; ``tier_peer_fallbacks`` probes where a better
    # peer existed but the transfer degraded (export/import failed) —
    # dispatch proceeded without warm peer KV, token-identical
    tier_probes: int = 0
    tier_peer_transfers: int = 0
    tier_peer_fallbacks: int = 0
    # admission-queue pressure gauges, refreshed through the probe the
    # owning fleet attaches (the metrics object cannot see the queue):
    # depth says how much is waiting, oldest-wait age how badly —
    # summary() carries both so /metrics and the signal bus read one
    # ledger, not two
    queue_depth: int = 0
    queue_oldest_wait_s: float = 0.0
    _queue_probe: Optional[Callable] = None
    # percentile sources, reservoir-bounded like the engine's
    # (serve/metrics.Reservoir): exact below the cap, uniform sampling
    # above — a long-lived front door stops leaking one float per
    # request; summary() surfaces the true count as "n"
    ttfts: "serve_metrics.Reservoir" = field(
        default_factory=serve_metrics.Reservoir)
    latencies: "serve_metrics.Reservoir" = field(
        default_factory=serve_metrics.Reservoir)

    @property
    def shed(self) -> int:
        return (self.shed_queue_full + self.shed_deadline
                + self.shed_shutdown + self.shed_pool_down)

    @property
    def shed_rate(self) -> float:
        return self.shed / max(self.submitted, 1)

    def summary(self) -> Dict:
        if self._queue_probe is not None:
            depth, age = self._queue_probe()
            self.queue_depth = int(depth)
            self.queue_oldest_wait_s = float(age)
        return {
            "submitted": self.submitted,
            "accepted": self.accepted,
            "finished": self.finished,
            "queue_depth": self.queue_depth,
            "queue_oldest_wait_s": round(self.queue_oldest_wait_s, 4),
            "shed": self.shed,
            "shed_queue_full": self.shed_queue_full,
            "shed_deadline": self.shed_deadline,
            "shed_shutdown": self.shed_shutdown,
            "shed_pool_down": self.shed_pool_down,
            "shed_rate": round(self.shed_rate, 4),
            "deadline_exceeded": self.deadline_exceeded,
            "migrations": self.migrations,
            "replica_deaths": self.replica_deaths,
            "stalls": self.stalls,
            "restarts": self.restarts,
            "handoffs": self.handoffs,
            "handoff_transfers": self.handoff_transfers,
            "handoff_retries": self.handoff_retries,
            "handoff_fallbacks": self.handoff_fallbacks,
            "tier_probes": self.tier_probes,
            "tier_peer_transfers": self.tier_peer_transfers,
            "tier_peer_fallbacks": self.tier_peer_fallbacks,
            "ttft_s": serve_metrics._pcts(self.ttfts),
            "latency_s": serve_metrics._pcts(self.latencies),
        }


class ServeFleet:
    """Multi-replica serving front-end (see module docstring).

    ``engine_factory``: zero-arg callable returning a fresh
    :class:`~quintnet_tpu.serve.engine.ServeEngine`; called once per
    replica and once per breaker-approved restart. ``chaos``: one
    ``ft.ChaosMonkey`` (mode='raise') or a sequence; each is armed
    against the replica named by its ``target`` (default: replica 0).
    """

    def __init__(self, engine_factory: Callable, *, n_replicas: int = 2,
                 policy: str = "least_work", max_pending: int = 64,
                 max_dispatch: Optional[int] = None,
                 trip_after: int = 3, breaker_reset_s: float = 30.0,
                 chaos=None, clock: Callable[[], float] = time.monotonic,
                 name_prefix: str = "r", poll_s: float = 0.02,
                 obs: bool = False, crash_dir: Optional[str] = None,
                 ring_capacity: int = 512, slo=None,
                 lock_audit: bool = False):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self._factory = engine_factory
        self.clock = clock
        self.metrics = FleetMetrics()
        # lock-discipline runtime (analysis/lockrt.py): lock_audit=True
        # swaps every lock this fleet mints for an InstrumentedLock
        # sharing ONE order graph + ledger registry, so an A→B/B→A
        # inversion anywhere in the fleet raises a typed LockOrderError
        # instead of deadlocking, and GET /metrics grows the
        # quintnet_lock_* families. Off (the default) the locks are the
        # stock threading primitives — byte-identical behavior.
        self.lock_audit = None
        if lock_audit:
            from quintnet_tpu.analysis.lockrt import LockAudit

            self.lock_audit = LockAudit(
                clock=clock,
                on_violation=lambda info: self._emit(
                    "lock_order_violation", **info))
        # observability (quintnet_tpu/obs/): ``obs=True`` arms ONE
        # fleet-wide Tracer (engines share the address space, so every
        # replica engine records into it directly — one merged
        # timeline per trace id), a per-engine StepRecorder ring, and
        # the typed EventLog. On a replica death the affected ring +
        # spans become an in-memory post-mortem (``last_crash``) and,
        # with ``crash_dir`` set, a crash-dump file. All of it is
        # inert: tracing on is token-bit-identical to tracing off.
        # The SLO engine + signal bus (obs/slo.py, obs/signals.py)
        # read the engine step rings, so ``slo=`` implies ``obs=True``.
        self._obs = bool(obs) or slo is not None
        self.crash_dir = crash_dir
        self._ring_capacity = int(ring_capacity)
        self.tracer = None
        self.events = None
        self.slo = None            # obs.SLOEngine once armed
        self.signals = None        # obs.SignalBus once armed
        self.planner = None        # always None here: rebalancing
        #   moves replicas BETWEEN pools and the thread fleet has none
        #   (ProcessFleet(pools=...) is the planner's home)
        self._signal_next_t = 0.0
        if self._obs:
            from quintnet_tpu.obs import EventLog, Tracer

            self.tracer = Tracer(clock=clock,
                                 lock=self._audit_lock("obs.tracer"))
            self.events = EventLog(clock=clock,
                                   lock=self._audit_lock("obs.events"))
        self.crash_dumps: List[str] = []     # paths written (crash_dir)
        self.last_crash: Optional[Dict] = None
        self._pending_dumps: List[Dict] = []  # snapshotted under the
        #   lock at death; WRITTEN by the dispatcher outside it — a
        #   disk write must never stall token delivery
        self._breaker_seen: Dict[str, str] = {}
        self._router = Router(policy)
        # threading.Condition()'s default lock IS an RLock — the
        # audited swap must preserve reentrancy (audit.condition)
        self._cv = (self.lock_audit.condition("fleet._cv")
                    if self.lock_audit is not None
                    else threading.Condition())
        self._queue = AdmissionQueue(max_pending, clock=clock)
        self.metrics._queue_probe = self._queue_gauges
        if slo is not None:
            self.arm_slo(slo)
        self._requests: Dict[int, FleetRequest] = {}
        self._fid_counter = 0
        self._open = 0                 # accepted, not yet finished/shed
        self._draining = False
        self._closed = False
        self._max_dispatch = max_dispatch
        self._poll_s = poll_s
        self._retired_metrics: List = []   # ServeMetrics of dead engines

        monkeys = [] if chaos is None else (
            list(chaos) if isinstance(chaos, (list, tuple)) else [chaos])
        names = [f"{name_prefix}{i}" for i in range(n_replicas)]
        by_target = {}
        for m in monkeys:
            by_target[m.target if m.target is not None else names[0]] = m
        unknown = set(by_target) - set(names)
        if unknown:
            raise ValueError(
                f"chaos target(s) {sorted(unknown)} name no replica "
                f"(have {names})")

        self._breakers = {
            name: CircuitBreaker(trip_after=trip_after,
                                 reset_s=breaker_reset_s, clock=clock)
            for name in names}
        self._replicas = [self._spawn(name, by_target.get(name))
                          for name in names]
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="fleet-dispatch", daemon=True)
        self._dispatcher.start()

    def _audit_lock(self, name: str):
        """An instrumented Lock under ``lock_audit=True``, else None
        (the primitive constructors fall back to a stock Lock — the
        off path constructs exactly what it always did)."""
        return (self.lock_audit.lock(name)
                if self.lock_audit is not None else None)

    def _spawn(self, name: str, chaos) -> Replica:
        rep = Replica(name, self._factory, chaos=chaos,
                      max_dispatch=self._max_dispatch,
                      on_finish=self._on_finish, on_death=self._on_death,
                      on_reject=self._on_reject, poll_s=self._poll_s)
        if self._obs:
            from quintnet_tpu.obs import StepRecorder

            # shared tracer (one address space, one merged timeline);
            # per-engine flight-recorder ring (the replica's black box)
            rep.engine.tracer = self.tracer
            rep.engine.recorder = StepRecorder(
                capacity=self._ring_capacity, clock=rep.engine.clock,
                lock=self._audit_lock(f"recorder.{name}"))
        return rep

    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(kind, **fields)

    def _note_breaker(self, name: str) -> None:
        """Emit a typed event when a breaker's state CHANGED since the
        fleet last looked — transitions are driven from several sites
        (failure, success, restart gating), so the edge detection
        lives here instead of inside the breaker."""
        if self.events is None:
            return
        st = self._breakers[name].state
        if self._breaker_seen.get(name, "closed") != st:
            self._breaker_seen[name] = st
            self.events.emit("breaker", replica=name, state=st)

    # ------------------------------------------------------------------
    # submission / results
    # ------------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *, key=None,
               priority: int = 0, deadline_s: Optional[float] = None,
               on_token=None, adapter_id: Optional[str] = None) -> int:
        """Queue one request fleet-wide; returns its fleet id. Raises
        :class:`Overloaded` instead of queueing when the fleet is over
        capacity (``queue_full``), the deadline is unmeetable
        (``deadline``), or the fleet is draining (``shutdown``).

        ``key`` defaults to ``fold_in(key(0), fid)`` — fleet-level, so
        a request's sampled output does not depend on which replica
        serves it. ``deadline_s`` is a whole-request budget from now,
        enforced end to end: a request still queued when it expires is
        shed (``Overloaded('deadline')``), and one already DECODING at
        expiry is retired by its engine with a typed
        ``serve.DeadlineExceeded`` (blocks published) instead of
        finishing a stream the client stopped waiting for.
        ``on_token(fid, token, is_last)`` fires from a replica worker
        thread as tokens are produced, across migrations, each token
        exactly once. ``adapter_id``: serve through the named LoRA
        adapter (serve/adapters.py) — the router prefers replicas
        where the adapter is already resident; the binding survives
        migration (a cold replica loads it on demand)."""
        import jax

        # requests the fleet could NEVER run fail fast here, like
        # engine.submit would — dispatched, they would bounce off every
        # replica's validation instead (all engines share one config,
        # so replica 0's limits speak for the fleet)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        self._replicas[0].engine._check_admissible(
            prompt, int(max_new_tokens))
        if adapter_id is not None:
            # registration check only — deliberately NOT
            # validate_adapter, which would LOAD the weights into
            # replica 0's registry as a side effect (skewing the
            # router's affinity toward r0 and churning its LRU for
            # requests that route elsewhere). Shape problems surface
            # at the serving replica's ingest, which errors that
            # request alone (_on_reject), never the replica.
            reg = getattr(self._replicas[0].engine, "adapters", None)
            if reg is None:
                raise ValueError(
                    "this fleet's engines were built without adapters; "
                    "cannot serve adapter_id requests")
            reg.entry(adapter_id)      # KeyError for unknown ids
        with self._cv:
            self.metrics.submitted += 1
            if self._draining or self._closed:
                self.metrics.shed_shutdown += 1
                self._slo_observe("shed", 1.0)
                raise Overloaded(
                    "shutdown", "fleet is draining; not accepting work")
            now = self.clock()
            if deadline_s is not None and deadline_s <= 0:
                self.metrics.shed_deadline += 1
                self._slo_observe("shed", 1.0)
                raise Overloaded(
                    "deadline", f"deadline_s={deadline_s} already expired "
                    f"at submit")
            fid = self._fid_counter
            self._fid_counter += 1
            if key is None:
                key = jax.random.fold_in(jax.random.key(0), fid)
            freq = FleetRequest(
                fid, prompt, int(max_new_tokens), key=key,
                priority=int(priority),
                deadline=(None if deadline_s is None
                          else now + float(deadline_s)),
                on_token=on_token, submit_time=now, clock=self.clock,
                adapter_id=adapter_id, trace_id=f"f{fid}")
            freq.slo = self.slo    # TTFT/ITL observed at delivery
            #   (FleetRequest.deliver — the thread fleet's client-
            #   visible point; None when the engine is not armed)
            if self.tracer is not None:
                self.tracer.event(freq.trace_id, "fleet_submit",
                                  fid=fid, prompt_len=int(prompt.size),
                                  max_new_tokens=int(max_new_tokens),
                                  adapter_id=adapter_id)
            try:
                self._queue.push(freq)
            except Overloaded:
                self.metrics.shed_queue_full += 1
                self._slo_observe("shed", 1.0)
                raise
            self._requests[fid] = freq
            self._open += 1
            self.metrics.accepted += 1
            self._slo_observe("shed", 0.0)
            self._cv.notify_all()
            return fid

    def result(self, fid: int, *, timeout: Optional[float] = None
               ) -> np.ndarray:
        """Block until the request finishes; returns prompt+generated.
        Raises the request's typed error if it was shed."""
        freq = self._requests[fid]
        if not freq.event.wait(timeout):
            raise TimeoutError(
                f"fleet request {fid} unfinished after {timeout}s "
                f"(replica={freq.replica_name}, "
                f"migrations={freq.migrations})")
        if freq.error is not None:
            raise freq.error
        return freq.output

    def request(self, fid: int) -> FleetRequest:
        return self._requests[fid]

    def generate(self, prompts: Sequence, *, max_new_tokens, keys=None,
                 priorities=None, timeout: Optional[float] = None
                 ) -> List[np.ndarray]:
        """Blocking batch surface over the whole fleet (the analogue of
        serve.api.generate). Sheds propagate as Overloaded."""
        n = len(prompts)
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * n
        keys = [None] * n if keys is None else keys
        priorities = [0] * n if priorities is None else priorities
        if not (len(max_new_tokens) == len(keys) == len(priorities) == n):
            raise ValueError(
                "per-prompt argument lengths must match prompts")
        fids = [self.submit(p, m, key=k, priority=pr)
                for p, m, k, pr in zip(prompts, max_new_tokens, keys,
                                       priorities)]
        return [self.result(f, timeout=timeout) for f in fids]

    # ------------------------------------------------------------------
    # worker callbacks (replica threads)
    # ------------------------------------------------------------------
    def _on_finish(self, rep: Replica, freq: FleetRequest,
                   output: np.ndarray) -> None:
        with self._cv:
            rep.in_flight -= 1
            rep.outstanding_tokens -= freq.cost
            self._breakers[rep.name].record_success()
            self._note_breaker(rep.name)
            freq.output = output
            freq.finish_time = self.clock()
            self.metrics.finished += 1
            self._slo_observe("error", 0.0)
            if freq.first_token_time is not None:
                self.metrics.ttfts.append(
                    freq.first_token_time - freq.submit_time)
            self.metrics.latencies.append(
                freq.finish_time - freq.submit_time)
            self._open -= 1
            freq.event.set()
            self._cv.notify_all()

    def _on_reject(self, rep: Replica, freq: FleetRequest,
                   error: BaseException) -> None:
        """A request the engine refused at ingest (ValueError from its
        submit/restore validation) or retired with a typed terminal
        error (DeadlineExceeded mid-decode, Overloaded('deadline') at
        ingest): error that request's waiter; the replica stays
        healthy."""
        from quintnet_tpu.serve.scheduler import DeadlineExceeded

        with self._cv:
            rep.in_flight -= 1
            rep.outstanding_tokens -= freq.cost
            if isinstance(error, DeadlineExceeded):
                self.metrics.deadline_exceeded += 1
                self._emit("deadline_exceeded", fid=freq.fid,
                           trace_id=freq.trace_id, replica=rep.name,
                           generated=error.generated)
            elif (isinstance(error, Overloaded)
                    and error.reason == "deadline"):
                self.metrics.shed_deadline += 1
            freq.error = error
            self._slo_observe("error", 1.0)
            self._open -= 1
            freq.event.set()
            self._cv.notify_all()

    def _on_death(self, rep: Replica, error: BaseException,
                  exports: List) -> None:
        with self._cv:
            self.metrics.replica_deaths += 1
            self._breakers[rep.name].record_failure()
            self._note_breaker(rep.name)
            self._retired_metrics.append(rep.engine.metrics)
            rep.in_flight = 0
            rep.outstanding_tokens = 0
            # the worker exported without the fleet lock; a dispatch
            # racing the death can have landed one more inbox item
            # since — re-drain under the lock enqueues are made under
            exports = list(exports) + rep.drain_inbox()
            self._emit("replica_death", replica=rep.name,
                       error=f"{type(error).__name__}: {error}",
                       in_flight=len(exports))
            self._record_crash(rep, reason="death", error=error,
                               affected=[f for f, _p in exports])
            migrated = []
            for freq, prog in sorted(exports, key=lambda e: e[0].fid):
                if prog is not None:
                    freq.progress = prog
                if self._closed:
                    # the dispatcher is gone; nothing can resume this
                    self._shed_locked(freq, "shutdown",
                                      "replica died during close")
                    continue
                freq.migrations += 1
                freq.last_token_time = None   # ITL re-anchors on the
                #   survivor: the migration gap is a fault cost, not a
                #   decode-cadence reading (see fleet/proc.py)
                self.metrics.migrations += 1
                self._emit("migration", fid=freq.fid,
                           trace_id=freq.trace_id,
                           from_replica=rep.name,
                           committed=len(freq.committed))
                if self.tracer is not None:
                    self.tracer.event(freq.trace_id, "migration",
                                      from_replica=rep.name,
                                      committed=len(freq.committed))
                migrated.append(freq)
            self._queue.push_front(migrated)
            self._cv.notify_all()

    def _record_crash(self, rep, *, reason: str, error, affected) -> None:
        """The black box, thread-fleet flavor: the dead engine's ring
        and the affected requests' spans survive in THIS address
        space — freeze them into ``last_crash`` before migration
        rewrites anything. With ``crash_dir`` set the payload is
        QUEUED here (lock held) and written by the dispatcher OUTSIDE
        the lock (:meth:`_write_dumps`): file IO must never stall
        token delivery."""
        if not self._obs:
            return
        recorder = getattr(rep.engine, "recorder", None)
        ring = recorder.snapshot() if recorder is not None else []
        tids = [f.trace_id for f in affected if f.trace_id]
        traces = (self.tracer.snapshot(tids)
                  if self.tracer is not None else {})
        requests = [{"fid": f.fid, "trace_id": f.trace_id,
                     "committed": len(f.committed),
                     "migrations": f.migrations,
                     "adapter_id": f.adapter_id} for f in affected]
        self.last_crash = {
            "replica": rep.name, "reason": reason,
            "error": f"{type(error).__name__}: {error}",
            "ring": ring, "traces": traces, "requests": requests,
            # last pool-pressure snapshot (obs/signals.py), when the
            # signal plane is armed — same black-box field the process
            # fleet freezes (fleet/proc.py)
            "signals": (self.signals.snapshot()
                        if self.signals is not None else {}),
            # the lock-audit ledgers ride the black box under
            # lock_audit=True: "who held what, for how long" at death
            "locks": (self.lock_audit.summary()
                      if self.lock_audit is not None else {}),
        }
        if self.crash_dir is not None:
            self._pending_dumps.append(dict(
                self.last_crash,
                events=(self.events.snapshot(last=64)
                        if self.events is not None else [])))

    def _write_dumps(self, pending: List[Dict]) -> None:
        """Write queued crash dumps (called WITHOUT the fleet lock)."""
        from quintnet_tpu.obs import write_crash_dump

        for spec in pending:
            path = write_crash_dump(self.crash_dir, **spec)
            self.crash_dumps.append(path)
            # the writer keeps only the newest N files — drop ledger
            # entries whose file was pruned so every path here loads
            self.crash_dumps = [p for p in self.crash_dumps
                                if os.path.exists(p)]
            self._emit("crash_dump", replica=spec["replica"],
                       path=path)

    # ------------------------------------------------------------------
    # dispatcher
    # ------------------------------------------------------------------
    def _shed_locked(self, freq: FleetRequest, reason: str,
                     message: str) -> None:
        if reason == "deadline":
            self.metrics.shed_deadline += 1
        else:
            self.metrics.shed_shutdown += 1
        self._slo_observe("shed", 1.0)
        self._emit("shed", fid=freq.fid, trace_id=freq.trace_id,
                   reason=reason)
        freq.error = Overloaded(reason, message)
        self._open -= 1
        freq.event.set()
        self._cv.notify_all()

    def _tend_replicas_locked(self) -> None:
        for i, rep in enumerate(self._replicas):
            if rep.state != DEAD:
                continue
            allowed = self._breakers[rep.name].allow_restart()
            self._note_breaker(rep.name)
            if not allowed:
                continue
            chaos = rep.chaos
            if chaos is not None and getattr(chaos, "rearm", False):
                chaos.rearm_now()
            self._replicas[i] = self._spawn(rep.name, chaos)
            self.metrics.restarts += 1
            self._emit("replica_restart", replica=rep.name)

    def _dispatch_locked(self) -> None:
        for freq in self._queue.shed_expired():
            self._shed_locked(
                freq, "deadline",
                f"request {freq.fid} still queued at its deadline; shed "
                f"instead of serving a result the client stopped "
                f"waiting for")
        while len(self._queue):
            cands = router_eligible(self._replicas)
            if not cands:
                return
            # adapter affinity: peek the queue head's binding so the
            # router can prefer adapter-warm replicas (fleet/router.py)
            rep = self._router.pick(
                cands, adapter_id=self._queue.peek_adapter_id())
            freq = self._queue.pop()
            freq.cost = freq.outstanding_cost()
            freq.replica_name = rep.name
            rep.in_flight += 1
            rep.outstanding_tokens += freq.cost
            if self.tracer is not None:
                self.tracer.add(freq.trace_id, "fleet_queue",
                                t0=freq.submit_time, t1=self.clock(),
                                migrations=freq.migrations)
                self.tracer.event(freq.trace_id, "dispatch",
                                  replica=rep.name)
            rep.enqueue(freq, freq.progress)

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                self._tend_replicas_locked()
                self._tend_signals_locked(self.clock())
                self._dispatch_locked()
                pending, self._pending_dumps = self._pending_dumps, []
                if not pending:
                    self._cv.wait(self._poll_s)
            if pending:
                self._write_dumps(pending)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def pause_all(self) -> None:
        for rep in self._replicas:
            rep.pause()

    def resume_all(self) -> None:
        for rep in self._replicas:
            rep.resume()
        with self._cv:
            self._cv.notify_all()

    def arm_chaos(self, monkey) -> None:
        """Attach a (mode='raise') ChaosMonkey to the replica named by
        its ``target`` (default: replica 0) on a RUNNING fleet — the
        bench arms faults after warmup so kill_at_step counts replay
        steps only."""
        name = monkey.target
        with self._cv:
            reps = {r.name: r for r in self._replicas}
            if name is not None and name not in reps:
                raise ValueError(f"no replica named {name!r}")
            rep = self._replicas[0] if name is None else reps[name]
            rep.chaos = monkey

    def drain(self, *, timeout: Optional[float] = None) -> None:
        """Graceful shutdown: refuse new submissions, let everything
        already accepted run to completion (migrations included), then
        stop the worker threads. Raises TimeoutError (fleet left
        draining but alive) if the backlog does not clear in time."""
        deadline = None if timeout is None else self.clock() + timeout
        with self._cv:
            self._draining = True
            self._emit("drain", open_requests=self._open)
            self._cv.notify_all()
            while self._open > 0:
                if deadline is not None and self.clock() >= deadline:
                    raise TimeoutError(
                        f"drain: {self._open} request(s) still open "
                        f"after {timeout}s")
                self._cv.wait(self._poll_s)
        self.close()

    def close(self) -> None:
        """Hard stop: shed everything pending, stop all threads, error
        any request still in flight (``Overloaded('shutdown')``). Use
        :meth:`drain` for the graceful path."""
        with self._cv:
            if self._closed:
                return
            self._draining = True
            self._closed = True
            self._emit("close", open_requests=self._open)
            for freq in self._queue.drain_all():
                self._shed_locked(freq, "shutdown",
                                  "fleet closed before dispatch")
            self._cv.notify_all()
        self._dispatcher.join(timeout=10.0)
        for rep in self._replicas:
            rep.stop()
        with self._cv:
            for rep in self._replicas:
                for freq in rep.unfinished():
                    if not freq.event.is_set():
                        self._shed_locked(
                            freq, "shutdown",
                            "fleet closed with the request in flight")
            pending, self._pending_dumps = self._pending_dumps, []
        self._write_dumps(pending)   # dumps a closing race queued
        if self.lock_audit is not None:
            self.lock_audit.close()

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    def breaker(self, name: str) -> CircuitBreaker:
        return self._breakers[name]

    def health(self) -> Dict:
        """Cheap liveness snapshot (no engine access beyond counters) —
        what the HTTP front door's /healthz serves
        (fleet/frontdoor.py); shape-compatible with
        :meth:`ProcessFleet.health`."""
        with self._cv:
            return {
                "replicas": {r.name: {"state": r.state,
                                      "steps": r.steps,
                                      "in_flight": r.in_flight,
                                      "breaker": self._breakers[r.name].state}
                             for r in self._replicas},
                "queue_depth": len(self._queue),
                "queue_oldest_wait_s": round(
                    self._queue.oldest_wait_s(), 4),
                "open_requests": self._open,
                "draining": self._draining,
            }

    def _queue_gauges(self):
        """(depth, oldest wait age) for FleetMetrics' probe — and the
        front door's Retry-After hint. Reads snapshot copies, so it is
        safe from any thread without the fleet lock."""
        return len(self._queue), self._queue.oldest_wait_s()

    # ------------------------------------------------------------------
    # SLO engine + signal plane (obs/slo.py, obs/signals.py)
    # ------------------------------------------------------------------
    def arm_slo(self, config) -> None:
        """Arm the SLO engine + signal bus against this fleet's
        dispatcher (``config``: :class:`~quintnet_tpu.obs.slo.
        SLOConfig`). TTFT/ITL observe at token delivery, shed/error
        rates at submit/finish, and the dispatcher samples queue/
        occupancy/KV pressure each ``eval_interval_s``. No rebalance
        planner here — the thread fleet has no pools to move replicas
        between (see :meth:`ProcessFleet.arm_slo`). Requires the
        flight recorder (``slo=`` at the constructor implies it) for
        the step rings the occupancy signals read."""
        from quintnet_tpu.obs import EventLog
        from quintnet_tpu.obs.signals import SignalBus
        from quintnet_tpu.obs.slo import SLOEngine
        if not self._obs:
            # silently arming would sample permanently-zero occupancy
            # and KV pressure (the rings are only recorded when the
            # flight recorder is on) — judgment over dead gauges
            raise ValueError(
                "arm_slo requires a fleet built with obs=True (or "
                "slo= at the constructor): the occupancy/KV signals "
                "read the per-replica step rings")
        with self._cv:
            if self.events is None:
                self.events = EventLog(
                    clock=self.clock,
                    lock=self._audit_lock("obs.events"))
            self.slo = SLOEngine(config, clock=self.clock,
                                 events=self.events)
            self.signals = SignalBus(
                clock=self.clock,
                lock=self._audit_lock("obs.signals"))
            self._signal_next_t = 0.0

    def _slo_observe(self, stream: str, value: float) -> None:
        if self.slo is not None:
            self.slo.observe(stream, value)

    def _tend_signals_locked(self, now: float) -> None:
        """One signal-plane tick on the dispatcher thread: sample
        pressure gauges from state already in this address space (the
        admission queue, each engine's step ring, the breakers), then
        re-evaluate the SLO engine. Host-side floats only; no device
        sync, no mutation — inert by construction."""
        if self.slo is None:
            return
        if now < self._signal_next_t:
            return
        self._signal_next_t = now + self.slo.config.eval_interval_s
        bus = self.signals
        bus.sample("queue_depth", float(len(self._queue)))
        bus.sample("queue_oldest_wait_s", self._queue.oldest_wait_s())
        running = slots = kv_used = kv_total = 0
        open_breakers = 0
        for rep in self._replicas:
            if self._breakers[rep.name].state != CLOSED:
                open_breakers += 1
            if rep.state != HEALTHY:
                # a dead worker's recorder still holds its last step
                # record — stale occupancy/KV, not live pressure
                continue
            eng = rep.engine
            slots += int(getattr(eng, "max_slots", 0) or 0)
            recorder = getattr(eng, "recorder", None)
            last = recorder.last() if recorder is not None else None
            if last is None:
                continue
            running += int(last.get("running", 0))
            kv_used += int(last.get("kv_blocks_used", 0))
            kv_total += int(last.get("kv_blocks_total", 0))
        bus.sample("occupancy", running / slots if slots else 0.0)
        bus.sample("kv_pressure",
                   kv_used / kv_total if kv_total else 0.0)
        bus.sample("breakers_open", float(open_breakers))
        self.slo.evaluate(now)

    def queue_oldest_wait_s(self) -> float:
        """Wait age of the oldest queued request (0.0 when empty)."""
        return self._queue.oldest_wait_s()

    def reset_metrics(self) -> None:
        """Fresh ledgers fleet-wide (bench warmup boundary): fleet
        counters, every live engine's ServeMetrics, retired-engine
        stash, and each replica's step counter — so a ChaosMonkey armed
        after warmup (:meth:`arm_chaos`) counts REPLAY steps only."""
        with self._cv:
            self.metrics = FleetMetrics()
            self.metrics._queue_probe = self._queue_gauges
            self._retired_metrics = []
            for rep in self._replicas:
                rep.steps = 0
                rep.engine.metrics = type(rep.engine.metrics)(
                    clock=rep.engine.clock)

    def engine_summaries(self) -> Dict[str, Dict]:
        """Per-replica ``ServeMetrics.summary()`` dicts (the front
        door's /metrics and /v1/metrics surface — shape-compatible
        with :meth:`ProcessFleet.engine_summaries`)."""
        with self._cv:
            return {rep.name: rep.engine.metrics.summary()
                    for rep in self._replicas}

    def engine_summary(self) -> Dict:
        """serve.metrics.aggregate over every engine that served this
        fleet — live replicas plus engines retired by a death."""
        with self._cv:
            ms = ([rep.engine.metrics for rep in self._replicas]
                  + list(self._retired_metrics))
        return serve_metrics.aggregate(ms)

    def summary(self) -> Dict:
        """One JSON-able dict: fleet front-door metrics + aggregated
        engine metrics + per-replica state."""
        with self._cv:
            per_replica = {
                rep.name: {
                    "state": rep.state,
                    "steps": rep.steps,
                    "in_flight": rep.in_flight,
                    "outstanding_tokens": rep.outstanding_tokens,
                    "breaker": self._breakers[rep.name].state,
                    "compile_stats": rep.engine.compile_stats(),
                } for rep in self._replicas}
        out = self.metrics.summary()
        out["policy"] = self._router.policy
        out["replicas"] = per_replica
        out["engine"] = self.engine_summary()
        if self.slo is not None:
            out["slo"] = self.slo.status()
        return out

    def assert_compile_count(self, prefill: Optional[int] = None,
                             decode: int = 1, *,
                             include_idle: bool = False) -> None:
        """The fleet-wide bounded-compile promise: every replica engine
        that served at least one request must have compiled EXACTLY
        ``decode`` decode programs, at least one prefill program, no
        more than one per bucket, and no more than ``prefill`` in
        total (default: that replica's own bucket count). An UPPER
        bound, not an exact total — the router legitimately sends
        different tail-length mixes to different replicas, so replicas
        compile different bucket subsets. The decode sentinels are
        routed through analysis.assert_compile_count for its
        signature-diffing error. Engines that never admitted work
        (0 compiles — e.g. a just-restarted probe that got no traffic)
        are skipped unless ``include_idle``. Spec-enabled engines
        additionally carry ``verify[<k>]`` sentinels: at most one
        compile per draft-length bucket, any total from 0 (speculation
        never triggered) to the bucket count. Adapter-enabled engines
        carry ``decode[r<rank>]`` sentinels instead of one ``decode``
        — at most one compile per rank bucket, accounted like verify
        (traffic decides which rank buckets trigger). The fleet-wide
        bound is ``prefill buckets + verify buckets + decode rank
        buckets (or 1 decode)`` per replica."""
        from quintnet_tpu.analysis.recompile import RecompileError

        expected: Dict[str, int] = {}
        sentinels: Dict = {}
        for rep in self._replicas:
            if not include_idle and rep.engine.metrics.admitted == 0:
                continue
            rep_sentinels = rep.engine.compile_sentinels()
            has_verify = any(k.startswith("verify[")
                             for k in rep_sentinels)
            if "decode" not in rep_sentinels:
                # adapter-enabled engine: rank-bucketed decode — at
                # most one compile per bucket, any total up to the
                # bucket count (which buckets fire is traffic-shaped)
                per_decode = {kind: s.compile_count
                              for kind, s in rep_sentinels.items()
                              if kind.startswith("decode[")}
                if any(n > 1 for n in per_decode.values()):
                    raise RecompileError(
                        f"replica {rep.name}: expected at most one "
                        f"compiled decode program per LoRA rank "
                        f"bucket, observed {per_decode}")
            else:
                key = f"{rep.name}_decode"
                # a spec-enabled replica whose every step speculated
                # may legitimately never compile the plain decode
                # program — 0 or `decode` compiles both keep the bound
                if not (has_verify
                        and rep_sentinels["decode"].compile_count == 0):
                    expected[key] = decode
                    sentinels[key] = rep_sentinels["decode"]
            per_bucket = {kind: s.compile_count
                          for kind, s in rep_sentinels.items()
                          if kind.startswith("prefill[")}
            total = sum(per_bucket.values())
            cap = prefill if prefill is not None else len(per_bucket)
            if not 1 <= total <= cap or any(n > 1
                                            for n in per_bucket.values()):
                raise RecompileError(
                    f"replica {rep.name}: expected 1..{cap} compiled "
                    f"prefill bucket program(s) (at most one per "
                    f"bucket), observed {total} ({per_bucket})")
            per_verify = {kind: s.compile_count
                          for kind, s in rep_sentinels.items()
                          if kind.startswith("verify[")}
            if any(n > 1 for n in per_verify.values()):
                raise RecompileError(
                    f"replica {rep.name}: expected at most one compiled "
                    f"verify program per draft-length bucket, observed "
                    f"{per_verify}")
        _assert_cc(expected, **sentinels)

"""Sharding-aware checkpoint save/restore with resume.

The reference has three save paths and NO resume: naive whole-state save
(trainer.py:344-363), per-(pp,tp)-shard .pt files (GPT2_Trainer.py:453-
507), and an offline merge CLI (merge_checkpoints.py); utils/checkpoint.py
is a TODO stub. Orbax replaces all of it: sharded arrays are written as
one logical checkpoint (each host writes its shards), restore reapplies
any target sharding, and step-indexed directories give resume.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    _HAVE_ORBAX = False


class CheckpointRestoreError(RuntimeError):
    """A specific checkpoint step failed to load — with the recovery
    options spelled out, instead of a raw orbax/tensorstore traceback
    from deep inside the array reader.

    Attributes: ``directory``, ``step`` (the bad one), ``available``
    (other steps present in the directory, newest first).
    """

    def __init__(self, directory: str, step: int, *, available, cause):
        self.directory = directory
        self.step = step
        self.available = sorted(available, reverse=True)
        msg = (f"checkpoint step {step} in {directory} failed to "
               f"restore: {cause}")
        if self.available:
            msg += (f". Older steps exist: {self.available} — retry with "
                    f"restore(step={self.available[0]}), or use "
                    "quintnet_tpu.ft.restore.restore_with_fallback to "
                    "resume from the newest step that loads")
        else:
            msg += (". No other steps exist in this directory; the run "
                    "must re-init from scratch")
        super().__init__(msg)


class CheckpointManager:
    """Step-indexed train-state checkpoints (params + opt_state + step).

    save(step, state) / restore(step=None -> latest, template=) where
    ``template`` is a pytree of jax.ShapeDtypeStruct or arrays carrying
    the target shardings (restore onto ANY mesh — the capability the
    reference's merge_checkpoints.py CLI exists to approximate offline).

    ``save(..., cursor=dict)`` additionally writes a JSON item into the
    SAME step directory (``ocp.args.Composite``), so the host-side
    train cursor (quintnet_tpu/ft/cursor.py) commits atomically with
    the arrays: a checkpoint either has both or neither.
    """

    def __init__(self, directory: str, *, max_to_keep: Optional[int] = 3):
        if not _HAVE_ORBAX:
            raise ImportError("orbax-checkpoint not available")
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: Any, *, cursor: Optional[dict] = None,
             wait: bool = True, force: bool = False) -> None:
        if step in self._mgr.all_steps():
            # never overwrite a committed step by default. A re-reached
            # step is bit-identical by deterministic replay (rewriting
            # buys nothing), and a delete-then-rewrite would hand a
            # mid-write kill BOTH copies — a torn step must cost one
            # fallback interval (ft/restore.py), never the data that
            # still loads. ``force`` is for the two cases where the
            # on-disk copy is known worthless or superseded: a step the
            # restore fallback PROVED unreadable (deleting it loses
            # nothing, and without the rewrite replay could never move
            # the high-water mark past it), and an epoch-boundary
            # rewrite of a same-step mid-epoch cursor (trainer
            # save_state(boundary=True), done synchronously).
            if not force:
                return
            # the doomed copy may still be mid-async-write (a cadence
            # save moments ago) — barrier before deleting it
            self._mgr.wait_until_finished()
            self._mgr.delete(step)
        items = {"state": ocp.args.StandardSave(state)}
        if cursor is not None:
            items["cursor"] = ocp.args.JsonSave(cursor)
        self._mgr.save(step, args=ocp.args.Composite(**items))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        """Barrier on any in-flight async save."""
        self._mgr.wait_until_finished()

    def restore(self, template: Any = None, *, step: Optional[int] = None
                ) -> Any:
        """``template=None`` restores as plain host numpy arrays with the
        saved structure — the no-mesh reload path the single-device
        verifiers use (reference: examples/verify_model.py:23-60 reloads
        with no distributed code).

        An incomplete/corrupt step raises :class:`CheckpointRestoreError`
        naming the bad step and the fallbacks, never a raw orbax
        traceback."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        single = (ocp.args.StandardRestore(template)
                  if template is not None else ocp.args.StandardRestore())
        try:
            return self._mgr.restore(
                step, args=ocp.args.Composite(state=single))["state"]
        except Exception as e:  # noqa: BLE001 — orbax/tensorstore raise
            # a zoo of types for torn files; all mean "this step is bad"
            try:
                # pre-cursor checkpoints are a SINGLE StandardSave item,
                # which orbax refuses to read through Composite — retry
                # with the legacy layout before declaring the step bad
                return self._mgr.restore(step, args=single)
            except Exception:  # noqa: BLE001 — genuinely bad step;
                pass           # report the ORIGINAL failure below
            others = [s for s in self.all_steps() if s != step]
            raise CheckpointRestoreError(self.directory, step,
                                         available=others, cause=e) from e

    def restore_cursor(self, *, step: Optional[int] = None
                       ) -> Optional[dict]:
        """The JSON train cursor saved next to the arrays, or None for
        checkpoints written without one (resume then degrades to the
        epoch-granular contract). A PRESENT-but-unreadable cursor raises
        :class:`CheckpointRestoreError` — that step is damaged goods."""
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        if not os.path.isdir(os.path.join(self.directory, str(step),
                                          "cursor")):
            return None
        try:
            return self._mgr.restore(
                step, args=ocp.args.Composite(
                    cursor=ocp.args.JsonRestore()))["cursor"]
        except Exception as e:  # noqa: BLE001 — see restore()
            others = [s for s in self.all_steps() if s != step]
            raise CheckpointRestoreError(self.directory, step,
                                         available=others, cause=e) from e

    def close(self):
        self._mgr.close()


def save_pytree(path: str, tree: Any) -> None:
    """One-shot whole-pytree save (small models / tests) via the
    pure-python safetensors writer — no orbax needed."""
    from quintnet_tpu.utils import safetensors_io as st

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    tensors = {jax.tree_util.keystr(path_): np.asarray(jax.device_get(x))
               for path_, x in flat}
    st.save_file(tensors, path)


def load_pytree(path: str, template: Any) -> Any:
    """Inverse of :func:`save_pytree` given a matching-structure template."""
    from quintnet_tpu.utils import safetensors_io as st

    data = st.load_file(path)
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [data[jax.tree_util.keystr(p)] for p, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves)

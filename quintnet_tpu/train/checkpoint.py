"""Sharding-aware checkpoint save/restore with resume.

The reference has three save paths and NO resume: naive whole-state save
(trainer.py:344-363), per-(pp,tp)-shard .pt files (GPT2_Trainer.py:453-
507), and an offline merge CLI (merge_checkpoints.py); utils/checkpoint.py
is a TODO stub. Orbax replaces all of it: sharded arrays are written as
one logical checkpoint (each host writes its shards), restore reapplies
any target sharding, and step-indexed directories give resume.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    _HAVE_ORBAX = False


class CheckpointManager:
    """Step-indexed train-state checkpoints (params + opt_state + step).

    save(step, state) / restore(step=None -> latest, template=) where
    ``template`` is a pytree of jax.ShapeDtypeStruct or arrays carrying
    the target shardings (restore onto ANY mesh — the capability the
    reference's merge_checkpoints.py CLI exists to approximate offline).
    """

    def __init__(self, directory: str, *, max_to_keep: Optional[int] = 3):
        if not _HAVE_ORBAX:
            raise ImportError("orbax-checkpoint not available")
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: Any, *, wait: bool = True) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait_until_finished(self) -> None:
        """Barrier on any in-flight async save."""
        self._mgr.wait_until_finished()

    def restore(self, template: Any = None, *, step: Optional[int] = None
                ) -> Any:
        """``template=None`` restores as plain host numpy arrays with the
        saved structure — the no-mesh reload path the single-device
        verifiers use (reference: examples/verify_model.py:23-60 reloads
        with no distributed code)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        args = (ocp.args.StandardRestore(template)
                if template is not None else ocp.args.StandardRestore())
        return self._mgr.restore(step, args=args)

    def close(self):
        self._mgr.close()


def save_pytree(path: str, tree: Any) -> None:
    """One-shot whole-pytree save (small models / tests) via the
    pure-python safetensors writer — no orbax needed."""
    from quintnet_tpu.utils import safetensors_io as st

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    tensors = {jax.tree_util.keystr(path_): np.asarray(jax.device_get(x))
               for path_, x in flat}
    st.save_file(tensors, path)


def load_pytree(path: str, template: Any) -> Any:
    """Inverse of :func:`save_pytree` given a matching-structure template."""
    from quintnet_tpu.utils import safetensors_io as st

    data = st.load_file(path)
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [data[jax.tree_util.keystr(p)] for p, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves)

"""Training loop layer: trainers, metrics, checkpointing, logging."""

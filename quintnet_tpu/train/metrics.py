"""Metrics: classification, perplexity, ROUGE/BLEU, greedy generation.

Reference: utils/metrics.py (rouge_score + sacrebleu + a greedy
generation loop, :12-206). Those packages are not in this image, so
ROUGE-1/2/L and BLEU are implemented directly (same definitions:
ROUGE f-measure on unigrams/bigrams/LCS; BLEU-4 with brevity penalty).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Callable, Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def accuracy(logits, labels) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def perplexity(loss) -> jnp.ndarray:
    """exp(loss) capped at loss 20 (reference overflow guard,
    GPT2_Trainer.py:316-318)."""
    return jnp.exp(jnp.minimum(loss, 20.0))


# --------------------------------------------------------------------------
# ROUGE / BLEU (pure python)

def _ngrams(tokens: Sequence[str], n: int) -> Counter:
    return Counter(tuple(tokens[i:i + n]) for i in range(len(tokens) - n + 1))


def _f1(match: int, pred: int, ref: int) -> float:
    if pred == 0 or ref == 0 or match == 0:
        return 0.0
    p, r = match / pred, match / ref
    return 2 * p * r / (p + r)


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    if not a or not b:
        return 0
    dp = [0] * (len(b) + 1)
    for x in a:
        prev = 0
        for j, y in enumerate(b, 1):
            cur = dp[j]
            dp[j] = prev + 1 if x == y else max(dp[j], dp[j - 1])
            prev = cur
    return dp[-1]


def rouge_scores(prediction: str, reference: str) -> Dict[str, float]:
    """ROUGE-1/2/L f-measures (whitespace tokenisation, lowercased)."""
    p = prediction.lower().split()
    r = reference.lower().split()
    out = {}
    for n, key in ((1, "rouge1"), (2, "rouge2")):
        pn, rn = _ngrams(p, n), _ngrams(r, n)
        match = sum((pn & rn).values())
        out[key] = _f1(match, max(len(p) - n + 1, 0), max(len(r) - n + 1, 0))
    lcs = _lcs_len(p, r)
    out["rougeL"] = _f1(lcs, len(p), len(r))
    return out


def bleu_score(prediction: str, references: Sequence[str],
               max_n: int = 4) -> float:
    """Corpus-style BLEU-4 for a single sentence (sacrebleu definition:
    geometric mean of clipped n-gram precisions x brevity penalty)."""
    p = prediction.lower().split()
    refs = [r.lower().split() for r in references]
    if not p:
        return 0.0
    log_prec = 0.0
    for n in range(1, max_n + 1):
        pn = _ngrams(p, n)
        if not pn:
            return 0.0
        best = Counter()
        for r in refs:
            rn = _ngrams(r, n)
            for g in pn:
                best[g] = max(best[g], rn.get(g, 0))
        match = sum(min(c, best[g]) for g, c in pn.items())
        # smoothed (add-eps) to avoid log 0, as sacrebleu's exp smoothing
        prec = max(match, 0.1) / sum(pn.values()) if match == 0 else \
            match / sum(pn.values())
        log_prec += math.log(prec)
    ref_len = min((abs(len(r) - len(p)), len(r)) for r in refs)[1]
    bp = 1.0 if len(p) >= ref_len else math.exp(1 - ref_len / len(p))
    return bp * math.exp(log_prec / max_n)


def compute_rouge_bleu(predictions: Sequence[str],
                       references: Sequence[str]) -> Dict[str, float]:
    """Mean ROUGE-1/2/L + BLEU over pairs (reference
    utils/metrics.py:12-71)."""
    agg = {"rouge1": 0.0, "rouge2": 0.0, "rougeL": 0.0, "bleu": 0.0}
    n = max(len(predictions), 1)
    for pred, ref in zip(predictions, references):
        r = rouge_scores(pred, ref)
        for k in ("rouge1", "rouge2", "rougeL"):
            agg[k] += r[k] / n
        agg["bleu"] += bleu_score(pred, [ref]) / n
    return agg


# --------------------------------------------------------------------------
# Generation eval (the production decode path is the KV-cache decoder in
# models/gpt2_generate.py; the reference's full-forward-per-token loop,
# utils/metrics.py:74-149, survives only as a golden oracle inside
# tests/test_generate.py)

def evaluate_generation(params, cfg, prompts: Sequence, tokenizer, *,
                        max_new_tokens: int = 64,
                        eos_token_id: int | None = None,
                        batch_size: int = 8,
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 1.0, key=None, beams: int = 1,
                        generate_fn=None,
                        mesh=None, tp_axis: str = "tp") -> Dict[str, float]:
    """Generate continuations with the KV-cache decoder and score
    ROUGE-1/2/L + BLEU against references (reference evaluate_generation:
    utils/metrics.py:152-206, which re-runs the full prefix per token and
    scores with rouge_score/sacrebleu).

    ``prompts``: (prompt token ids, reference text) pairs, e.g. from
    SummarizationDataset.eval_prompts. Prompts are grouped by length so
    each distinct shape compiles once, then generated in batches.

    ``mesh``: run TP-SHARDED decode on a live mesh — ``params`` stay in
    their tp training layout (models/gpt2_generate.py gpt2_generate_tp).
    The reference skips generation eval under any parallelism
    (GPT2_Trainer.py:509-555).

    ``generate_fn(params, batch_ids, cfg, max_new_tokens=...,
    eos_token_id=..., temperature=..., top_k=..., top_p=..., key=...)``:
    override the decoder — e.g. models/llama_generate.llama_generate
    scores a Llama model with the same ROUGE/BLEU harness. Default:
    the GPT-2 decoders (+beams/tp routing below). With ``beams > 1``
    the sampling kwargs are replaced by ``beams=`` — pass a
    beam-capable decoder (e.g. llama_generate.llama_beam_search).
    """
    from quintnet_tpu.models.gpt2_generate import (gpt2_beam_search,
                                                   gpt2_generate,
                                                   gpt2_generate_tp)

    if (beams > 1 and generate_fn is None and mesh is not None
            and mesh.shape.get(tp_axis, 1) > 1):
        # the built-in gpt2 beam decode is single-device; silently
        # scoring the tp sampling decoder instead of the requested
        # beams would corrupt the comparison — refuse instead (a
        # custom generate_fn receives beams= and routes itself)
        raise ValueError(
            "beams > 1 under a tp>1 mesh is not implemented by the "
            "built-in decoder; use beams=1 (sampling/greedy tp "
            "decode), a single-device mesh, or a beam-capable "
            "generate_fn")

    by_len: Dict[int, List[int]] = {}
    for i, (ids, _ref) in enumerate(prompts):
        by_len.setdefault(len(ids), []).append(i)

    preds: List[str] = [""] * len(prompts)
    for n, idxs in sorted(by_len.items()):
        for j in range(0, len(idxs), batch_size):
            grp = idxs[j:j + batch_size]
            batch = np.asarray([prompts[i][0] for i in grp], np.int32)
            if len(grp) < batch_size and len(idxs) > batch_size:
                # pad the trailing partial batch to the compiled batch
                # shape (extra rows discarded) — a second XLA compile of
                # prefill+decode costs far more than the wasted rows
                pad = np.repeat(batch[-1:], batch_size - len(grp), axis=0)
                batch = np.concatenate([batch, pad], axis=0)
            sample = dict(temperature=temperature, top_k=top_k,
                          top_p=top_p, key=key)
            if generate_fn is not None:
                # beam decoders (e.g. llama_beam_search) take beams=
                # and are deterministic (no sampling kwargs)
                kw = (dict(beams=beams) if beams > 1 else sample)
                out = generate_fn(params, batch, cfg,
                                  max_new_tokens=max_new_tokens,
                                  eos_token_id=eos_token_id, **kw)
            elif beams > 1:
                # beam decode is single-device (deterministic, so no
                # key); the tp>1 case was refused above
                out = gpt2_beam_search(params, batch, cfg, beams=beams,
                                       max_new_tokens=max_new_tokens,
                                       eos_token_id=eos_token_id)
            elif mesh is not None and mesh.shape.get(tp_axis, 1) > 1:
                out = gpt2_generate_tp(params, batch, cfg, mesh=mesh,
                                       tp_axis=tp_axis,
                                       max_new_tokens=max_new_tokens,
                                       eos_token_id=eos_token_id, **sample)
            else:
                out = gpt2_generate(params, batch, cfg,
                                    max_new_tokens=max_new_tokens,
                                    eos_token_id=eos_token_id, **sample)
            for row, i in zip(out, grp):
                new = row[n:]
                if eos_token_id is not None:
                    stop = np.where(new == eos_token_id)[0]
                    if stop.size:
                        new = new[: stop[0]]
                preds[i] = tokenizer.decode([int(t) for t in new])

    return compute_rouge_bleu(preds, [ref for _ids, ref in prompts])

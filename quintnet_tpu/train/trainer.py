"""Trainers: config-driven epoch loops over strategy-built train steps.

Reference: ``Trainer`` (ViT classification, trainer.py:57-363) and
``GPT2Trainer`` (CLM/summarization, GPT2_Trainer.py:56-555). One class
covers both here (task_type switches metrics), because all parallelism
differences live in the Strategy — the loop does not care whether the
step underneath is single-device, DP, or a 3D 1F1B pipeline.

Differences from the reference worth knowing:
- metrics come back from the step already reduced (no MAX-allreduce
  metric propagation dance, trainer.py:168-187 — and no silent
  assumption that metrics are non-negative);
- checkpoints save sharded via train/checkpoint.py and RESUME works
  (the reference is save-only);
- a single process drives the whole mesh (SPMD), so "rank 0 only"
  logging guards are unnecessary.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from quintnet_tpu.core.config import Config
from quintnet_tpu.parallel.strategy import ModelSpec, Strategy, get_strategy


def make_lr_schedule(cfg: Config):
    """LR schedule from config fields (the reference trains at constant
    lr only — trainer.py:89, GPT2_Trainer.py:100-104).

    ``lr_schedule``: constant | cosine | linear; ``warmup_steps``
    prepends a linear 0->peak ramp; cosine/linear decay to
    ``learning_rate * min_lr_ratio`` at step ``decay_steps`` (a TOTAL
    step count, warmup included). Returns a float for the plain
    constant case so optimizer states stay countless where possible.
    """
    t = cfg.training
    lr, name = t.learning_rate, t.lr_schedule.lower()
    if name == "constant":
        if not t.warmup_steps:
            return lr
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, lr, t.warmup_steps),
             optax.schedules.constant_schedule(lr)],
            [t.warmup_steps])
    if t.decay_steps <= t.warmup_steps:
        raise ValueError(
            f"lr_schedule={name!r} needs decay_steps > warmup_steps "
            f"(got decay_steps={t.decay_steps}, warmup={t.warmup_steps})")
    end = lr * t.min_lr_ratio
    if name == "cosine":
        return optax.schedules.warmup_cosine_decay_schedule(
            init_value=0.0 if t.warmup_steps else lr, peak_value=lr,
            warmup_steps=t.warmup_steps, decay_steps=t.decay_steps,
            end_value=end)
    if name == "linear":
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(
                0.0 if t.warmup_steps else lr, lr, max(t.warmup_steps, 1)),
             optax.schedules.linear_schedule(
                 lr, end, t.decay_steps - t.warmup_steps)],
            [t.warmup_steps])
    raise ValueError(f"unknown lr_schedule {t.lr_schedule!r}")


def masked_decay(weight_decay: float):
    """Decoupled weight decay skipping biases and norm scales/shifts.

    Standard practice (and what torch AdamW users hand-configure via
    param groups); the reference decays everything (GPT2_Trainer.py:100).
    Default mask: NAME-based — dict keys in core/pytree.DECAY_KEYS
    (weight matrices, embedding tables) decay; everything else is
    skipped. Name-based because an ndim test misclassifies
    stacked-block leaves (a stacked bias is [L, out] = ndim 2).

    Under ZeRO the optimizer runs on a flat chunk where per-leaf
    masking cannot see parameter boundaries, so the transform also
    accepts an ELEMENTWISE ``decay_mask`` extra arg (optax extra-args
    protocol); parallel/zero.py ravels the SAME mask alongside the
    params and passes its chunk — the two paths are bit-identical
    (tests/test_optimizer.py).
    """
    from quintnet_tpu.core.pytree import decay_mask as default_mask

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params, *, decay_mask=None, **extra):
        del extra
        if params is None:
            raise ValueError("masked_decay requires params")
        if decay_mask is None:
            decay_mask = default_mask(params)
        updates = jax.tree.map(
            lambda u, p, m: u + weight_decay * m.astype(u.dtype) * p,
            updates, params, decay_mask)
        return updates, state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def make_optimizer(cfg: Config) -> optax.GradientTransformation:
    """Optimizer from config (reference: Adam in Trainer vs AdamW in
    GPT2Trainer — trainer.py:89 vs GPT2_Trainer.py:100; here one factory).
    zero1_* names shard the state over dp (parallel/zero.py). AdamW is
    built as scale_by_adam + masked_decay + lr so the decay composes
    with schedules exactly like optax.adamw (decay scaled by lr_t) while
    skipping LN/bias leaves."""
    t = cfg.training
    name = t.optimizer.lower()
    if name.startswith(("zero1_", "zero2_")):
        name = name[len("zero1_"):]
    lr = make_lr_schedule(cfg)
    # mu_dtype=bfloat16 halves the first-moment memory (planner: 'opt'
    # row); nu stays f32 (second moments span too many decades for bf16)
    mu = jnp.bfloat16 if t.adam_mu_dtype == "bfloat16" else None
    if name == "adam":
        return optax.adam(lr, mu_dtype=mu)
    if name == "adamw":
        return optax.chain(
            optax.scale_by_adam(mu_dtype=mu),
            masked_decay(0.01 if t.weight_decay is None
                         else t.weight_decay),
            optax.scale_by_learning_rate(lr),
        )
    if name == "sgd":
        return optax.sgd(lr)
    raise ValueError(f"unknown optimizer {t.optimizer!r}")


@dataclass
class History:
    train_loss: List[float] = field(default_factory=list)
    val_loss: List[float] = field(default_factory=list)
    train_metric: List[float] = field(default_factory=list)
    val_metric: List[float] = field(default_factory=list)
    wall_time_s: float = 0.0
    best_val_loss: float = float("inf")
    best_epoch: int = -1

    def to_jsonl(self, path: str):
        """One JSON line per epoch (loss/metrics) + a final summary line
        — greppable run record (the reference's only run record is
        stdout scrollback)."""
        import json

        with open(path, "w") as f:
            for i, tl in enumerate(self.train_loss):
                row = {"epoch": i, "train_loss": tl}
                for name, series in (("val_loss", self.val_loss),
                                     ("train_metric", self.train_metric),
                                     ("val_metric", self.val_metric)):
                    if i < len(series):
                        row[name] = series[i]
                f.write(json.dumps(row) + "\n")
            f.write(json.dumps({
                "wall_time_s": round(self.wall_time_s, 2),
                "best_val_loss": self.best_val_loss,
                "best_epoch": self.best_epoch}) + "\n")


class Trainer:
    """fit() over (x, y) batch iterables.

    ``task_type``: 'classification' (metric: accuracy, pp=1 only) or
    'clm' (metric: perplexity).
    """

    def __init__(self, config: Config, model: ModelSpec,
                 *, strategy: Optional[Strategy] = None,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 task_type: str = "classification",
                 checkpoint_dir: Optional[str] = None,
                 log_fn: Callable[[str], None] = print):
        self.config = config
        self.model = model
        self.strategy = strategy or get_strategy(config.strategy_name, config)
        self.optimizer = optimizer or make_optimizer(config)
        self.task_type = task_type
        self.checkpoint_dir = checkpoint_dir
        self.log = log_fn
        if self.strategy.is_multiprocess and jax.process_index() != 0:
            # one SPMD log per job, not per host (reference: rank-0 tqdm
            # guards); checkpoint saves stay collective on every process
            self.log = lambda msg: None

        self.step_fn = self.strategy.make_train_step(self.model, self.optimizer)
        self._eval_fn = None

    # -- state -------------------------------------------------------------
    def init_state(self, seed: Optional[int] = None):
        seed = self.config.training.seed if seed is None else seed
        host_params = self.model.init(jax.random.key(seed))
        params = self.strategy.shard_params(self.model, host_params)
        opt_state = self.strategy.init_opt_state(self.model, self.optimizer,
                                                 params)
        return params, opt_state

    def resume_or_init(self, seed: Optional[int] = None):
        """Restore the latest checkpoint if one exists (absent from the
        reference), else fresh init. Returns (params, opt_state, start_epoch)."""
        params, opt_state = self.init_state(seed)
        if self.checkpoint_dir:
            mgr = self._manager()
            if mgr.latest_step() is not None:
                restored = mgr.restore({"params": params, "opt": opt_state,
                                        "epoch": 0})
                self.log(f"resumed from epoch {int(restored['epoch'])}")
                return (restored["params"], restored["opt"],
                        int(restored["epoch"]) + 1)
        return params, opt_state, 0

    def _manager(self, *, best: bool = False):
        """Cached CheckpointManager(s) — one per directory, reused across
        epochs (a fresh manager per save re-lists the directory and
        resets orbax's async machinery)."""
        from quintnet_tpu.train.checkpoint import CheckpointManager

        if not hasattr(self, "_mgrs"):
            self._mgrs = {}
        key = "best" if best else "main"
        if key not in self._mgrs:
            self._mgrs[key] = (
                CheckpointManager(self.checkpoint_dir.rstrip("/") + "-best",
                                  max_to_keep=1) if best
                else CheckpointManager(self.checkpoint_dir))
        return self._mgrs[key]

    def save(self, epoch: int, params, opt_state):
        if not self.checkpoint_dir:
            return
        # async: orbax snapshots device arrays before returning, then
        # writes in the background — the next epoch's compute overlaps
        # the IO. fit() barriers at the end (wait_for_saves).
        self._manager().save(
            epoch, {"params": params, "opt": opt_state, "epoch": epoch},
            wait=False)

    def save_best(self, epoch: int, params, opt_state, val_loss: float):
        """Best-by-val-loss retention in a sibling ``<dir>-best``
        directory (one kept), alongside the rolling epoch saves —
        reference: best-and-final per-shard save, GPT2_Trainer.py:453-507.
        Sibling, not subdir, so orbax's step listing of the main
        directory never sees a non-numeric entry."""
        if not self.checkpoint_dir:
            return
        self._manager(best=True).save(
            epoch, {"params": params, "opt": opt_state, "epoch": epoch,
                    "val_loss": val_loss}, wait=False)

    def wait_for_saves(self):
        """Barrier on in-flight async checkpoint writes."""
        for mgr in getattr(self, "_mgrs", {}).values():
            mgr.wait_until_finished()

    # -- evaluation --------------------------------------------------------
    def _build_eval(self):
        """One jitted eval step returning ``{name: scalar}`` device
        metrics — loss always; accuracy for classification (incl. under
        pp, via the forward-only pipeline eval gathering last-stage
        metrics — the reference cannot report its headline 93.24% val
        accuracy under pp at all)."""
        if self._eval_fn is not None:
            return self._eval_fn
        from jax.sharding import PartitionSpec as P

        from quintnet_tpu.core import collectives as cc

        strat = self.strategy
        specs = strat.param_specs(self.model)
        tp_axis = strat.axis_or_none("tp")
        sp_axis = strat.axis_or_none("sp")
        ep_axis = strat.axis_or_none("ep")
        fsdp_kw = ({"fsdp_axis": strat.fsdp_axis}
                   if strat.fsdp_axis is not None else {})

        if strat.uses_pp:
            from quintnet_tpu.parallel.pp import (PipelineSpec,
                                                  make_afab_eval_fn)

            pspec = PipelineSpec(
                n_micro=self.config.training.gradient_accumulation_steps)
            if self.model.pipeline_eval_fns is not None:
                embed_fn, stage_fn, head_metrics_fn = \
                    self.model.pipeline_eval_fns(
                        tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis)
            else:
                from quintnet_tpu.parallel.pp import SplitHead

                embed_fn, stage_fn, head = self.model.pipeline_fns(
                    tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis)
                if isinstance(head, SplitHead):
                    head_metrics_fn = SplitHead(
                        head.local_fn,
                        lambda local, y, valid:
                            {"loss": head.reduce_fn(local, y, valid)})
                else:
                    def head_metrics_fn(p, h, y, _h=head):
                        return {"loss": _h(p, h, y)}

            metrics_fn = make_afab_eval_fn(
                embed_fn, stage_fn, head_metrics_fn, pspec)
        elif self.model.eval_metrics_fn is not None:
            def metrics_fn(p, b):
                return self.model.eval_metrics_fn(
                    p, b, tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis,
                    **fsdp_kw)
        else:
            def metrics_fn(p, b):
                return {"loss": self.model.loss_fn(
                    p, b, tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis,
                    **fsdp_kw)}

        def local_eval(p, b):
            mets = metrics_fn(p, b)
            if strat.batch_axes:
                mets = jax.tree.map(
                    lambda v: jax.lax.pmean(v, strat.batch_axes), mets)
            return mets

        batch_spec = strat.batch_partition_specs(self.model)
        self._eval_fn = jax.jit(cc.shard_map_fn(
            local_eval, strat.mesh,
            in_specs=(specs, batch_spec),
            out_specs=P()))
        return self._eval_fn

    def evaluate(self, params, batches: Iterable) -> Dict[str, float]:
        eval_fn = self._build_eval()
        acc: Dict[str, list] = {}
        for xb, yb in batches:
            b = self.strategy.shard_batch((jnp.asarray(xb), jnp.asarray(yb)),
                                          self.model)
            for k, v in eval_fn(params, b).items():
                acc.setdefault(k, []).append(v)  # device scalars; no sync
        out = {k: float(np.mean([float(v) for v in vs]))
               for k, vs in acc.items()}
        out.setdefault("loss", float("nan"))
        if self.task_type == "clm":
            out["perplexity"] = float(np.exp(min(out["loss"], 20.0)))
        return out

    # -- training ----------------------------------------------------------
    def fit(self, train_batches_fn: Callable[[int], Iterable],
            *, epochs: Optional[int] = None,
            val_batches_fn: Optional[Callable[[int], Iterable]] = None,
            params=None, opt_state=None) -> History:
        """``train_batches_fn(epoch) -> iterable of (x, y)`` host batches
        (global batch size; sharding happens here)."""
        epochs = epochs or self.config.training.epochs
        if params is None:
            params, opt_state, start = self.resume_or_init()
        else:
            start = 0
        hist = History()
        t0 = time.time()
        log_every = self.config.training.log_every

        for epoch in range(start, epochs):
            # losses stay DEVICE scalars during the epoch — no per-step
            # host sync blocking async dispatch (the reference blocks on
            # .item() every step; so did round 1's float(loss)). Host
            # reads happen only at log boundaries and epoch end.
            losses = []
            t_win = time.time()
            sync_every = self.config.training.sync_every
            batches = train_batches_fn(epoch)
            if self.config.training.prefetch:
                from quintnet_tpu.data import prefetch_batches

                batches = prefetch_batches(
                    iter(batches), n=self.config.training.prefetch)
            for i, (xb, yb) in enumerate(batches):
                batch = self.strategy.shard_batch(
                    (jnp.asarray(xb), jnp.asarray(yb)), self.model)
                # per-step dropout seed: deterministic in (config seed,
                # epoch, step) so resume-from-epoch reproduces the run
                seed = (self.config.training.seed * 2_000_003
                        + epoch * 1_000_003 + i) & 0x7FFFFFFF
                params, opt_state, loss = self.step_fn(params, opt_state,
                                                       batch, seed)
                losses.append(loss)
                if sync_every and (i + 1) % sync_every == 0:
                    # bound async run-ahead (training.sync_every docs)
                    float(loss)
                if log_every and (i + 1) % log_every == 0:
                    # the float() is the device sync for the window, so
                    # the wall clock measured here is honest throughput
                    window = float(jnp.mean(jnp.stack(losses[-log_every:])))
                    dt = time.time() - t_win
                    sps = log_every * len(xb) / max(dt, 1e-9)
                    msg = (f"epoch {epoch} step {i + 1}: "
                           f"loss {window:.4f} | {sps:.1f} samples/s")
                    if self.task_type == "clm":
                        msg += f" ({sps * xb.shape[1] / 1e3:.1f}k tok/s)"
                    self.log(msg)
                    t_win = time.time()
            train_loss = (float(jnp.mean(jnp.stack(losses)))
                          if losses else float("nan"))
            hist.train_loss.append(train_loss)
            msg = f"epoch {epoch}: train_loss {train_loss:.4f}"
            if self.task_type == "clm":
                ppl = float(np.exp(min(train_loss, 20.0)))
                hist.train_metric.append(ppl)
                msg += f" ppl {ppl:.2f}"
            if val_batches_fn is not None:
                ev = self.evaluate(params, val_batches_fn(epoch))
                hist.val_loss.append(ev["loss"])
                msg += f" | val_loss {ev['loss']:.4f}"
                for k in ("perplexity", "accuracy"):
                    if k in ev:
                        hist.val_metric.append(ev[k])
                        msg += f" val_{k} {ev[k]:.4f}"
                if ev["loss"] < hist.best_val_loss:
                    hist.best_val_loss = ev["loss"]
                    hist.best_epoch = epoch
                    self.save_best(epoch, params, opt_state, ev["loss"])
                    msg += " (best)"
            self.log(msg)
            self.save(epoch, params, opt_state)

        self.wait_for_saves()
        hist.wall_time_s = time.time() - t0
        self._final_state = (params, opt_state)
        return hist

    @property
    def final_state(self):
        """(params, opt_state) after the last fit() epoch."""
        return getattr(self, "_final_state", None)

"""Trainers: config-driven epoch loops over strategy-built train steps.

Reference: ``Trainer`` (ViT classification, trainer.py:57-363) and
``GPT2Trainer`` (CLM/summarization, GPT2_Trainer.py:56-555). One class
covers both here (task_type switches metrics), because all parallelism
differences live in the Strategy — the loop does not care whether the
step underneath is single-device, DP, or a 3D 1F1B pipeline.

Differences from the reference worth knowing:
- metrics come back from the step already reduced (no MAX-allreduce
  metric propagation dance, trainer.py:168-187 — and no silent
  assumption that metrics are non-negative);
- checkpoints save sharded via train/checkpoint.py and RESUME works
  (the reference is save-only) — STEP-granular: the host-side cursor
  (epoch, step, epoch losses, History) rides in the checkpoint
  (quintnet_tpu/ft/), so a preempted run continues mid-epoch with
  bit-identical results to an uninterrupted one (tests/test_ft.py);
- a single process drives the whole mesh (SPMD), so "rank 0 only"
  logging guards are unnecessary.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from quintnet_tpu.core.config import Config
from quintnet_tpu.parallel.strategy import ModelSpec, Strategy, get_strategy


def make_lr_schedule(cfg: Config):
    """LR schedule from config fields (the reference trains at constant
    lr only — trainer.py:89, GPT2_Trainer.py:100-104).

    ``lr_schedule``: constant | cosine | linear; ``warmup_steps``
    prepends a linear 0->peak ramp; cosine/linear decay to
    ``learning_rate * min_lr_ratio`` at step ``decay_steps`` (a TOTAL
    step count, warmup included). Returns a float for the plain
    constant case so optimizer states stay countless where possible.
    """
    t = cfg.training
    lr, name = t.learning_rate, t.lr_schedule.lower()
    if name == "constant":
        if not t.warmup_steps:
            return lr
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(0.0, lr, t.warmup_steps),
             optax.schedules.constant_schedule(lr)],
            [t.warmup_steps])
    if t.decay_steps <= t.warmup_steps:
        raise ValueError(
            f"lr_schedule={name!r} needs decay_steps > warmup_steps "
            f"(got decay_steps={t.decay_steps}, warmup={t.warmup_steps})")
    end = lr * t.min_lr_ratio
    if name == "cosine":
        return optax.schedules.warmup_cosine_decay_schedule(
            init_value=0.0 if t.warmup_steps else lr, peak_value=lr,
            warmup_steps=t.warmup_steps, decay_steps=t.decay_steps,
            end_value=end)
    if name == "linear":
        return optax.schedules.join_schedules(
            [optax.schedules.linear_schedule(
                0.0 if t.warmup_steps else lr, lr, max(t.warmup_steps, 1)),
             optax.schedules.linear_schedule(
                 lr, end, t.decay_steps - t.warmup_steps)],
            [t.warmup_steps])
    raise ValueError(f"unknown lr_schedule {t.lr_schedule!r}")


def masked_decay(weight_decay: float):
    """Decoupled weight decay skipping biases and norm scales/shifts.

    Standard practice (and what torch AdamW users hand-configure via
    param groups); the reference decays everything (GPT2_Trainer.py:100).
    Default mask: NAME-based — dict keys in core/pytree.DECAY_KEYS
    (weight matrices, embedding tables) decay; everything else is
    skipped. Name-based because an ndim test misclassifies
    stacked-block leaves (a stacked bias is [L, out] = ndim 2).

    Under ZeRO the optimizer runs on a flat chunk where per-leaf
    masking cannot see parameter boundaries, so the transform also
    accepts an ELEMENTWISE ``decay_mask`` extra arg (optax extra-args
    protocol); parallel/zero.py ravels the SAME mask alongside the
    params and passes its chunk — the two paths are bit-identical
    (tests/test_optimizer.py).
    """
    from quintnet_tpu.core.pytree import decay_mask as default_mask

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params, *, decay_mask=None, **extra):
        del extra
        if params is None:
            raise ValueError("masked_decay requires params")
        if decay_mask is None:
            decay_mask = default_mask(params)
        updates = jax.tree.map(
            lambda u, p, m: u + weight_decay * m.astype(u.dtype) * p,
            updates, params, decay_mask)
        return updates, state

    return optax.GradientTransformationExtraArgs(init_fn, update_fn)


def make_optimizer(cfg: Config) -> optax.GradientTransformation:
    """Optimizer from config (reference: Adam in Trainer vs AdamW in
    GPT2Trainer — trainer.py:89 vs GPT2_Trainer.py:100; here one factory).
    zero1_* names shard the state over dp (parallel/zero.py). AdamW is
    built as scale_by_adam + masked_decay + lr so the decay composes
    with schedules exactly like optax.adamw (decay scaled by lr_t) while
    skipping LN/bias leaves."""
    t = cfg.training
    name = t.optimizer.lower()
    if name.startswith(("zero1_", "zero2_")):
        name = name[len("zero1_"):]
    lr = make_lr_schedule(cfg)
    # mu_dtype=bfloat16 halves the first-moment memory (planner: 'opt'
    # row); nu stays f32 (second moments span too many decades for bf16)
    mu = jnp.bfloat16 if t.adam_mu_dtype == "bfloat16" else None
    if name == "adam":
        return optax.adam(lr, mu_dtype=mu)
    if name == "adamw":
        return optax.chain(
            optax.scale_by_adam(mu_dtype=mu),
            masked_decay(0.01 if t.weight_decay is None
                         else t.weight_decay),
            optax.scale_by_learning_rate(lr),
        )
    if name == "sgd":
        return optax.sgd(lr)
    raise ValueError(f"unknown optimizer {t.optimizer!r}")


@dataclass
class History:
    train_loss: List[float] = field(default_factory=list)
    val_loss: List[float] = field(default_factory=list)
    train_metric: List[float] = field(default_factory=list)
    val_metric: List[float] = field(default_factory=list)
    wall_time_s: float = 0.0
    best_val_loss: float = float("inf")
    best_epoch: int = -1

    def to_jsonl(self, path: str):
        """One JSON line per epoch (loss/metrics) + a final summary line
        — greppable run record (the reference's only run record is
        stdout scrollback).

        Rewrites the whole file: safe because ``History`` is part of the
        checkpointed train cursor (ft/cursor.py), so after a restart the
        in-memory object holds the FULL run — pre-crash epochs included
        — and ``wall_time_s`` accumulates across restarts. (Before the
        cursor existed, this "w" open silently clobbered the pre-crash
        record with a fresh one.)"""
        import json

        with open(path, "w") as f:
            for i, tl in enumerate(self.train_loss):
                row = {"epoch": i, "train_loss": tl}
                for name, series in (("val_loss", self.val_loss),
                                     ("train_metric", self.train_metric),
                                     ("val_metric", self.val_metric)):
                    if i < len(series):
                        row[name] = series[i]
                f.write(json.dumps(row) + "\n")
            f.write(json.dumps({
                "wall_time_s": round(self.wall_time_s, 2),
                "best_val_loss": self.best_val_loss,
                "best_epoch": self.best_epoch}) + "\n")


def _call_batches_fn(fn, epoch: int, skip: int):
    """Call a train/val batches factory, passing the mid-epoch resume
    offset to factories that accept it.

    Returns ``(iterable, skip_consumed)``: the offset is handed to the
    factory ONLY when it declares a parameter literally named ``start``
    or ``start_batch`` (second positional, or keyword-only) — it then
    handles the skip itself (the map-style iterators in
    data/datasets.py slice the shuffled index — zero data touched).
    Matching by NAME, not arity, keeps unrelated two-argument factories
    (``lambda ep, shuffle=True: ...``) safe from a silently hijacked
    second parameter. Everything else gets the generic
    consume-and-discard skip in ``fit``. A matching offset parameter is
    passed even when the offset is 0, so it may be a required one.
    """
    names = ("start", "start_batch")
    try:
        import inspect

        ps = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):  # builtins/partials w/o signature
        ps = None
    if ps is not None:
        if (len(ps) >= 2
                and ps[1].kind in (ps[1].POSITIONAL_ONLY,
                                   ps[1].POSITIONAL_OR_KEYWORD)
                and ps[1].name in names):
            return fn(epoch, skip), True
        kw = next((p.name for p in ps
                   if p.kind == p.KEYWORD_ONLY and p.name in names), None)
        if kw is not None:
            return fn(epoch, **{kw: skip}), True
    return fn(epoch), False


class Trainer:
    """fit() over (x, y) batch iterables.

    ``task_type``: 'classification' (metric: accuracy, pp=1 only) or
    'clm' (metric: perplexity).
    """

    def __init__(self, config: Config, model: ModelSpec,
                 *, strategy: Optional[Strategy] = None,
                 optimizer: Optional[optax.GradientTransformation] = None,
                 task_type: str = "classification",
                 checkpoint_dir: Optional[str] = None,
                 log_fn: Callable[[str], None] = print):
        self.config = config
        self.model = model
        self.strategy = strategy or get_strategy(config.strategy_name, config)
        self.optimizer = optimizer or make_optimizer(config)
        self.task_type = task_type
        self.checkpoint_dir = checkpoint_dir
        self.log = log_fn
        if self.strategy.is_multiprocess and jax.process_index() != 0:
            # one SPMD log per job, not per host (reference: rank-0 tqdm
            # guards); checkpoint saves stay collective on every process
            self.log = lambda msg: None

        # recompile sentinel (analysis/recompile.py): observe-only — a
        # legit recompile exists (a differently-shaped final batch), but
        # each one is logged with the signature diff so shape drift is
        # named in the log, not guessed from a slow step
        from quintnet_tpu.analysis.recompile import RecompileSentinel

        self.step_fn = RecompileSentinel(
            "train.step",
            self.strategy.make_train_step(self.model, self.optimizer),
            on_recompile=self._on_recompile)
        self._eval_fn = None
        self._last_ckpt_step = None  # newest orbax step written/restored
        # steps the restore fallback proved unreadable: replay re-reaches
        # them and must REWRITE (save force=True), or the corrupt step
        # would shadow every future save attempt at that step and each
        # new preemption would fall back to the same old good step
        self._bad_ckpt_steps: set = set()
        # whether the newest checkpoint carries a mid-epoch cursor —
        # lets the epoch-boundary save heal a cadence save that landed
        # on the epoch's final batch (same global_step, boundary shape)
        self._last_ckpt_midepoch = False

    def _on_recompile(self, name: str, count: int, diff: str) -> None:
        self.log(f"{name}: lowering #{count} — {diff}")

    def assert_compile_count(self, steps: int = 1,
                             evals: Optional[int] = None) -> None:
        """Enforce the one-compiled-program promise after a run: the
        step (and optionally eval) function lowered exactly N times.
        Raises RecompileError with a signature diff otherwise."""
        self.step_fn.assert_compile_count(steps)
        if evals is not None and self._eval_fn is not None:
            self._eval_fn.assert_compile_count(evals)

    # -- state -------------------------------------------------------------
    def init_state(self, seed: Optional[int] = None):
        seed = self.config.training.seed if seed is None else seed
        host_params = self.model.init(jax.random.key(seed))
        params = self.strategy.shard_params(self.model, host_params)
        opt_state = self.strategy.init_opt_state(self.model, self.optimizer,
                                                 params)
        return params, opt_state

    def resume_or_init(self, seed: Optional[int] = None):
        """Epoch-level view of :meth:`resume_state` kept for callers that
        only schedule whole epochs. Returns (params, opt_state,
        start_epoch). A MID-EPOCH checkpoint (cadence save / emergency
        snapshot) cannot be expressed as an epoch boundary — handing it
        back as one would make an external epoch loop re-apply the
        epoch's first steps on top of params that already contain them —
        so this raises instead; drive the run through :meth:`fit`
        (step-granular resume) or :meth:`resume_state` in that case."""
        params, opt_state, cursor = self.resume_state(seed)
        if cursor is not None and cursor.step_in_epoch:
            raise RuntimeError(
                f"latest checkpoint is mid-epoch (epoch {cursor.epoch} "
                f"step {cursor.step_in_epoch}, global step "
                f"{cursor.global_step}); resume_or_init only hands back "
                "epoch boundaries — resume via Trainer.fit() "
                "(step-granular), or resume_state() and pass its cursor "
                "to fit(params=..., opt_state=..., cursor=...)")
        return params, opt_state, (cursor.epoch if cursor is not None else 0)

    def resume_state(self, seed: Optional[int] = None, *, goodput=None,
                     chaos=None):
        """Restore the newest checkpoint that loads (corrupt steps fall
        back to the previous good one — ft/restore.py), else fresh init.

        Returns ``(params, opt_state, cursor)`` where ``cursor`` is a
        :class:`~quintnet_tpu.ft.cursor.TrainCursor` pointing at the
        next (epoch, step) to run — None on fresh init. Checkpoints
        written before the cursor existed degrade to epoch granularity.
        """
        params, opt_state = self.init_state(seed)
        if not self.checkpoint_dir:
            return params, opt_state, None
        mgr = self._manager()
        if mgr.latest_step() is None:
            return params, opt_state, None
        from quintnet_tpu.ft.cursor import TrainCursor
        from quintnet_tpu.ft.restore import restore_with_fallback

        t_restore = time.time()
        state, cursor_dict, step, skipped = restore_with_fallback(
            mgr, {"params": params, "opt": opt_state, "epoch": 0},
            chaos=chaos, log=self.log)
        self._last_ckpt_step = step
        self._bad_ckpt_steps = set(skipped)
        cursor = TrainCursor.from_dict(cursor_dict)
        self._last_ckpt_midepoch = (cursor is not None
                                    and cursor.step_in_epoch != 0)
        if cursor is None:
            # legacy cursor-less checkpoint: orbax steps were EPOCH
            # indices. Anchor global_step at the restored index so new
            # (global-step-indexed) saves — including an emergency
            # snapshot on the very first resumed steps — sort strictly
            # after it and are never skipped by the save_state guard.
            cursor = TrainCursor(epoch=int(state["epoch"]) + 1,
                                 global_step=step)
        if goodput is not None:
            goodput.on_resume(cursor.global_step, time.time() - t_restore,
                              len(skipped))
        self.log(f"resumed from checkpoint step {step}: continuing at "
                 f"epoch {cursor.epoch} step {cursor.step_in_epoch} "
                 f"(global step {cursor.global_step})")
        return state["params"], state["opt"], cursor

    def _manager(self, *, best: bool = False):
        """Cached CheckpointManager(s) — one per directory, reused across
        epochs (a fresh manager per save re-lists the directory and
        resets orbax's async machinery)."""
        from quintnet_tpu.train.checkpoint import CheckpointManager

        if not hasattr(self, "_mgrs"):
            self._mgrs = {}
        key = "best" if best else "main"
        if key not in self._mgrs:
            self._mgrs[key] = (
                CheckpointManager(self.checkpoint_dir.rstrip("/") + "-best",
                                  max_to_keep=1) if best
                else CheckpointManager(self.checkpoint_dir))
        return self._mgrs[key]

    def save(self, epoch: int, params, opt_state):
        """Epoch-indexed save without a cursor — external callers that
        drive their own loop. ``fit`` itself saves via
        :meth:`save_state` (global-step indexed, cursor attached)."""
        if not self.checkpoint_dir:
            return
        # async: orbax snapshots device arrays before returning, then
        # writes in the background — the next epoch's compute overlaps
        # the IO. fit() barriers at the end (wait_for_saves).
        self._manager().save(
            epoch, {"params": params, "opt": opt_state, "epoch": epoch},
            wait=False)

    def save_state(self, params, opt_state, cursor, *,
                   wait: bool = False, boundary: bool = False) -> float:
        """Checkpoint arrays + train cursor at orbax step
        ``cursor.global_step``. Returns host-blocking seconds (goodput's
        checkpoint-overhead figure). Skips steps already on disk — a
        resumed run revisits the boundary it restored from (the state is
        identical by construction, rewriting it buys nothing) — with two
        exceptions: a step the restore fallback proved UNREADABLE is
        rewritten in place (force), and an epoch-boundary save
        (``boundary=True``) whose global step equals a just-written
        mid-epoch cadence save rewrites it synchronously so the newest
        on-disk cursor reflects the true epoch boundary
        (:meth:`resume_or_init` would otherwise refuse a run that in
        fact sits at one)."""
        if not self.checkpoint_dir:
            return 0.0
        step = cursor.global_step
        force = step in self._bad_ckpt_steps
        if self._last_ckpt_step is not None and step <= self._last_ckpt_step:
            heal = (boundary and step == self._last_ckpt_step
                    and self._last_ckpt_midepoch)
            if not heal:
                return 0.0
            # cadence landed on the epoch's final batch: same arrays,
            # but the cursor on disk is mid-epoch-shaped. Rewrite with
            # the boundary cursor (synchronous — the delete+rewrite
            # window must not outlive this call).
            force, wait = True, True
        t = time.time()
        # the state's "epoch" is the epoch the arrays were produced in
        # (end-of-epoch cursors already point at epoch+1) — what the
        # single-device verifiers report (tools/verify_vit.py)
        epoch = (cursor.epoch - 1 if cursor.step_in_epoch == 0
                 else cursor.epoch)
        self._manager().save(
            step, {"params": params, "opt": opt_state, "epoch": epoch},
            cursor=cursor.to_dict(), wait=wait, force=force)
        self._last_ckpt_step = step
        self._last_ckpt_midepoch = cursor.step_in_epoch != 0
        self._bad_ckpt_steps.discard(step)
        return time.time() - t

    def save_best(self, epoch: int, params, opt_state, val_loss: float):
        """Best-by-val-loss retention in a sibling ``<dir>-best``
        directory (one kept), alongside the rolling epoch saves —
        reference: best-and-final per-shard save, GPT2_Trainer.py:453-507.
        Sibling, not subdir, so orbax's step listing of the main
        directory never sees a non-numeric entry."""
        if not self.checkpoint_dir:
            return
        self._manager(best=True).save(
            epoch, {"params": params, "opt": opt_state, "epoch": epoch,
                    "val_loss": val_loss}, wait=False)

    def wait_for_saves(self):
        """Barrier on in-flight async checkpoint writes."""
        for mgr in getattr(self, "_mgrs", {}).values():
            mgr.wait_until_finished()

    # -- evaluation --------------------------------------------------------
    def _build_eval(self):
        """One jitted eval step returning ``{name: scalar}`` device
        metrics — loss always; accuracy for classification (incl. under
        pp, via the forward-only pipeline eval gathering last-stage
        metrics — the reference cannot report its headline 93.24% val
        accuracy under pp at all)."""
        if self._eval_fn is not None:
            return self._eval_fn
        from jax.sharding import PartitionSpec as P

        from quintnet_tpu.core import collectives as cc

        strat = self.strategy
        specs = strat.param_specs(self.model)
        tp_axis = strat.axis_or_none("tp")
        sp_axis = strat.axis_or_none("sp")
        ep_axis = strat.axis_or_none("ep")
        fsdp_kw = ({"fsdp_axis": strat.fsdp_axis}
                   if strat.fsdp_axis is not None else {})

        if strat.uses_pp:
            from quintnet_tpu.parallel.pp import (PipelineSpec,
                                                  make_afab_eval_fn)

            pspec = PipelineSpec(
                n_micro=self.config.training.gradient_accumulation_steps)
            if self.model.pipeline_eval_fns is not None:
                embed_fn, stage_fn, head_metrics_fn = \
                    self.model.pipeline_eval_fns(
                        tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis)
            else:
                from quintnet_tpu.parallel.pp import SplitHead

                embed_fn, stage_fn, head = self.model.pipeline_fns(
                    tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis)
                if isinstance(head, SplitHead):
                    head_metrics_fn = SplitHead(
                        head.local_fn,
                        lambda local, y, valid:
                            {"loss": head.reduce_fn(local, y, valid)})
                else:
                    def head_metrics_fn(p, h, y, _h=head):
                        return {"loss": _h(p, h, y)}

            metrics_fn = make_afab_eval_fn(
                embed_fn, stage_fn, head_metrics_fn, pspec)
        elif self.model.eval_metrics_fn is not None:
            def metrics_fn(p, b):
                return self.model.eval_metrics_fn(
                    p, b, tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis,
                    **fsdp_kw)
        else:
            def metrics_fn(p, b):
                return {"loss": self.model.loss_fn(
                    p, b, tp_axis=tp_axis, sp_axis=sp_axis, ep_axis=ep_axis,
                    **fsdp_kw)}

        def local_eval(p, b):
            mets = metrics_fn(p, b)
            if strat.batch_axes:
                mets = jax.tree.map(
                    lambda v: jax.lax.pmean(v, strat.batch_axes), mets)
            return mets

        from quintnet_tpu.analysis.recompile import RecompileSentinel

        batch_spec = strat.batch_partition_specs(self.model)
        # donate the batch: evaluate() ships a fresh device batch per
        # call and never touches it again, so its buffer can be freed
        # as soon as the forward consumes it instead of after the call
        # (the donation report flagged eval/validation loops as the
        # undonated ones — train steps already donate params/opt_state)
        self._eval_fn = RecompileSentinel(
            "train.eval",
            jax.jit(cc.shard_map_fn(
                local_eval, strat.mesh,
                in_specs=(specs, batch_spec),
                out_specs=P()), donate_argnums=(1,)),
            on_recompile=self._on_recompile)
        return self._eval_fn

    def evaluate(self, params, batches: Iterable) -> Dict[str, float]:
        import warnings

        eval_fn = self._build_eval()
        acc: Dict[str, list] = {}

        def fresh(v):
            # eval_fn donates the batch. For host inputs (the normal
            # case) shard_batch builds a new device buffer, so donation
            # is free; a DEVICE-resident input may pass through
            # device_put unchanged and donation would delete the
            # caller's array — copy those first (the copy is what
            # donation consumes).
            return jnp.copy(v) if isinstance(v, jax.Array) \
                else jnp.asarray(v)

        with warnings.catch_warnings():
            # metric outputs are scalars, so XLA cannot ALIAS the
            # donated batch and warns it went unaliased — expected;
            # scoped here so genuine donation mistakes elsewhere still
            # warn
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            for xb, yb in batches:
                b = self.strategy.shard_batch((fresh(xb), fresh(yb)),
                                              self.model)
                for k, v in eval_fn(params, b).items():
                    acc.setdefault(k, []).append(v)  # device scalars
        out = {k: float(np.mean([float(v) for v in vs]))
               for k, vs in acc.items()}
        out.setdefault("loss", float("nan"))
        if self.task_type == "clm":
            out["perplexity"] = float(np.exp(min(out["loss"], 20.0)))
        return out

    # -- training ----------------------------------------------------------
    def fit(self, train_batches_fn: Callable[[int], Iterable],
            *, epochs: Optional[int] = None,
            val_batches_fn: Optional[Callable[[int], Iterable]] = None,
            params=None, opt_state=None, cursor=None, ft=None) -> History:
        """``train_batches_fn(epoch) -> iterable of (x, y)`` host batches
        (global batch size; sharding happens here). A factory whose
        second parameter is named ``start`` or ``start_batch`` (second
        positional or keyword-only) receives the mid-epoch resume
        offset and lets map-style data skip to it for free
        (data/datasets.py ``start_batch=``); other factories are
        skipped generically (each skipped batch is materialised and
        discarded).

        Explicit state: ``fit(params=..., opt_state=...)`` skips the
        automatic resume; pass the matching ``cursor`` from
        :meth:`resume_state` to continue that state's run mid-stream
        (without one, the explicit state is treated as a FRESH run from
        epoch 0).

        ``ft``: optional :class:`~quintnet_tpu.ft.FTContext` wiring in
        preemption handling, fault injection, and goodput accounting.
        Step-granular cadence saves are controlled by
        ``training.save_every_steps`` / ``save_every_seconds`` and work
        with or without an ``ft`` context.
        """
        from quintnet_tpu.ft.cursor import TrainCursor
        from quintnet_tpu.ft.preempt import (CadenceController,
                                             TrainingPreempted)

        epochs = epochs or self.config.training.epochs
        if ft is not None and ft.preemption is not None \
                and not self.checkpoint_dir:
            # the preemption contract is "emergency snapshot saved, exit
            # 75, relaunch me" — without a checkpoint_dir the snapshot
            # writes nowhere and every relaunch would silently restart
            # from epoch 0 while the logs claim snapshots were saved
            raise ValueError(
                "FTContext.preemption requires a checkpoint_dir: a "
                "preemption snapshot with nowhere to write would make "
                "the exit-75 relaunch contract silently discard the run "
                "— pass checkpoint_dir= to Trainer, or drop the "
                "PreemptionHandler from the context")
        if params is None:
            params, opt_state, cursor = self.resume_state(
                goodput=ft.goodput if ft is not None else None,
                chaos=ft.chaos if ft is not None else None)
        elif cursor is None:
            # explicit fresh state: its trajectory owes nothing to
            # whatever checkpoint this trainer touched earlier — don't
            # let a stale high-water mark suppress its saves
            self._last_ckpt_step = None
        if cursor is None:
            cursor = TrainCursor(seed=self.config.training.seed)
        if (cursor.seed is not None
                and cursor.seed != self.config.training.seed):
            raise RuntimeError(
                f"checkpoint was written with training.seed="
                f"{cursor.seed} but the config now says "
                f"{self.config.training.seed}; dropout seeds and data "
                "order derive from the seed, so resuming would silently "
                "diverge from the original run — restore the original "
                "seed (or start a fresh run directory)")
        hist = cursor.history
        # wall_time_s accumulates across restarts: this process adds its
        # own elapsed time on top of what the cursor carried in
        prior_wall = hist.wall_time_s
        global_step = cursor.global_step
        start_epoch, resume_step = cursor.epoch, cursor.step_in_epoch
        t0 = time.time()
        log_every = self.config.training.log_every
        cadence = CadenceController(self.config.training.save_every_steps,
                                    self.config.training.save_every_seconds)
        # arm from the restored step: the state at global_step was just
        # read from disk, re-saving it one step later buys nothing
        cadence.saved(global_step)

        for epoch in range(start_epoch, epochs):
            # losses stay DEVICE scalars during the epoch — no per-step
            # host sync blocking async dispatch (the reference blocks on
            # .item() every step; so did round 1's float(loss)). Host
            # reads (flushes into the running epoch sum) happen only at
            # checkpoint boundaries and epoch end.
            losses = []
            skip = resume_step if epoch == start_epoch else 0
            # running float64 sum/count of this epoch's host-synced step
            # losses. Sequential f64 accumulation is the SAME computation
            # in an uninterrupted and a resumed run (JSON round-trips
            # binary64 exactly), so the epoch mean is bit-identical while
            # the cursor stays O(1) — no per-step list rides in it.
            loss_sum = cursor.loss_sum if skip else 0.0
            loss_count = cursor.loss_count if skip else 0
            n_flushed = 0

            def flush():
                nonlocal n_flushed, loss_sum, loss_count
                for dev_loss in losses[n_flushed:]:
                    # deliberate sync: flush runs only at checkpoint
                    # boundaries and epoch end, never per step
                    loss_sum += float(dev_loss)  # qtcheck: ok[QT104]
                    loss_count += 1
                n_flushed = len(losses)

            def cursor_at(next_epoch, next_step):
                hist.wall_time_s = prior_wall + (time.time() - t0)
                at_boundary = next_step == 0
                return TrainCursor(
                    epoch=next_epoch, step_in_epoch=next_step,
                    global_step=global_step,
                    # an epoch boundary starts the next epoch's record
                    # fresh; mid-epoch cursors carry the sum so far
                    loss_sum=0.0 if at_boundary else loss_sum,
                    loss_count=0 if at_boundary else loss_count,
                    history=hist, seed=self.config.training.seed)

            t_win = time.time()
            sync_every = self.config.training.sync_every
            batches, skip_consumed = _call_batches_fn(
                train_batches_fn, epoch, skip)
            if skip and not skip_consumed:
                from quintnet_tpu.data.datasets import skip_batches

                batches = skip_batches(batches, skip)
            if self.config.training.prefetch:
                from quintnet_tpu.data import prefetch_batches

                batches = prefetch_batches(
                    iter(batches), n=self.config.training.prefetch)
            for i, (xb, yb) in enumerate(batches, start=skip):
                batch = self.strategy.shard_batch(
                    (jnp.asarray(xb), jnp.asarray(yb)), self.model)
                # per-step dropout seed: deterministic in (config seed,
                # epoch, step) so a step-granular resume (ft/TrainCursor)
                # replays the exact same dropout sequence mid-epoch
                seed = (self.config.training.seed * 2_000_003
                        + epoch * 1_000_003 + i) & 0x7FFFFFFF
                params, opt_state, loss = self.step_fn(params, opt_state,
                                                       batch, seed)
                losses.append(loss)
                global_step += 1
                if sync_every and (i + 1) % sync_every == 0:
                    # bound async run-ahead (training.sync_every docs)
                    float(loss)  # qtcheck: ok[QT104] — windowed by design
                if log_every and (i + 1) % log_every == 0:
                    # the float() is the device sync for the window, so
                    # the wall clock measured here is honest throughput
                    window = float(  # qtcheck: ok[QT104] — window sync
                        jnp.mean(jnp.stack(losses[-log_every:])))
                    dt = time.time() - t_win
                    sps = log_every * len(xb) / max(dt, 1e-9)
                    msg = (f"epoch {epoch} step {i + 1}: "
                           f"loss {window:.4f} | {sps:.1f} samples/s")
                    if self.task_type == "clm":
                        msg += f" ({sps * xb.shape[1] / 1e3:.1f}k tok/s)"
                    self.log(msg)
                    t_win = time.time()
                # -- fault-tolerance boundary (after the step landed) --
                if ft is not None:
                    if ft.goodput is not None:
                        # the loss rides along so the meter can sync on
                        # the last step's device work before reading its
                        # wall clock (ft/goodput.py report)
                        ft.goodput.on_step(global_step, loss)
                    if ft.chaos is not None:
                        # may os._exit / SIGTERM self / raise ChaosKilled
                        ft.chaos.on_step_end(global_step)
                if ft is not None and ft.preemption_requested:
                    # finish-the-step-then-save: the in-flight step above
                    # already landed; one SYNCHRONOUS emergency snapshot
                    flush()
                    blocked = self.save_state(
                        params, opt_state, cursor_at(epoch, i + 1),
                        wait=True)
                    if ft.goodput is not None:
                        ft.goodput.on_save(blocked)
                    self.log(f"preempted: emergency snapshot at epoch "
                             f"{epoch} step {i + 1} (global step "
                             f"{global_step})")
                    raise TrainingPreempted(epoch, i + 1, global_step)
                if cadence.should_save(global_step):
                    flush()
                    blocked = self.save_state(
                        params, opt_state, cursor_at(epoch, i + 1))
                    if ft is not None and ft.goodput is not None:
                        ft.goodput.on_save(blocked)
                    cadence.saved(global_step)
            flush()
            # host-side sequential f64 mean (not a device jnp.mean):
            # identical value whether the epoch ran in one process or
            # resumed mid-way from the checkpointed running sum
            train_loss = (loss_sum / loss_count if loss_count
                          else float("nan"))
            hist.train_loss.append(train_loss)
            msg = f"epoch {epoch}: train_loss {train_loss:.4f}"
            if self.task_type == "clm":
                ppl = float(np.exp(min(train_loss, 20.0)))
                hist.train_metric.append(ppl)
                msg += f" ppl {ppl:.2f}"
            if val_batches_fn is not None:
                ev = self.evaluate(params, val_batches_fn(epoch))
                hist.val_loss.append(ev["loss"])
                msg += f" | val_loss {ev['loss']:.4f}"
                for k in ("perplexity", "accuracy"):
                    if k in ev:
                        hist.val_metric.append(ev[k])
                        msg += f" val_{k} {ev[k]:.4f}"
                if ev["loss"] < hist.best_val_loss:
                    hist.best_val_loss = ev["loss"]
                    hist.best_epoch = epoch
                    self.save_best(epoch, params, opt_state, ev["loss"])
                    msg += " (best)"
            self.log(msg)
            blocked = self.save_state(params, opt_state,
                                      cursor_at(epoch + 1, 0),
                                      boundary=True)
            if ft is not None and ft.goodput is not None:
                ft.goodput.on_save(blocked)
            cadence.saved(global_step)
            if ft is not None and ft.preemption_requested:
                # SIGTERM landed during eval / epoch-boundary work (the
                # per-step poll only sees it after a step): the state at
                # this boundary is already written above — barrier it to
                # disk and hand control to the supervisor instead of
                # starting an epoch we will not finish
                t_b = time.time()
                self.wait_for_saves()
                if ft.goodput is not None:
                    ft.goodput.on_save(time.time() - t_b)
                self.log(f"preempted: epoch {epoch} checkpoint durable "
                         f"(global step {global_step})")
                raise TrainingPreempted(epoch + 1, 0, global_step)

        t_barrier = time.time()
        self.wait_for_saves()
        if ft is not None and ft.goodput is not None:
            ft.goodput.on_save(time.time() - t_barrier)
        hist.wall_time_s = prior_wall + (time.time() - t0)
        self._final_state = (params, opt_state)
        return hist

    @property
    def final_state(self):
        """(params, opt_state) after the last fit() epoch."""
        return getattr(self, "_final_state", None)

"""Shared example plumbing: platform selection, config load, ViT runner.

Every example accepts the reference's YAML schema (examples/config.yaml)
and a ``--simulate N`` flag that swaps the real TPU for N virtual CPU
devices (the capability the reference lacks — it needs torchrun + GPUs
for every smoke test)."""

from __future__ import annotations

import argparse
import os


def parse_args(default_config: str):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default=default_config)
    ap.add_argument("--simulate", type=int, default=0,
                    help="run on N virtual CPU devices instead of TPU")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--limit", type=int, default=None,
                    help="cap train/val samples per epoch (smoke runs)")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--data-dir", default=None)
    add_multihost_args(ap)
    return ap.parse_args()


def add_multihost_args(ap):
    """Pod-scale launch flags (reference: torchrun env rendezvous,
    README.md:93-97). One process per host; on TPU pods --multihost
    alone auto-detects the slice topology."""
    ap.add_argument("--multihost", action="store_true",
                    help="jax.distributed.initialize() (TPU pod)")
    ap.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                    help="explicit coordinator (CPU/dev multi-process)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    return ap


def setup_platform(simulate: int, args=None):
    """Must run before first jax backend use."""
    multihost = args is not None and (args.multihost or args.coordinator)
    if simulate and not multihost:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={simulate}")
    import jax

    if multihost:
        from quintnet_tpu.core import runtime

        runtime.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
            local_device_count=simulate or None,
            platform="cpu" if simulate else None,
        )
        print(f"process {jax.process_index()}/{jax.process_count()}: "
              f"{jax.local_device_count()} local / "
              f"{jax.device_count()} global devices")
    elif simulate:
        jax.config.update("jax_platforms", "cpu")
    return jax


def run_vit(args, strategy_name: str):
    setup_platform(args.simulate, args)

    from quintnet_tpu.core.config import load_config
    from quintnet_tpu.data import ArrayDataset, load_mnist, make_batches
    from quintnet_tpu.models.vit import ViTConfig, vit_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy
    from quintnet_tpu.train.trainer import Trainer

    cfg = load_config(args.config)
    if args.epochs:
        cfg.training.epochs = args.epochs

    vcfg = ViTConfig.from_model_config(cfg.model)
    model = vit_model_spec(vcfg, remat=cfg.training.remat_mode)
    strategy = get_strategy(strategy_name, cfg)
    print(f"strategy={strategy.name} mesh={dict(strategy.mesh.shape)}")

    xtr, ytr = load_mnist(args.data_dir, split="train")
    xte, yte = load_mnist(args.data_dir, split="test")
    limit = getattr(args, "limit", None)
    if limit:
        xtr, ytr = xtr[:limit], ytr[:limit]
        xte, yte = xte[:limit], yte[:limit]
    train = ArrayDataset(xtr, ytr)
    test = ArrayDataset(xte, yte)
    bs = cfg.training.batch_size

    trainer = Trainer(cfg, model, strategy=strategy,
                      task_type="classification",
                      checkpoint_dir=args.checkpoint_dir)
    hist = trainer.fit(
        lambda ep: make_batches(train, bs, seed=ep),
        val_batches_fn=lambda ep: make_batches(test, bs, shuffle=False),
    )
    msg = (f"done in {hist.wall_time_s:.1f}s; "
           f"final train_loss {hist.train_loss[-1]:.4f}")
    if hist.val_metric:
        # reference headline metric (README.md:214: 93.24% val acc)
        msg += f"; final val_accuracy {hist.val_metric[-1]:.4f}"
    print(msg)
    return hist

"""Long-context training walkthrough: sequence parallelism end to end.

The reference caps context at 1024 tokens with a single local SDPA call
per rank (SURVEY.md §5.7 — no ring attention, no sequence sharding
anywhere). Here one flag choice shards the SEQUENCE dim of every
activation over the ``sp`` mesh axis and runs exact attention across the
shards:

    ring    — K/V blocks rotate via ppermute; online-softmax exact
    zigzag  — load-balanced causal ring (~2x less idle compute)
    ulysses — all-to-all head scatter; composes with the flash kernel

Memory per device for activations scales 1/sp, so an sp=8 mesh trains
8x the context of one device at the same activation footprint — this is
the capability that lets the framework run sequence lengths the
reference cannot represent at all.

Run (8 virtual devices, GPT-2-tiny, seq 2048 sharded 256/device):

    python -m quintnet_tpu.examples.long_context --simulate 8
    python -m quintnet_tpu.examples.long_context --simulate 8 \
        --seq 4096 --sp-mode zigzag

The SERVING side of the same workload (``--serve``): a document-length
prompt — longer than the engine's whole compiled prefill window — is
round-tripped through the chunked-prefill serving engine
(serve/longctx.py): admitted whole, streamed through bucket-sized
chunks under a per-step token budget, output bit-identical to a
widened single-shot engine. With ``--simulate N`` the chunks
additionally run ring-attention sequence-parallel over the N devices:

    python -m quintnet_tpu.examples.long_context --serve
    python -m quintnet_tpu.examples.long_context --serve --simulate 2
"""

from __future__ import annotations

import argparse
import time


def serve_demo(args):
    """Chunked-prefill serving smoke: one long prompt end to end."""
    import jax
    import numpy as np

    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
    from quintnet_tpu.serve import ServeEngine, generate, gpt2_family

    cfg = GPT2Config.tiny(n_layer=2, n_positions=1024)
    params = gpt2_init(jax.random.key(0), cfg)
    family = gpt2_family(cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size,
                          (args.serve_prompt,)).astype(np.int32)
    key = jax.random.key(1)

    window, budget = 64, 64
    kw = {}
    sp = args.simulate or 1
    if sp > 1:
        from jax.sharding import Mesh

        kw = dict(mesh=Mesh(np.array(jax.devices()[:sp]), ("sp",)),
                  sp_axis="sp")
    chunked = ServeEngine(
        family, params, max_slots=4, block_size=16, num_blocks=128,
        max_seq_len=cfg.n_positions, prefill_len=window,
        chunked_prefill=True, prefill_chunk_budget=budget, **kw)
    print(f"prompt {len(prompt)} tokens vs prefill window {window} "
          f"(top bucket {chunked.prefill_buckets[-1]}), chunk budget "
          f"{budget}/step, sp={sp}")
    t0 = time.perf_counter()
    out = generate(chunked, [prompt], max_new_tokens=args.serve_new,
                   keys=[key], max_steps=2000)[0]
    jax.block_until_ready(chunked.pool.caches())
    dt = time.perf_counter() - t0
    m = chunked.metrics
    print(f"served in {m.steps} engine steps / {dt:.2f}s: "
          f"{m.prefill_chunks} chunks, "
          f"{m.chunk_tokens_per_step:.1f} chunk tokens/step (<= "
          f"{budget} by construction)")

    # the golden contract, demonstrated: a widened single-bucket
    # engine given the same tokens + key produces the same bits
    wide = ServeEngine(family, params, max_slots=4, block_size=16,
                       num_blocks=128, max_seq_len=cfg.n_positions)
    want = generate(wide, [prompt], max_new_tokens=args.serve_new,
                    keys=[key])[0]
    same = bool(np.array_equal(out, want))
    print(f"bit-identical to single-shot widened engine: {same}")
    print("generated:", out[len(prompt):].tolist())
    if not same:
        raise SystemExit("chunked output diverged from single-shot")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--simulate", type=int, default=None,
                    help="virtual CPU devices (= sp size); training "
                         "default 8, --serve default 1 (plain chunked "
                         "engine — pass N > 1 for sp-parallel chunks)")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--sp-mode", default="ring",
                    choices=["ring", "zigzag", "ulysses"])
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--serve", action="store_true",
                    help="serving smoke: round-trip one document-length "
                         "prompt through the chunked-prefill engine "
                         "(serve/longctx.py) instead of training")
    ap.add_argument("--serve-prompt", type=int, default=384,
                    help="--serve prompt length (tokens)")
    ap.add_argument("--serve-new", type=int, default=8,
                    help="--serve generated tokens")
    args = ap.parse_args()

    from quintnet_tpu.examples.common import setup_platform

    if args.serve:
        setup_platform(max(args.simulate or 1, 1))
        serve_demo(args)
        return

    if args.simulate is None:
        args.simulate = 8
    setup_platform(args.simulate)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy

    sp = args.simulate
    cfg = Config.from_dict({
        "mesh_dim": [sp], "mesh_name": ["sp"],
        "training": {"batch_size": args.batch, "sp_mode": args.sp_mode,
                     "optimizer": "adamw", "grad_clip_norm": 1.0},
    })
    # ulysses scatters HEADS over sp (all-to-all), so it needs
    # n_head % sp == 0; ring/zigzag shard the sequence only. Give the
    # tiny model enough heads to cover the mesh.
    n_head = max(4, sp) if args.sp_mode == "ulysses" else 4
    if args.sp_mode == "ulysses" and n_head % sp:
        ap.error(f"--sp-mode ulysses needs n_head ({n_head}) divisible "
                 f"by the sp mesh size ({sp})")
    gcfg = GPT2Config.tiny(n_layer=2, n_head=n_head,
                           n_positions=args.seq)
    model = gpt2_model_spec(gcfg, sp_mode=args.sp_mode)
    strat = get_strategy("sp", cfg)
    print(f"mesh sp={sp}, seq {args.seq} -> {args.seq // sp}/device, "
          f"sp_mode={args.sp_mode}")

    opt = optax.adamw(1e-3)
    params = strat.shard_params(model, model.init(jax.random.key(0)))
    opt_state = strat.init_opt_state(model, opt, params)
    ids = np.random.default_rng(0).integers(
        0, gcfg.vocab_size, (args.batch, args.seq), dtype=np.int32)
    batch = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    step = strat.make_train_step(model, opt)

    for i in range(args.steps):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, batch)
        # sync before reading the clock: dt must measure device work,
        # not dispatch (qtcheck QT106)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        note = " (compile)" if i == 0 else ""
        loss_v = float(loss)  # qtcheck: ok[QT104] — per-step demo print
        print(f"step {i}: loss {loss_v:.4f}  {dt:.2f}s{note}")
    print("done — every attention op ran sequence-parallel across "
          f"{sp} devices; the [S, S] score matrix never existed on any "
          "one of them")


if __name__ == "__main__":
    main()

"""Long-context training walkthrough: sequence parallelism end to end.

The reference caps context at 1024 tokens with a single local SDPA call
per rank (SURVEY.md §5.7 — no ring attention, no sequence sharding
anywhere). Here one flag choice shards the SEQUENCE dim of every
activation over the ``sp`` mesh axis and runs exact attention across the
shards:

    ring    — K/V blocks rotate via ppermute; online-softmax exact
    zigzag  — load-balanced causal ring (~2x less idle compute)
    ulysses — all-to-all head scatter; composes with the flash kernel

Memory per device for activations scales 1/sp, so an sp=8 mesh trains
8x the context of one device at the same activation footprint — this is
the capability that lets the framework run sequence lengths the
reference cannot represent at all.

Run (8 virtual devices, GPT-2-tiny, seq 2048 sharded 256/device):

    python -m quintnet_tpu.examples.long_context --simulate 8
    python -m quintnet_tpu.examples.long_context --simulate 8 \
        --seq 4096 --sp-mode zigzag
"""

from __future__ import annotations

import argparse
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--simulate", type=int, default=8,
                    help="virtual CPU devices (= sp size)")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--sp-mode", default="ring",
                    choices=["ring", "zigzag", "ulysses"])
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--batch", type=int, default=2)
    args = ap.parse_args()

    from quintnet_tpu.examples.common import setup_platform

    setup_platform(args.simulate)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy

    sp = args.simulate
    cfg = Config.from_dict({
        "mesh_dim": [sp], "mesh_name": ["sp"],
        "training": {"batch_size": args.batch, "sp_mode": args.sp_mode,
                     "optimizer": "adamw", "grad_clip_norm": 1.0},
    })
    # ulysses scatters HEADS over sp (all-to-all), so it needs
    # n_head % sp == 0; ring/zigzag shard the sequence only. Give the
    # tiny model enough heads to cover the mesh.
    n_head = max(4, sp) if args.sp_mode == "ulysses" else 4
    if args.sp_mode == "ulysses" and n_head % sp:
        ap.error(f"--sp-mode ulysses needs n_head ({n_head}) divisible "
                 f"by the sp mesh size ({sp})")
    gcfg = GPT2Config.tiny(n_layer=2, n_head=n_head,
                           n_positions=args.seq)
    model = gpt2_model_spec(gcfg, sp_mode=args.sp_mode)
    strat = get_strategy("sp", cfg)
    print(f"mesh sp={sp}, seq {args.seq} -> {args.seq // sp}/device, "
          f"sp_mode={args.sp_mode}")

    opt = optax.adamw(1e-3)
    params = strat.shard_params(model, model.init(jax.random.key(0)))
    opt_state = strat.init_opt_state(model, opt, params)
    ids = np.random.default_rng(0).integers(
        0, gcfg.vocab_size, (args.batch, args.seq), dtype=np.int32)
    batch = strat.shard_batch((jnp.asarray(ids), jnp.asarray(ids)), model)
    step = strat.make_train_step(model, opt)

    for i in range(args.steps):
        t0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, batch)
        # sync before reading the clock: dt must measure device work,
        # not dispatch (qtcheck QT106)
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        note = " (compile)" if i == 0 else ""
        loss_v = float(loss)  # qtcheck: ok[QT104] — per-step demo print
        print(f"step {i}: loss {loss_v:.4f}  {dt:.2f}s{note}")
    print("done — every attention op ran sequence-parallel across "
          f"{sp} devices; the [S, S] score matrix never existed on any "
          "one of them")


if __name__ == "__main__":
    main()

"""PP-only ViT-MNIST walkthrough (reference examples/simple_pp.py).

Run:  python -m quintnet_tpu.examples.simple_pp [--simulate 8]
"""

from quintnet_tpu.examples.common import parse_args, run_vit
import os

if __name__ == "__main__":
    here = os.path.dirname(__file__)
    args = parse_args(os.path.join(here, "pp_config.yaml"))
    run_vit(args, "pp")

"""GPT-2 summarization finetune over a 3D mesh
(reference examples/gpt2_finetune.py:37-254).

Run:  python -m quintnet_tpu.examples.gpt2_finetune \
          [--simulate 8] [--checkpoint path/to/hf/model.safetensors] \
          [--csv cnn_dailymail.csv]

Without --checkpoint the model starts from random init (useful for
schedule/throughput work); without --csv a synthetic summarization set
stands in (no network egress in this environment). With a HF tokenizer
directory (--tokenizer) it tokenises like the reference; otherwise a
byte-level tokenizer is used.
"""

from __future__ import annotations

import argparse
import os


def main():
    ap = argparse.ArgumentParser()
    here = os.path.dirname(__file__)
    ap.add_argument("--config", default=os.path.join(here, "gpt2_config.yaml"))
    ap.add_argument("--simulate", type=int, default=0)
    ap.add_argument("--checkpoint", default=None,
                    help="HF gpt2 model.safetensors to start from")
    ap.add_argument("--tokenizer", default=None,
                    help="HF tokenizer dir (GPT2Tokenizer.from_pretrained)")
    ap.add_argument("--csv", default=None, help="article/highlights CSV")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--tiny", action="store_true",
                    help="use a tiny GPT-2 (smoke/sim runs)")
    ap.add_argument("--experts", type=int, default=0,
                    help="n_experts: turn the model into a GPT-2-MoE "
                         "(shard them with an 'ep' mesh axis)")
    ap.add_argument("--gen-eval", type=int, default=0, metavar="N",
                    help="after training, generate summaries for "
                         "N val samples (KV-cache decoder) and report "
                         "ROUGE-1/2/L + BLEU (greedy unless --gen-temp)")
    ap.add_argument("--gen-temp", type=float, default=0.0,
                    help="sampling temperature for --gen-eval (0=greedy)")
    ap.add_argument("--gen-top-k", type=int, default=0)
    ap.add_argument("--gen-top-p", type=float, default=1.0)
    ap.add_argument("--gen-beams", type=int, default=1,
                    help="beam width for --gen-eval (beats greedy on "
                         "summary likelihood; single-device decode)")
    from quintnet_tpu.examples.common import add_multihost_args

    add_multihost_args(ap)
    args = ap.parse_args()

    from quintnet_tpu.examples.common import setup_platform

    setup_platform(args.simulate, args)

    import jax

    from quintnet_tpu.core.config import load_config
    from quintnet_tpu.data import ByteTokenizer, SummarizationDataset
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec
    from quintnet_tpu.models.gpt2_io import load_hf_gpt2
    from quintnet_tpu.parallel.strategy import get_strategy
    from quintnet_tpu.train.trainer import Trainer, make_optimizer

    cfg = load_config(args.config)
    if args.epochs:
        cfg.training.epochs = args.epochs

    if args.tokenizer:
        from transformers import GPT2Tokenizer

        tok = GPT2Tokenizer.from_pretrained(args.tokenizer)
        tok.pad_token = tok.eos_token
    else:
        tok = ByteTokenizer()

    if args.tiny:
        # vocab must cover the tokenizer (OOB ids NaN-fill under jit);
        # round up to a lane-friendly multiple of 8
        v = -(-max(getattr(tok, "vocab_size", 257), 128) // 8) * 8
        gcfg = GPT2Config.tiny(vocab_size=v)
    else:
        gcfg = GPT2Config.from_dict(
            {**cfg.model.extra, **{k: v for k, v in vars(cfg.model).items()
                                   if not isinstance(v, dict)}})
    if args.experts:
        import dataclasses as _dc

        gcfg = _dc.replace(gcfg, n_experts=args.experts)
    if cfg.training.scan_unroll != 1 and gcfg.scan_unroll == 1:
        import dataclasses as _dc

        gcfg = _dc.replace(gcfg, scan_unroll=cfg.training.scan_unroll)

    max_len = int(cfg.data.get("max_seq_length", 512))
    if args.tiny:
        max_len = min(max_len, gcfg.n_positions)
    if args.csv:
        train_ds = SummarizationDataset.from_csv(
            args.csv, tok, max_length=max_len,
            limit=cfg.data.get("train_samples"))
        val_ds = SummarizationDataset.from_csv(
            args.csv, tok, max_length=max_len,
            limit=cfg.data.get("val_samples"))
    else:
        train_ds = SummarizationDataset.synthetic(
            int(cfg.data.get("train_samples", 1024)), tok, max_length=max_len)
        val_ds = SummarizationDataset.synthetic(
            max(int(cfg.data.get("val_samples", 128)),
                cfg.training.batch_size),  # >= one global batch
            tok, max_length=max_len, seed=1)

    import jax.numpy as jnp

    if cfg.training.dtype not in ("bfloat16", "float32"):
        raise ValueError(
            f"training.dtype must be 'bfloat16' or 'float32', "
            f"got {cfg.training.dtype!r}")
    compute_dtype = (jnp.bfloat16 if cfg.training.dtype == "bfloat16"
                     else None)
    model = gpt2_model_spec(gcfg, remat=cfg.training.remat_mode,
                            sp_mode=cfg.training.sp_mode,
                            compute_dtype=compute_dtype)
    strategy = get_strategy(cfg.strategy_name, cfg)
    print(f"strategy={strategy.name} mesh={dict(strategy.mesh.shape)} "
          f"gpt2 n_layer={gcfg.n_layer} n_embd={gcfg.n_embd}")

    trainer = Trainer(cfg, model, strategy=strategy, task_type="clm",
                      checkpoint_dir=args.checkpoint_dir)

    if args.checkpoint_dir and jax.process_index() == 0:
        # record the model geometry next to the checkpoints so post-run
        # tools (pod_run merge-test / export_gpt2) can rebuild the
        # restore template without re-supplying flags
        import dataclasses as _dc
        import json as _json

        os.makedirs(args.checkpoint_dir, exist_ok=True)
        with open(os.path.join(args.checkpoint_dir,
                               "model_config.json"), "w") as f:
            _json.dump({"family": "gpt2", "tp_layout": cfg.tp_size,
                        **_dc.asdict(gcfg)}, f, indent=1)

    if args.checkpoint:
        host_params, _ = load_hf_gpt2(args.checkpoint, gcfg)
        if gcfg.n_experts > 0:
            # HF checkpoints are dense; sparse-upcycle into the MoE
            from quintnet_tpu.models.gpt2 import gpt2_upcycle_to_moe

            host_params = gpt2_upcycle_to_moe(host_params, gcfg)
        params = strategy.shard_params(model, host_params)
        opt_state = strategy.init_opt_state(model, trainer.optimizer, params)
    else:
        params, opt_state = trainer.init_state()

    bs = cfg.training.batch_size
    hist = trainer.fit(
        lambda ep: train_ds.batches(bs, seed=ep),
        val_batches_fn=lambda ep: val_ds.batches(bs, shuffle=False),
        params=params, opt_state=opt_state,
    )
    print(f"done in {hist.wall_time_s:.1f}s; "
          f"train_loss {hist.train_loss[-1]:.4f}")

    if args.gen_eval:
        # single-device generation eval on the trained weights
        # (reference: optional ROUGE/BLEU pass, GPT2_Trainer.py:509-555,
        # skipped under PP there; here any mesh works — params are
        # gathered to host and de-TP-layouted first)
        from quintnet_tpu.models.gpt2 import gpt2_from_tp_layout
        from quintnet_tpu.train.metrics import evaluate_generation

        host = jax.device_get(trainer.final_state[0])
        host = gpt2_from_tp_layout(host, gcfg, cfg.tp_size)
        max_prompt = max(max_len // 2, 8)
        prompts = val_ds.eval_prompts(
            max_prompt_len=max_prompt, limit=args.gen_eval)
        # clamp against the ACTUAL max prompt length so prompt+new never
        # exceeds n_positions (tiny configs have max_len//2 < 8)
        scores = evaluate_generation(
            host, gcfg, prompts, tok,
            max_new_tokens=min(64, gcfg.n_positions - max_prompt),
            eos_token_id=getattr(tok, "eos_token_id", None),
            temperature=args.gen_temp, top_k=args.gen_top_k,
            top_p=args.gen_top_p, beams=args.gen_beams,
            key=jax.random.key(cfg.training.seed) if args.gen_temp
            else None)
        print("generation eval:",
              {k: round(v, 4) for k, v in scores.items()})
    return hist


if __name__ == "__main__":
    main()

"""DP-only ViT-MNIST walkthrough (reference examples/simple_dp.py).

Run:  python -m quintnet_tpu.examples.simple_dp [--simulate 8]
"""

from quintnet_tpu.examples.common import parse_args, run_vit
import os

if __name__ == "__main__":
    here = os.path.dirname(__file__)
    args = parse_args(os.path.join(here, "dp_config.yaml"))
    run_vit(args, "dp")

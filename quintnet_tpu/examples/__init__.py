"""Entry-point examples (reference: examples/*.py run via torchrun;
here: plain python, optionally with --simulate N for a CPU mesh)."""

"""LoRA finetuning walkthrough: train rank-r adapters over a frozen
GPT-2, then merge and generate.

The reference finetunes every weight (GPT2_Trainer.py — optimizer state
for all 124M params); here Adam state exists only for the adapters
(<1% of the model at r=8), and the merged model is a plain GPT-2 again.

Run (CPU ok):
    python -m quintnet_tpu.examples.lora_finetune --steps 30
    python -m quintnet_tpu.examples.lora_finetune --rank 16 --targets qkv
"""

from __future__ import annotations

import argparse
import time
from functools import partial


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--alpha", type=float, default=16.0)
    ap.add_argument("--lr", type=float, default=1e-2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--targets", nargs="+",
                    default=["qkv", "proj", "fc"])
    ap.add_argument("--simulate", type=int, default=1,
                    help="run on N virtual CPU devices (0 = real "
                         "accelerator backend)")
    args = ap.parse_args()

    from quintnet_tpu.examples.common import setup_platform

    setup_platform(args.simulate)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from quintnet_tpu.models.gpt2 import (GPT2Config, clm_loss, gpt2_apply,
                                          gpt2_init)
    from quintnet_tpu.models.lora import (LoRAConfig, lora_init,
                                          lora_merge_tree, lora_param_count,
                                          lora_wrap)

    cfg = GPT2Config.tiny(n_positions=max(64, args.seq))
    params = gpt2_init(jax.random.key(0), cfg)
    lcfg = LoRAConfig(rank=args.rank, alpha=args.alpha,
                      targets=tuple(args.targets))
    lora = lora_init(jax.random.key(1), params["blocks"], lcfg)

    n_base = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    n_lora = lora_param_count(lora)
    print(f"base {n_base/1e6:.2f}M params frozen; "
          f"training {n_lora/1e3:.1f}k adapter params "
          f"({100*n_lora/n_base:.2f}%) at rank {args.rank}")

    fwd = lora_wrap(lambda p, ids: gpt2_apply(p, ids, cfg), params, lcfg)
    opt = optax.adam(args.lr)
    opt_state = opt.init(lora)

    # toy objective: reproduce a fixed synthetic batch
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (args.batch, args.seq), dtype=np.int32))

    # donate (lora, opt_state): both alias the step's outputs, so the
    # adapter update runs in place instead of double-buffering
    @partial(jax.jit, donate_argnums=(0, 1))
    def step(lora, opt_state):
        loss, g = jax.value_and_grad(
            lambda l: clm_loss(fwd(l, ids), ids))(lora)
        up, opt_state = opt.update(g, opt_state, lora)
        return optax.apply_updates(lora, up), opt_state, loss

    t0 = time.perf_counter()
    for i in range(args.steps):
        lora, opt_state, loss = step(lora, opt_state)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")  # qtcheck: ok[QT104]
    # sync before reading the clock (qtcheck QT106): the loop above can
    # run ahead of the device by many dispatched steps
    jax.block_until_ready(loss)
    print(f"{args.steps} adapter steps in {time.perf_counter()-t0:.1f}s")

    from quintnet_tpu.models.gpt2_generate import gpt2_generate

    # persist + reload through the safetensors round-trip BEFORE the
    # merged-generate check — this is the exact artifact the serving
    # AdapterRegistry consumes (serve/adapters.py), so the example
    # exercises the file a tenant would actually deploy
    import os
    import tempfile

    from quintnet_tpu.models.lora import load_lora, save_lora

    path = os.path.join(tempfile.mkdtemp(prefix="lora_"),
                        "adapters.safetensors")
    save_lora(lora, lcfg, path)
    lora, lcfg = load_lora(path)
    print(f"saved + reloaded adapters via {path} "
          f"({os.path.getsize(path)} bytes)")

    merged = lora_merge_tree(params, lora, lcfg)
    out = gpt2_generate(merged, np.asarray(ids[:1, :8]), cfg,
                        max_new_tokens=8)
    print(f"merged model generated {out.shape[1] - 8} tokens ok")


if __name__ == "__main__":
    main()

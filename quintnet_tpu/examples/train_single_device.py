"""Single-device ViT baseline (reference examples/train_on_single_gpu.py)."""

from quintnet_tpu.examples.common import parse_args, run_vit
import os

if __name__ == "__main__":
    here = os.path.dirname(__file__)
    args = parse_args(os.path.join(here, "dp_config.yaml"))
    # force a 1-device mesh regardless of the config's mesh_dim
    from quintnet_tpu.core.config import load_config
    import tempfile, yaml
    cfg = yaml.safe_load(open(args.config))
    cfg["mesh_dim"], cfg["mesh_name"] = [1], ["dp"]
    with tempfile.NamedTemporaryFile("w", suffix=".yaml", delete=False) as f:
        yaml.safe_dump(cfg, f)
        args.config = f.name
    run_vit(args, "auto")

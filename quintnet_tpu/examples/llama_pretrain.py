"""Llama pretraining walkthrough: packed CLM data + any mesh.

Demonstrates the round-4 additions end to end: the Llama family
(models/llama.py) training under the generic Trainer with
concat-and-chunk packed sequences (zero pad waste, data/datasets.py),
cosine LR schedule, ZeRO-2 AdamW, optional tp/sp axes.

Run (CPU ok):
    python -m quintnet_tpu.examples.llama_pretrain --simulate 4
    python -m quintnet_tpu.examples.llama_pretrain --simulate 8 \
        --mesh dp2,tp2,sp2 --epochs 2
"""

from __future__ import annotations

import argparse
import re


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--simulate", type=int, default=4)
    ap.add_argument("--mesh", default=None,
                    help="e.g. dp2,tp2 (default: all devices on dp)")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--docs", type=int, default=512,
                    help="synthetic documents to pack")
    ap.add_argument("--experts", type=int, default=0,
                    help="n_experts: Mixtral-style SwiGLU-MoE blocks "
                         "(add an 'ep' axis to --mesh to shard them)")
    ap.add_argument("--fsdp", action="store_true",
                    help="ZeRO-3: store block params dp-sharded, gather "
                         "per layer in the scan (training.fsdp)")
    ap.add_argument("--isolate-docs", action="store_true",
                    help="mask cross-document attention in the packed "
                         "rows (segment ids derived from the EOS "
                         "separator; default: GPT-2-style cross-doc "
                         "attention). Works under any mesh incl. sp "
                         "(sp-aware segment ids are golden-tested).")
    args = ap.parse_args()

    from quintnet_tpu.examples.common import setup_platform

    setup_platform(args.simulate)

    import jax
    import numpy as np

    from quintnet_tpu.core.config import Config
    from quintnet_tpu.data import ByteTokenizer, PackedLMDataset
    from quintnet_tpu.models.llama import LlamaConfig, llama_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy
    from quintnet_tpu.train.trainer import Trainer

    if args.mesh:
        names, dims = [], []
        for part in args.mesh.split(","):
            m = re.fullmatch(r"([a-z]+)(\d+)", part)
            if not m:
                ap.error(f"bad --mesh part {part!r} (want e.g. dp2,tp2)")
            names.append(m.group(1))
            dims.append(int(m.group(2)))
    else:
        names, dims = ["dp"], [args.simulate or 1]

    cfg = Config.from_dict({
        "mesh_dim": dims, "mesh_name": names,
        "training": {
            "batch_size": args.batch, "epochs": args.epochs,
            "optimizer": ("adamw" if args.fsdp else "zero2_adamw"),
            "learning_rate": 3e-3,
            "lr_schedule": "cosine", "warmup_steps": 10,
            "decay_steps": 200, "grad_clip_norm": 1.0,
            "sp_mode": "zigzag", "log_every": 20,
            "fsdp": args.fsdp,
        },
    })
    # vocab 257+pad to 264 covers the byte tokenizer; n_kv < n_heads
    # exercises GQA under whatever mesh was picked
    tok_eos = 256  # ByteTokenizer.eos_token_id
    lcfg = LlamaConfig.tiny(vocab_size=264, n_positions=args.seq,
                            dim=64, n_layers=4, n_heads=4, n_kv_heads=2,
                            intermediate_size=128,
                            n_experts=args.experts,
                            segment_eos_id=(tok_eos if args.isolate_docs
                                            else None))
    model = llama_model_spec(lcfg, sp_mode="zigzag")
    strat = get_strategy("auto", cfg)
    print(f"mesh={dict(strat.mesh.shape)} llama dim={lcfg.dim} "
          f"L={lcfg.n_layers} gqa {lcfg.n_heads}/{lcfg.n_kv_heads}")

    tok = ByteTokenizer()
    rng = np.random.default_rng(0)
    words = ["the", "quick", "brown", "fox", "jumps", "over", "lazy",
             "dogs", "while", "packing", "sequences", "tightly"]
    texts = [" ".join(rng.choice(words, size=rng.integers(8, 40)))
             for _ in range(args.docs)]
    ds = PackedLMDataset.from_texts(texts, tok, seq_len=args.seq)
    print(f"packed {args.docs} docs -> {len(ds)} rows x {args.seq} "
          "tokens, zero padding")

    trainer = Trainer(cfg, model, strategy=strat, task_type="clm")
    hist = trainer.fit(lambda ep: ds.batches(args.batch, seed=ep))
    print(f"done in {hist.wall_time_s:.1f}s; "
          f"loss {hist.train_loss[0]:.3f} -> {hist.train_loss[-1]:.3f}")


if __name__ == "__main__":
    main()

"""Full 3D (DPxTPxPP) ViT-MNIST training (reference examples/full_3d.py).

Run:  python -m quintnet_tpu.examples.full_3d [--simulate 8]
"""

from quintnet_tpu.examples.common import parse_args, run_vit
import os

if __name__ == "__main__":
    here = os.path.dirname(__file__)
    args = parse_args(os.path.join(here, "config.yaml"))
    run_vit(args, "3d")

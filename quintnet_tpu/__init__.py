"""QuintNet-TPU: a TPU-native 5D-parallel training framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
QuintNet library (pure-Python PyTorch + NCCL 3D parallelism; see
/root/reference). Instead of process groups, autograd-wrapped NCCL
collectives, and in-place ``nn.Linear`` rewriting, this framework uses:

- one ``jax.sharding.Mesh`` with named axes (``dp``, ``tp``, ``pp``, ``sp``)
  instead of ``MeshGenerator`` + ``ProcessGroupManager``
  (reference: core/mesh.py:124, core/process_groups.py:42);
- ``jax.lax`` collectives under ``shard_map`` — differentiable by
  construction — instead of hand-written autograd Functions
  (reference: core/communication.py:46-600);
- sharding rules on parameter pytrees instead of module surgery
  (reference: parallelism/tensor_parallel/model_wrapper.py:37);
- ``lax.scan`` + ``ppermute`` pipeline schedules instead of batched
  isend/irecv P2P (reference: parallelism/pipeline_parallel/schedule.py);
- a single grad ``psum`` over the ``dp`` axis instead of DDP gradient
  bucketing (reference: parallelism/data_parallel/ddp.py:49).

Capabilities beyond the reference: sequence parallelism / ring attention
for long context, ZeRO-1/2 optimizer sharding (reference stubs:
optimizers/zero.py), Pallas TPU kernels, profiling, and a simulated
multi-device test story that needs no real multi-host hardware.
"""

__version__ = "0.2.0"

from quintnet_tpu.core import compat as _compat  # installs jax shims

_compat.install()

from quintnet_tpu.core.config import Config, load_config
from quintnet_tpu.core.mesh import MeshSpec, build_mesh

__all__ = [
    "Config",
    "load_config",
    "MeshSpec",
    "build_mesh",
    "__version__",
]

"""Minimal pure-Python safetensors reader/writer.

The reference reads HF GPT-2 shards with ``safetensors.safe_open`` +
``get_slice`` so each rank touches only its bytes
(core/distributed_loading.py:262-374). This module reimplements the
format (8-byte LE header length, JSON header with dtype/shape/
data_offsets, raw row-major payload) with numpy + mmap so:

- no dependency on the safetensors package;
- :class:`SafeTensorFile` exposes zero-copy memmap views — slicing a
  tensor reads only the pages the slice touches, which is exactly the
  per-shard lazy-load behavior the reference gets from safe_open.
"""

from __future__ import annotations

import json
import mmap
import struct
from typing import Any, Dict, Iterable, Mapping, Optional

import numpy as np

try:  # bf16 support (ml_dtypes ships with jax)
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

_DTYPES = {
    "F64": np.dtype("<f8"),
    "F32": np.dtype("<f4"),
    "F16": np.dtype("<f2"),
    "I64": np.dtype("<i8"),
    "I32": np.dtype("<i4"),
    "I16": np.dtype("<i2"),
    "I8": np.dtype("i1"),
    "U8": np.dtype("u1"),
    "BOOL": np.dtype("bool"),
}
if _BF16 is not None:
    _DTYPES["BF16"] = _BF16
_DTYPE_NAMES = {v: k for k, v in _DTYPES.items()}


def _dtype_name(dt: np.dtype) -> str:
    dt = np.dtype(dt)
    if dt in _DTYPE_NAMES:
        return _DTYPE_NAMES[dt]
    # map platform-endian aliases
    for name, ref in _DTYPES.items():
        if dt == ref:
            return name
    raise ValueError(f"unsupported dtype {dt}")


def save_file(tensors: Mapping[str, np.ndarray], path: str,
              metadata: Optional[Dict[str, str]] = None) -> None:
    """Write a safetensors file (sorted keys, contiguous payload)."""
    header: Dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = {str(k): str(v) for k, v in metadata.items()}
    offset = 0
    arrays = {}
    for name in sorted(tensors):
        arr = np.ascontiguousarray(tensors[name])
        n = arr.nbytes
        header[name] = {
            "dtype": _dtype_name(arr.dtype),
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + n],
        }
        arrays[name] = arr
        offset += n
    blob = json.dumps(header, separators=(",", ":")).encode("utf-8")
    pad = (-(len(blob)) % 8)
    blob += b" " * pad
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        for name in sorted(arrays):
            f.write(arrays[name].tobytes())


class SafeTensorFile:
    """Lazy safetensors reader over one mmap.

    ``f[name]`` returns a read-only memmap view (zero copy); slice it to
    read only what you need — the analogue of the reference's
    ``safe_open(...).get_slice(name)[rows, cols]``.
    """

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        (hlen,) = struct.unpack("<Q", self._mm[:8])
        self.header: Dict[str, Any] = json.loads(
            self._mm[8 : 8 + hlen].decode("utf-8"))
        self.metadata = self.header.pop("__metadata__", {})
        self._data_start = 8 + hlen

    def keys(self) -> Iterable[str]:
        return self.header.keys()

    def shape(self, name) -> tuple:
        return tuple(self.header[name]["shape"])

    def __contains__(self, name) -> bool:
        return name in self.header

    def __getitem__(self, name: str) -> np.ndarray:
        info = self.header[name]
        dt = _DTYPES[info["dtype"]]
        s, e = info["data_offsets"]
        buf = np.frombuffer(
            self._mm, dtype=dt,
            count=(e - s) // dt.itemsize,
            offset=self._data_start + s,
        )
        return buf.reshape(info["shape"])

    def tensor(self, name: str) -> np.ndarray:
        """Materialised copy (writable)."""
        return np.array(self[name])

    def close(self):
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_file(path: str) -> Dict[str, np.ndarray]:
    with SafeTensorFile(path) as f:
        return {k: f.tensor(k) for k in f.keys()}

"""Utilities: safetensors IO, logging, memory, profiling."""

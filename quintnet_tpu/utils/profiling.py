"""Profiling: step timing + XLA trace capture.

The reference's profiling module is a pass-body stub
(utils/profiling.py:11-27). Real tooling here:

- :func:`profile_time` / :class:`StepTimer`: wall-clock timing with a
  device sync (NOTE: sync via device->host transfer — on the tunneled
  'axon' platform jax.block_until_ready returns early);
- :func:`trace`: context manager around ``jax.profiler`` writing a
  TensorBoard-loadable XLA trace;
- :func:`device_memory_stats`: per-device live-bytes snapshot
  (the reference's utils/memory.py get_memory_usage equivalent).
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np


def sync(x: Any = None) -> None:
    """Force completion of pending device work reachable from x."""
    if x is None:
        return
    for leaf in jax.tree.leaves(x):
        if hasattr(leaf, "addressable_shards"):
            np.asarray(jax.device_get(
                leaf.addressable_shards[0].data.ravel()[:1]))


def profile_time(fn: Callable) -> Callable:
    """Decorator: prints wall time of each call (synced on the output)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        sync(out)
        print(f"[profile] {fn.__name__}: {time.perf_counter() - t0:.4f}s")
        return out

    return wrapped


class StepTimer:
    """Collects per-step durations; reports mean/p50/p99."""

    def __init__(self):
        self.times: List[float] = []
        self._t0: Optional[float] = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, out: Any = None):
        sync(out)
        assert self._t0 is not None
        self.times.append(time.perf_counter() - self._t0)
        self._t0 = None

    def summary(self) -> Dict[str, float]:
        """Zero recorded steps is a legal state (a run that died before
        its first stop(), an idle serving replica): report a zeroed
        summary with ``steps: 0`` instead of NaN means + a NumPy
        RuntimeWarning from an empty reduction."""
        if not self.times:
            return {"steps": 0, "mean_s": 0.0, "p50_s": 0.0, "p99_s": 0.0}
        a = np.asarray(self.times[1:] or self.times)  # drop compile step
        return {
            "steps": len(self.times),
            "mean_s": float(a.mean()),
            "p50_s": float(np.percentile(a, 50)),
            "p99_s": float(np.percentile(a, 99)),
        }


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA profiler trace viewable in TensorBoard/Perfetto."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Live/peak bytes per device where the backend exposes them."""
    out = {}
    for d in jax.devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            pass
        out[str(d)] = {
            "bytes_in_use": int(stats.get("bytes_in_use", -1)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", -1)),
            "bytes_limit": int(stats.get("bytes_limit", -1)),
        }
    return out

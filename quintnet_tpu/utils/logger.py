"""Logging: stdout + file tee (reference utils/logger.py:5-42 writes
logs/rank_{r}.log per process; SPMD drives the mesh from one process so
there is one log, optionally annotated with the mesh shape)."""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional


def setup_logging(log_dir: Optional[str] = None, *, name: str = "quintnet",
                  level: int = logging.INFO) -> logging.Logger:
    logger = logging.getLogger(name)
    logger.setLevel(level)
    logger.handlers.clear()
    fmt = logging.Formatter("%(asctime)s %(levelname)s %(message)s",
                            "%H:%M:%S")
    sh = logging.StreamHandler(sys.stdout)
    sh.setFormatter(fmt)
    logger.addHandler(sh)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        fh = logging.FileHandler(os.path.join(log_dir, f"{name}.log"))
        fh.setFormatter(fmt)
        logger.addHandler(fh)
    return logger


def log_once(logger: logging.Logger, msg: str, *, _seen=set()):  # noqa: B006
    """Log a message at most once per LOGGER per process (dedups
    warnings emitted from inside retraced functions). Keyed by
    ``(logger.name, msg)``: the module-level set is shared across all
    callers, so keying by message alone made two differently-named
    loggers dedupe EACH OTHER's messages — the second logger's first
    warning silently vanished."""
    key = (logger.name, msg)
    if key not in _seen:
        _seen.add(key)
        logger.info(msg)

"""AST linter for JAX footguns, with a committed-baseline workflow.

Generic linters do not know that ``np.random`` inside a jit-traced
function silently freezes into a compile-time constant, or that a
``float()`` in a step loop is a device sync that stalls async dispatch.
These rules do. Each is narrow on purpose: a rule that fires on half
the tree teaches people to ignore the tool.

Rules
-----
- **QT101 host-numpy-in-jit** — ``np.``/``numpy.`` calls inside a
  jit-traced function. If the call takes a tracer it fails at trace
  time anyway; if it does not, it is a host computation baked into the
  program as a constant — either way it does not belong in traced code
  (trace-time shape arithmetic that must stay should carry a pragma).
- **QT102 python-rng-in-jit** — ``np.random.*`` or stdlib ``random.*``
  inside a jit-traced function. The classic silent bug: the "random"
  value is drawn ONCE at trace time and replayed forever after;
  ``jax.random`` with explicit keys is the only RNG that exists inside
  jit.
- **QT103 tracer-branch** — ``if``/``while`` whose test calls into
  ``jnp``/``jax.numpy`` (or ``.any()``/``.all()``) inside a traced
  function. Python control flow executes at trace time; branching on a
  tracer raises ``ConcretizationTypeError`` at best and silently
  specializes the program at worst — use ``lax.cond``/``jnp.where``.
- **QT104 host-sync-in-step-loop** — ``.item()``/``float()``/``int()``
  on non-literals inside a host loop that drives a train/engine step.
  Each one blocks dispatch until the device drains; round 1 of this
  repo lost ~15% step time to exactly this (train/trainer.py docstring).
  Deliberate syncs (``training.sync_every``, log-window flushes) carry
  pragmas or baseline entries with a note.
- **QT105 mutable-default** — mutable literals or ``np``/``jnp``/
  ``jax`` calls as parameter defaults. A default evaluates once at
  import; an array default captures one buffer shared across every
  call (and keeps a device allocation alive for the process lifetime).
- **QT106 timing-no-sync** — a wall-clock delta (``time.time()``/
  ``monotonic()``/``perf_counter()`` subtraction) in a function that
  never calls ``block_until_ready``. Under async dispatch the delta
  measures enqueue latency, not device work; every throughput number
  this repo publishes must sync before reading the clock.

Suppression: append ``# qtcheck: ok`` (or ``# qtcheck: ok[QT104]``) to
the offending line — reserved for sites where the flagged pattern is
the point (e.g. the engine's scheduler reading sampled tokens). Legacy
violations live in the committed baseline (tools/qtcheck_baseline.json)
keyed by (rule, file, enclosing function) with a count and an optional
note; :func:`compare_baseline` fails on NEW violations and on STALE
entries alike, so the baseline can only shrink deliberately
(``--write-baseline``) and never drifts from the tree
(tests/test_qtcheck.py gates this in tier-1).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES = {
    "QT101": "host numpy call inside a jit-traced function",
    "QT102": "Python/NumPy RNG inside a jit-traced function",
    "QT103": "tracer-dependent Python branching inside a jit-traced "
             "function",
    "QT104": "host sync (.item()/float()/int()) inside a step loop",
    "QT105": "mutable or array-valued default argument",
    "QT106": "wall-clock timing delta without block_until_ready",
}

# call targets whose function-valued arguments are traced by JAX
_TRACING_WRAPPERS = {
    "jit", "shard_map", "shard_map_fn", "make_jaxpr", "grad",
    "value_and_grad", "vmap", "pmap", "checkpoint", "remat", "scan",
    "fori_loop", "while_loop", "cond", "switch", "associated_scan",
    "custom_jvp", "custom_vjp", "eval_shape",
}

_TIME_CALLS = {"time", "monotonic", "perf_counter", "process_time"}

_PRAGMA = re.compile(r"#\s*qtcheck:\s*ok(?:\[([A-Z0-9, ]+)\])?")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    symbol: str
    message: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.symbol}] "
                f"{self.message}")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root(dotted: Optional[str]) -> Optional[str]:
    return dotted.split(".", 1)[0] if dotted else None


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this expression denote a tracing wrapper? Covers ``jax.jit``,
    ``jit``, ``cc.shard_map_fn``, ``partial(jax.jit, ...)`` and
    ``functools.partial(jax.jit, ...)``."""
    name = _dotted(node)
    if name is not None:
        return name.split(".")[-1] in _TRACING_WRAPPERS
    if isinstance(node, ast.Call):
        fn = _dotted(node.func)
        if fn and fn.split(".")[-1] == "partial" and node.args:
            return _is_jit_expr(node.args[0])
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str,
                 rules: Set[str]):
        self.rel = rel
        self.lines = source.splitlines()
        self.rules = rules
        self.violations: List[Violation] = []
        self.traced_names: Set[str] = set()
        self._stack: List[str] = []          # enclosing def names
        self._traced_depth = 0               # >0 => inside traced code
        self._loop_stack: List[bool] = []    # QT104: step-driving loops

    # -- plumbing ------------------------------------------------------
    def _suppressed(self, line: int, rule: str) -> bool:
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _PRAGMA.search(self.lines[ln - 1])
                if m and (m.group(1) is None
                          or rule in m.group(1).replace(" ", "").split(",")):
                    return True
        return False

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        if rule not in self.rules:
            return
        line = getattr(node, "lineno", 0)
        if self._suppressed(line, rule):
            return
        self.violations.append(Violation(
            rule=rule, path=self.rel, line=line,
            symbol=".".join(self._stack) or "<module>", message=message))

    # -- traced-function discovery ------------------------------------
    def collect_traced(self, tree: ast.Module) -> None:
        """Names of functions handed to tracing wrappers anywhere in the
        module (``jax.jit(step)``, ``cc.shard_map_fn(local_step, ...)``,
        ``lax.scan(body, ...)``), plus jit-decorated defs."""
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_jit_expr(node.func):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        self.traced_names.add(arg.id)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_expr(dec):
                        self.traced_names.add(node.name)

    # -- visitors ------------------------------------------------------
    def _visit_def(self, node):
        self._check_defaults(node)
        traced = (node.name in self.traced_names
                  or self._traced_depth > 0
                  or any(_is_jit_expr(d) for d in node.decorator_list))
        self._stack.append(node.name)
        self._traced_depth += 1 if traced else 0
        if "QT106" in self.rules:
            self._check_timing(node)
        self.generic_visit(node)
        self._traced_depth -= 1 if traced else 0
        self._stack.pop()

    visit_FunctionDef = _visit_def
    visit_AsyncFunctionDef = _visit_def

    def _check_defaults(self, node) -> None:
        args = node.args
        for default in list(args.defaults) + [d for d in args.kw_defaults
                                              if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self._flag("QT105", default,
                           f"mutable default in {node.name}() is shared "
                           "across calls")
            elif isinstance(default, ast.Call):
                root = _root(_dotted(default.func))
                if root in ("np", "numpy", "jnp", "jax"):
                    self._flag("QT105", default,
                               f"array default in {node.name}() is built "
                               "once at import and shared across calls")

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        root = _root(name)
        if self._traced_depth > 0 and name is not None:
            if (root in ("np", "numpy")
                    and name.split(".")[1:2] == ["random"]) \
                    or root == "random":
                self._flag("QT102", node,
                           f"{name}() inside traced code draws once at "
                           "trace time and replays forever; use "
                           "jax.random with an explicit key")
            elif root in ("np", "numpy"):
                self._flag("QT101", node,
                           f"{name}() inside traced code runs on host at "
                           "trace time (constant-folded into the "
                           "program)")
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._maybe_host_sync(node, ".item()")
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int")
                and node.args
                and not isinstance(node.args[0], ast.Constant)
                and not self._is_host_math(node.args[0])):
            self._maybe_host_sync(node, f"{node.func.id}()")
        self.generic_visit(node)

    def _visit_loop(self, node):
        drives_step = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                callee = _dotted(sub.func) or ""
                if "step" in callee.split(".")[-1].lower():
                    drives_step = True
                    break
        self._loop_stack.append(drives_step)
        self.generic_visit(node)
        self._loop_stack.pop()

    visit_For = _visit_loop
    # While is handled by visit_While below: branch check + loop check

    @staticmethod
    def _is_host_math(node) -> bool:
        """float(np.exp(...)) / float(math.log(...)) never touch the
        device — numpy/math results are already host scalars."""
        return (isinstance(node, ast.Call)
                and _root(_dotted(node.func)) in ("np", "numpy", "math"))

    def _maybe_host_sync(self, node, what: str) -> None:
        if any(self._loop_stack):
            self._flag("QT104", node,
                       f"{what} in a step-driving loop blocks async "
                       "dispatch every iteration; keep device values "
                       "unsynced (or sync once per window)")

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, "while")
        self._visit_loop(node)

    def _check_branch(self, node, kind: str) -> None:
        if self._traced_depth == 0:
            return
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func) or ""
                root = _root(name)
                if root == "jnp" or name.startswith("jax.numpy"):
                    self._flag("QT103", node,
                               f"{kind} test calls {name}() inside traced "
                               "code — Python branching runs at trace "
                               "time; use lax.cond/jnp.where")
                    return
                if (isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in ("any", "all")):
                    self._flag("QT103", node,
                               f"{kind} test reduces an array with "
                               f".{sub.func.attr}() inside traced code — "
                               "use lax.cond/jnp.where")
                    return

    # -- QT106 ---------------------------------------------------------
    def _check_timing(self, fn_node) -> None:
        """Flag wall-clock deltas in functions that never sync: a
        Sub-expression where an operand is a time call (or a local
        assigned from one), in a function with no block_until_ready."""
        def is_time_call(n) -> bool:
            if not isinstance(n, ast.Call):
                return False
            name = _dotted(n.func) or ""
            # time.monotonic() or a bare imported perf_counter()
            return (name.split(".")[-1] in _TIME_CALLS
                    and (_root(name) == "time" or "." not in name))

        body_walk = list(ast.walk(fn_node))
        # skip nested defs' bodies: they get their own visit
        nested = set()
        for n in body_walk:
            if n is not fn_node and isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                nested.update(ast.walk(n))
        body_walk = [n for n in body_walk if n not in nested]

        if any(isinstance(n, ast.Attribute)
               and n.attr == "block_until_ready" for n in body_walk):
            return
        timed_names = {
            t.id
            for n in body_walk if isinstance(n, ast.Assign)
            and is_time_call(n.value)
            for t in n.targets if isinstance(t, ast.Name)
        }
        for n in body_walk:
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                ops = (n.left, n.right)
                if any(is_time_call(o)
                       or (isinstance(o, ast.Name) and o.id in timed_names)
                       for o in ops):
                    self._flag(
                        "QT106", n,
                        "wall-clock delta without block_until_ready "
                        "measures dispatch, not device work")
                    return


def lint_source(source: str, rel_path: str,
                rules: Optional[Sequence[str]] = None) -> List[Violation]:
    tree = ast.parse(source, filename=rel_path)
    linter = _Linter(rel_path, rel_path, source,
                     set(rules) if rules else set(RULES))
    linter.collect_traced(tree)
    linter.visit(tree)
    return sorted(linter.violations, key=lambda v: (v.path, v.line, v.rule))


@dataclass(frozen=True)
class SourceFile:
    """One collected file: the SHARED parse every pass consumes. The
    CLI walks and parses the tree exactly once (``collect_sources``)
    and hands the same list to the lint rules and the concurrency pass
    (analysis/threads.py) — re-reading and re-parsing per pass was the
    dominant cost of a full-tree run."""
    rel: str
    source: str
    tree: Optional[ast.Module]           # None when the file failed to
    error: Optional[str] = None          # parse (error says why)


def collect_sources(paths: Sequence[str], *,
                    root: str = ".") -> List[SourceFile]:
    """Read + parse every ``*.py`` under ``paths`` (files or
    directories) once, reporting paths relative to ``root`` so
    baselines stay stable across checkouts."""
    files: List[str] = []
    for p in paths:
        full = os.path.join(root, p)
        if os.path.isfile(full):
            files.append(full)
        else:
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                files.extend(os.path.join(dirpath, f)
                             for f in filenames if f.endswith(".py"))
    out: List[SourceFile] = []
    seen: Set[str] = set()
    for f in sorted(files):
        rel = os.path.relpath(f, root)
        if rel in seen:        # overlapping path args: parse once
            continue
        seen.add(rel)
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        try:
            out.append(SourceFile(rel, src,
                                  ast.parse(src, filename=rel)))
        except SyntaxError as e:  # pragma: no cover - tree is parseable
            out.append(SourceFile(rel, src, None,
                                  error=f"syntax error: {e.msg} "
                                        f"(line {e.lineno})"))
    return out


def lint_parsed(sources: Sequence[SourceFile],
                rules: Optional[Sequence[str]] = None) -> List[Violation]:
    """Lint pre-parsed sources (no file IO, no re-parse)."""
    out: List[Violation] = []
    for sf in sources:
        if sf.tree is None:
            out.append(Violation(rule="QT000", path=sf.rel, line=0,
                                 symbol="<module>",
                                 message=sf.error or "unparseable"))
            continue
        linter = _Linter(sf.rel, sf.rel, sf.source,
                         set(rules) if rules else set(RULES))
        linter.collect_traced(sf.tree)
        linter.visit(sf.tree)
        out.extend(linter.violations)
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def lint_paths(paths: Sequence[str], *, root: str = ".",
               rules: Optional[Sequence[str]] = None) -> List[Violation]:
    """Lint every ``*.py`` under ``paths`` (files or directories),
    reporting paths relative to ``root`` so baselines are stable across
    checkouts."""
    return lint_parsed(collect_sources(paths, root=root), rules)


# ---------------------------------------------------------------------------
# baseline


def violations_to_baseline(violations: Sequence[Violation],
                           notes: Optional[Dict[Tuple[str, str, str], str]]
                           = None) -> dict:
    counts: Dict[Tuple[str, str, str], int] = {}
    lines: Dict[Tuple[str, str, str], int] = {}
    for v in violations:
        counts[v.key] = counts.get(v.key, 0) + 1
        lines.setdefault(v.key, v.line)
    entries = []
    for (rule, path, symbol), n in sorted(counts.items()):
        e = {"rule": rule, "path": path, "symbol": symbol, "count": n,
             "line": lines[(rule, path, symbol)]}
        if notes and (rule, path, symbol) in notes:
            e["note"] = notes[(rule, path, symbol)]
        entries.append(e)
    return {"version": 1, "violations": entries}


def load_baseline(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def compare_baseline(violations: Sequence[Violation],
                     baseline: dict) -> Tuple[List[str], List[str]]:
    """(new, stale): ``new`` are violations beyond the baseline (fail
    CI), ``stale`` are baseline entries the tree no longer produces
    (fail too — regenerate with --write-baseline so the committed file
    always mirrors reality, same discipline as tests/test_bench_stale.py
    applies to benchmark artifacts)."""
    base = {(e["rule"], e["path"], e["symbol"]): e["count"]
            for e in baseline.get("violations", [])}
    cur: Dict[Tuple[str, str, str], List[Violation]] = {}
    for v in violations:
        cur.setdefault(v.key, []).append(v)

    new, stale = [], []
    for key, vs in sorted(cur.items()):
        allowed = base.get(key, 0)
        if len(vs) > allowed:
            for v in vs[allowed:]:
                new.append(v.render())
    for key, n in sorted(base.items()):
        have = len(cur.get(key, ()))
        if have < n:
            rule, path, symbol = key
            stale.append(f"{path}: {rule} [{symbol}] baseline says "
                         f"{n}, tree has {have} — regenerate the "
                         "baseline (--write-baseline)")
    return new, stale

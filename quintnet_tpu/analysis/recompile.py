"""Recompile sentinel: count lowerings by abstract signature.

PR 1's serving promise is "one compiled prefill + one compiled decode,
zero recompiles" — and the trainer's step loop makes the analogous
implicit promise (one compiled step per (shapes, dtypes) of the batch).
jit silently recompiles whenever an argument's abstract signature
drifts (a new shape from a non-dropped last batch, a weak-type Python
scalar where an array used to be, a dtype flip from a host round-trip),
and the only symptom is a mysteriously slow step. The sentinel makes
the promise checkable:

- wrap any callable (usually a ``jax.jit`` product) in
  :class:`RecompileSentinel`; every call records the ABSTRACT signature
  of its arguments (pytree structure + per-leaf shape/dtype/weak-type —
  exactly the jit cache key's array part);
- ``compile_count`` is the number of distinct signatures seen, i.e. the
  number of programs jit compiled for this callable;
- :meth:`assert_compile_count` turns the expected count into a hard
  error whose message DIFFS the offending signature against the first
  one, so the drifting leaf is named instead of guessed;
- ``max_compiles`` makes the sentinel enforce at call time: the serve
  engine wraps prefill/decode with ``max_compiles=1`` so a recompile
  fails the call that would cause it, not a benchmark three weeks
  later. The trainer wraps its step/eval functions in observe-only
  mode and logs signature diffs on every recompile.

Signature hashing never touches device data — ``jax.core.get_aval`` on
committed arrays is metadata-only, so wrapping costs microseconds per
call, not a sync.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax


class RecompileError(RuntimeError):
    pass


def _leaf_sig(leaf) -> str:
    try:
        aval = jax.core.get_aval(leaf)
    except TypeError:
        return f"static:{leaf!r}"
    return str(aval)


def abstract_signature(args: tuple, kwargs: dict) -> Tuple:
    """Hashable abstract signature of a call: treedef + per-leaf aval
    strings (shape/dtype/weak_type). Two calls with equal signatures
    hit the same jit cache entry; unequal signatures force a lowering."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef), tuple(_leaf_sig(l) for l in leaves))


def _diff_sigs(base: Tuple, new: Tuple) -> str:
    if base[0] != new[0]:
        return f"pytree structure changed:\n  was {base[0]}\n  now {new[0]}"
    lines = [f"  leaf[{i}]: {a} -> {b}"
             for i, (a, b) in enumerate(zip(base[1], new[1])) if a != b]
    if len(base[1]) != len(new[1]):
        lines.append(f"  leaf count: {len(base[1])} -> {len(new[1])}")
    return "changed leaves:\n" + "\n".join(lines)


class RecompileSentinel:
    """Wrap a (jitted) callable and count distinct abstract signatures.

    ``max_compiles``: raise :class:`RecompileError` BEFORE dispatching a
    call whose signature would exceed the budget. ``on_recompile(name,
    count, diff)`` fires on every new signature after the first —
    observe-only wiring (the trainer logs it).
    """

    def __init__(self, name: str, fn: Callable, *,
                 max_compiles: Optional[int] = None,
                 on_recompile: Optional[Callable[[str, int, str], None]]
                 = None):
        self.name = name
        self.fn = fn
        self.max_compiles = max_compiles
        self.on_recompile = on_recompile
        self._sigs: Dict[Tuple, int] = {}   # signature -> first-seen order

    def __call__(self, *args, **kwargs):
        sig = abstract_signature(args, kwargs)
        if sig not in self._sigs:
            if self._sigs:
                diff = _diff_sigs(next(iter(self._sigs)), sig)
                if (self.max_compiles is not None
                        and len(self._sigs) >= self.max_compiles):
                    raise RecompileError(
                        f"{self.name}: call would trigger lowering "
                        f"#{len(self._sigs) + 1} (budget "
                        f"{self.max_compiles}); {diff}")
                if self.on_recompile is not None:
                    self.on_recompile(self.name, len(self._sigs) + 1, diff)
            self._sigs[sig] = len(self._sigs)
        return self.fn(*args, **kwargs)

    @property
    def compile_count(self) -> int:
        return len(self._sigs)

    def assert_compile_count(self, expected: int) -> None:
        if len(self._sigs) != expected:
            sigs = list(self._sigs)
            detail = ""
            if len(sigs) > 1:
                detail = "; first drift: " + _diff_sigs(sigs[0], sigs[1])
            raise RecompileError(
                f"{self.name}: expected {expected} compiled program(s), "
                f"observed {len(self._sigs)}{detail}")


def assert_compile_count(expected: Dict[str, int],
                         **sentinels: RecompileSentinel) -> None:
    """Check several sentinels at once:
    ``assert_compile_count({'prefill': 1, 'decode': 1}, prefill=s1,
    decode=s2)``."""
    for key, n in expected.items():
        sentinels[key].assert_compile_count(n)


def check_serving_compile_counts(name: str, counts: Dict[str, int], *,
                                 max_prefill: Optional[int] = None,
                                 decode: int = 1) -> None:
    """The serving bounded-compile promise validated from a PLAIN
    ``{program: compile_count}`` dict — the form that crosses a
    process boundary. The sentinels themselves (and their
    signature-diffing errors) live in the replica process; its
    dispatcher gets the counts over the wire
    (``ServeEngine.compile_counts`` → the process fleet's stats frame)
    and holds them to the same rules the in-process
    ``ServeFleet.assert_compile_count`` enforces on live sentinels:

    - at most ONE compile per prefill bucket (``prefill[<width>]``),
      between 1 and ``max_prefill`` (default: the replica's bucket
      count) in total;
    - at most one compile per verify bucket (``verify[<k>]``);
    - exactly ``decode`` compiles of the single ``decode`` program —
      or 0 when a verify bucket compiled (an engine whose every step
      speculated legitimately never runs plain decode);
    - with adapters armed (``decode[r<rank>]`` keys instead), at most
      one compile per rank bucket.

    Raises :class:`RecompileError` naming the replica and the
    offending program counts."""
    per_prefill = {k: v for k, v in counts.items()
                   if k.startswith("prefill[")}
    per_verify = {k: v for k, v in counts.items()
                  if k.startswith("verify[")}
    per_rank = {k: v for k, v in counts.items()
                if k.startswith("decode[")}
    total = sum(per_prefill.values())
    cap = max_prefill if max_prefill is not None else len(per_prefill)
    if not 1 <= total <= cap or any(n > 1
                                    for n in per_prefill.values()):
        raise RecompileError(
            f"{name}: expected 1..{cap} compiled prefill bucket "
            f"program(s) (at most one per bucket), observed {total} "
            f"({per_prefill})")
    if any(n > 1 for n in per_verify.values()):
        raise RecompileError(
            f"{name}: expected at most one compiled verify program "
            f"per draft-length bucket, observed {per_verify}")
    if per_rank:
        if any(n > 1 for n in per_rank.values()):
            raise RecompileError(
                f"{name}: expected at most one compiled decode "
                f"program per LoRA rank bucket, observed {per_rank}")
    elif "decode" in counts:
        d = counts["decode"]
        has_verify = any(n > 0 for n in per_verify.values())
        if d != decode and not (has_verify and d == 0):
            raise RecompileError(
                f"{name}: expected {decode} compiled decode "
                f"program(s), observed {d}")
    else:
        raise RecompileError(
            f"{name}: no decode program count reported at all "
            f"({sorted(counts)})")

"""Recompile sentinel: count lowerings by abstract signature.

PR 1's serving promise is "one compiled prefill + one compiled decode,
zero recompiles" — and the trainer's step loop makes the analogous
implicit promise (one compiled step per (shapes, dtypes) of the batch).
jit silently recompiles whenever an argument's abstract signature
drifts (a new shape from a non-dropped last batch, a weak-type Python
scalar where an array used to be, a dtype flip from a host round-trip),
and the only symptom is a mysteriously slow step. The sentinel makes
the promise checkable:

- wrap any callable (usually a ``jax.jit`` product) in
  :class:`RecompileSentinel`; every call records the ABSTRACT signature
  of its arguments (pytree structure + per-leaf shape/dtype/weak-type —
  exactly the jit cache key's array part);
- ``compile_count`` is the number of distinct signatures seen, i.e. the
  number of programs jit compiled for this callable;
- :meth:`assert_compile_count` turns the expected count into a hard
  error whose message DIFFS the offending signature against the first
  one, so the drifting leaf is named instead of guessed;
- ``max_compiles`` makes the sentinel enforce at call time: the serve
  engine wraps prefill/decode with ``max_compiles=1`` so a recompile
  fails the call that would cause it, not a benchmark three weeks
  later. The trainer wraps its step/eval functions in observe-only
  mode and logs signature diffs on every recompile.

Signature hashing never touches device data — ``jax.core.get_aval`` on
committed arrays is metadata-only, so wrapping costs microseconds per
call, not a sync.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax


class RecompileError(RuntimeError):
    pass


def _leaf_sig(leaf) -> str:
    try:
        aval = jax.core.get_aval(leaf)
    except TypeError:
        return f"static:{leaf!r}"
    return str(aval)


def abstract_signature(args: tuple, kwargs: dict) -> Tuple:
    """Hashable abstract signature of a call: treedef + per-leaf aval
    strings (shape/dtype/weak_type). Two calls with equal signatures
    hit the same jit cache entry; unequal signatures force a lowering."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (str(treedef), tuple(_leaf_sig(l) for l in leaves))


def _diff_sigs(base: Tuple, new: Tuple) -> str:
    if base[0] != new[0]:
        return f"pytree structure changed:\n  was {base[0]}\n  now {new[0]}"
    lines = [f"  leaf[{i}]: {a} -> {b}"
             for i, (a, b) in enumerate(zip(base[1], new[1])) if a != b]
    if len(base[1]) != len(new[1]):
        lines.append(f"  leaf count: {len(base[1])} -> {len(new[1])}")
    return "changed leaves:\n" + "\n".join(lines)


class RecompileSentinel:
    """Wrap a (jitted) callable and count distinct abstract signatures.

    ``max_compiles``: raise :class:`RecompileError` BEFORE dispatching a
    call whose signature would exceed the budget. ``on_recompile(name,
    count, diff)`` fires on every new signature after the first —
    observe-only wiring (the trainer logs it).
    """

    def __init__(self, name: str, fn: Callable, *,
                 max_compiles: Optional[int] = None,
                 on_recompile: Optional[Callable[[str, int, str], None]]
                 = None):
        self.name = name
        self.fn = fn
        self.max_compiles = max_compiles
        self.on_recompile = on_recompile
        self._sigs: Dict[Tuple, int] = {}   # signature -> first-seen order

    def __call__(self, *args, **kwargs):
        sig = abstract_signature(args, kwargs)
        if sig not in self._sigs:
            if self._sigs:
                diff = _diff_sigs(next(iter(self._sigs)), sig)
                if (self.max_compiles is not None
                        and len(self._sigs) >= self.max_compiles):
                    raise RecompileError(
                        f"{self.name}: call would trigger lowering "
                        f"#{len(self._sigs) + 1} (budget "
                        f"{self.max_compiles}); {diff}")
                if self.on_recompile is not None:
                    self.on_recompile(self.name, len(self._sigs) + 1, diff)
            self._sigs[sig] = len(self._sigs)
        return self.fn(*args, **kwargs)

    @property
    def compile_count(self) -> int:
        return len(self._sigs)

    def assert_compile_count(self, expected: int) -> None:
        if len(self._sigs) != expected:
            sigs = list(self._sigs)
            detail = ""
            if len(sigs) > 1:
                detail = "; first drift: " + _diff_sigs(sigs[0], sigs[1])
            raise RecompileError(
                f"{self.name}: expected {expected} compiled program(s), "
                f"observed {len(self._sigs)}{detail}")


def assert_compile_count(expected: Dict[str, int],
                         **sentinels: RecompileSentinel) -> None:
    """Check several sentinels at once:
    ``assert_compile_count({'prefill': 1, 'decode': 1}, prefill=s1,
    decode=s2)``."""
    for key, n in expected.items():
        sentinels[key].assert_compile_count(n)

"""Declarative expected-census specs for QuintNet's compiled programs.

Each function returns the exact per-axis collective counts
(``{axis: {op: count}}``) that one call of the corresponding program
puts on the wire, derived from program structure — parameter-tree
leaf counts, block depth, microbatch count — rather than measured and
pasted. tests/test_qtcheck.py checks them against
:func:`~quintnet_tpu.analysis.jaxpr_audit.collective_census` of the
real lowered programs, so ANY change to the communication pattern of
``parallel/`` or ``serve/`` (an extra all-gather in a tp layer, a
second grad reduction, a resharding XLA was forced to insert) fails
tier-1 with a named diff instead of landing as a silent perf
regression.

Census terms, for reading the formulas below:

- **leaf pmean** — ``reduce_grads`` pmeans every gradient leaf over the
  data axes: one all_reduce per parameter leaf, plus one for the loss.
- **row-parallel psum** — each transformer block holds two RowParallel
  projections (attention out-proj, MLP down-proj): 2 psums per block
  per forward; autodiff's transpose doubles it (the backward re-psums
  the replicated cotangents), so a depth-L scan contributes ``4 L``.
- **replicated-grad psum** — leaves replicated over tp (LayerNorms,
  embeddings) receive rank-partial gradients and are psummed over tp:
  one all_reduce per tp-replicated leaf (the sync the reference torch
  implementation omits — parallel/tp.py docstring).
- **clip-norm psum** — ``clip_sharded_grads`` psums the local
  sum-of-squares of every SHARDED leaf over its sharding axes: one
  all_reduce per tp-sharded leaf when ``grad_clip_norm`` is set.
- **ZeRO terms** — ZeRO-1 re-assembles updated params with ONE
  all_gather (the chunks ravel into a single flat vector); ZeRO-2
  replaces the per-leaf dp pmean with ONE reduce_scatter into the
  rank's chunk plus one psum for the chunk-space clip norm.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec

CensusDict = Dict[str, Dict[str, int]]


def _merge(*censuses: CensusDict) -> CensusDict:
    out: CensusDict = {}
    for c in censuses:
        for axis, ops in c.items():
            cur = out.setdefault(axis, {})
            for op, n in ops.items():
                cur[op] = cur.get(op, 0) + n
    return out


def spec_leaf_counts(param_specs, axis: str) -> Tuple[int, int, int]:
    """(total, replicated-over-axis, sharded-over-axis) leaf counts of a
    PartitionSpec tree — the structural inputs to the formulas below."""
    from quintnet_tpu.parallel.train_step import _spec_axes

    leaves = jax.tree.leaves(
        param_specs, is_leaf=lambda s: isinstance(s, PartitionSpec))
    sharded = sum(1 for s in leaves if axis in _spec_axes(s))
    return len(leaves), len(leaves) - sharded, sharded


def expected_dp_train_step(n_param_leaves: int, *,
                           dp_axis: str = "dp") -> CensusDict:
    """make_parallel_train_step on a dp-only mesh: one leaf pmean per
    gradient leaf + the loss pmean. Nothing else — XLA does the
    bucketing/overlap the reference hand-built (parallel/dp.py)."""
    return {dp_axis: {"all_reduce": n_param_leaves + 1}}


def expected_tp_train_step(depth: int, n_tp_replicated: int,
                           n_tp_sharded: int, *, tp_axis: str = "tp",
                           row_collectives_per_block: int = 2,
                           grad_clip: bool = True) -> CensusDict:
    """tp-only train step of a stacked-block model (ViT/GPT-2 layout:
    QKV column-sharded, projections row-sharded):

      2 row-parallel psums/block x depth x (forward + backward)
      + one psum per tp-replicated gradient leaf
      + one psum per tp-sharded leaf for the global clip norm.

    No data axis -> no loss pmean (the loss is already replicated
    across tp by the final psum's semantics)."""
    fwd_bwd = 2 * row_collectives_per_block * depth
    n = fwd_bwd + n_tp_replicated + (n_tp_sharded if grad_clip else 0)
    return {tp_axis: {"all_reduce": n}}


def expected_dp_tp_train_step(n_param_leaves: int, depth: int,
                              n_tp_replicated: int, n_tp_sharded: int,
                              *, dp_axis: str = "dp",
                              tp_axis: str = "tp",
                              grad_clip: bool = True) -> CensusDict:
    """2-axis dp x tp mesh: the dp and tp patterns compose without
    cross terms — dp sees exactly its dp-only census, tp exactly its
    tp-only one. (That THIS holds is the point of auditing: a stray
    resharding would show up as a new op on one of the axes.)"""
    return _merge(
        expected_dp_train_step(n_param_leaves, dp_axis=dp_axis),
        expected_tp_train_step(depth, n_tp_replicated, n_tp_sharded,
                               tp_axis=tp_axis, grad_clip=grad_clip))


def expected_zero1_train_step(n_param_leaves: int, *,
                              dp_axis: str = "dp") -> CensusDict:
    """ZeRO-1: the dp-only census plus ONE all_gather re-assembling the
    updated flat parameter vector from per-rank chunks
    (parallel/zero.py _chunk_apply). Gradient traffic is unchanged —
    that is ZeRO-1's contract (state sharded, grads still allreduced)."""
    return {dp_axis: {"all_reduce": n_param_leaves + 1, "all_gather": 1}}


def expected_zero2_train_step(*, dp_axis: str = "dp",
                              grad_clip: bool = True) -> CensusDict:
    """ZeRO-2: per-leaf dp pmeans collapse into ONE reduce_scatter of
    the flat grad vector straight into the rank's chunk (half the
    allreduce traffic — parallel/zero.py scatter_grad_chunk); the loss
    pmean stays; clipping psums one chunk-space sum-of-squares; one
    all_gather re-assembles params."""
    return {dp_axis: {
        "all_reduce": 1 + (1 if grad_clip else 0),
        "reduce_scatter": 1,
        "all_gather": 1,
    }}


def expected_3d_train_step(n_param_leaves: int, depth: int,
                           n_tp_replicated: int, n_tp_sharded: int,
                           n_pp_replicated: int, n_pp_sharded: int,
                           n_micro: int, pp_size: int, *,
                           dp_axis: str = "dp", tp_axis: str = "tp",
                           pp_axis: str = "pp",
                           grad_clip: bool = True,
                           store_activations: bool = False) -> CensusDict:
    """3D (dp x tp x pp) 1F1B train step.

    - dp: unchanged leaf pmeans + loss pmean;
    - tp: the fwd+bwd row-parallel psums now run once per MICROBATCH,
      and the memory-lean 1F1B variant (``store_activations=False``)
      recomputes each forward inside the backward — one extra forward's
      worth of psums per microbatch;
    - pp: one psum per pp-REPLICATED gradient leaf (stage-partial
      grads: embedding on stage 0, head on the last stage), one per
      pp-SHARDED leaf for the clip norm, one for the loss (masked to
      the last stage, then shared via broadcast_from), plus the 1F1B
      schedule's boundary ppermutes: two per microbatch (its forward
      and backward each cross one boundary per shift of the ladder)
      plus four per stage boundary for the warmup/cooldown sweeps —
      ``2 * n_micro + 4 * (pp_size - 1)`` (pinned empirically over
      pp in {2, 4} x n_micro in {2, 4, 8}; parallel/pp.py).
    """
    per_block = 2
    fwd = per_block * depth
    tp_count = (n_micro * (2 + (0 if store_activations else 1)) * fwd
                + n_tp_replicated + (n_tp_sharded if grad_clip else 0))
    ppermutes = 2 * n_micro + 4 * (pp_size - 1)
    pp_count = (n_pp_replicated + (n_pp_sharded if grad_clip else 0) + 1)
    return {
        dp_axis: {"all_reduce": n_param_leaves + 1},
        tp_axis: {"all_reduce": tp_count},
        pp_axis: {"all_reduce": pp_count, "ppermute": ppermutes},
    }


# ---------------------------------------------------------------------------
# serving programs (quintnet_tpu/serve/engine.py)


def prefill_buckets(prefill_len: int, *, floor: int = 16) -> Tuple[int, ...]:
    """THE canonical padded-length ladder for the bucketed prefill
    programs: powers of two from ``floor`` up to (and capped at)
    ``prefill_len``. A prompt tail of length t runs in the smallest
    bucket >= t, so short prompts stop paying max-length compute while
    the compile count stays bounded: the engine compiles AT MOST
    ``len(prefill_buckets(prefill_len))`` prefill programs (one
    RecompileSentinel per bucket, ``max_compiles=1`` each — the
    no-recompile invariant, now per bucket). Pinned here — engine and
    census tests derive the same ladder from the same place."""
    if prefill_len < 1:
        raise ValueError(f"prefill_len must be >= 1; got {prefill_len}")
    out = []
    b = floor
    while b < prefill_len:
        out.append(b)
        b *= 2
    out.append(prefill_len)
    return tuple(out)


def expected_serve_prefill(n_layers: int, *,
                           tp_axis: Optional[str] = None,
                           vocab_parallel: bool = False) -> CensusDict:
    """One compiled prefill bucket: 2 row-parallel psums per block
    under tp (attention out-proj + MLP down-proj — forward only, no
    autodiff), plus the vocab-parallel embedding psum and logits
    all_gather when the vocabulary is sharded. Single-device: ZERO
    collectives. The census is independent of the bucket width AND of
    the prefix-cache split (paged scatter/gather add no collectives),
    so every bucket program must match this same spec."""
    if tp_axis is None:
        return {}
    c: CensusDict = {tp_axis: {"all_reduce": 2 * n_layers}}
    if vocab_parallel:
        c[tp_axis]["all_reduce"] += 1   # vocab_parallel_embedding psum
        c[tp_axis]["all_gather"] = 1    # vocab_parallel_logits gather
    return c


def expected_serve_decode(n_layers: int, *,
                          tp_axis: Optional[str] = None,
                          vocab_parallel: bool = False) -> CensusDict:
    """One compiled decode step for ALL slots: identical communication
    shape to prefill — the continuous-batching engine adds batching,
    paging and sampling but NO collectives of its own."""
    return expected_serve_prefill(n_layers, tp_axis=tp_axis,
                                  vocab_parallel=vocab_parallel)


def verify_buckets(max_draft: int, *, floor: int = 2) -> Tuple[int, ...]:
    """THE canonical draft-length ladder for the speculative VERIFY
    programs (serve/spec.py): powers of two from ``floor`` up to (and
    capped at) ``max_draft`` — the default ``max_draft=8`` gives
    ``(2, 4, 8)``. A step whose longest draft is k runs in the
    smallest bucket >= k (program width = bucket + 1 tokens per row:
    the slot's last sampled token rides in front of the draft), so the
    engine compiles AT MOST ``len(verify_buckets(max_draft))`` verify
    programs — one RecompileSentinel per bucket, ``max_compiles=1``
    each, extending the bounded-compile invariant to
    ``<= len(prefill_buckets) + len(verify_buckets) + 1 decode``.
    Pinned here so engine, census and compile-count tests derive the
    same ladder from the same place."""
    if max_draft < 1:
        raise ValueError(f"max_draft must be >= 1; got {max_draft}")
    out = []
    b = floor
    while b < max_draft:
        out.append(b)
        b *= 2
    out.append(max_draft)
    return tuple(out)


def expected_serve_verify(n_layers: int, *,
                          tp_axis: Optional[str] = None,
                          vocab_parallel: bool = False) -> CensusDict:
    """One compiled verify bucket: the decode census exactly — verify
    is the decode step widened from 1 to bucket+1 tokens per row, and
    the batched draft scatter/gather (nn/attention.paged_verify_update)
    adds no collectives. Independent of the bucket width, so every
    bucket program must match this same spec."""
    return expected_serve_decode(n_layers, tp_axis=tp_axis,
                                 vocab_parallel=vocab_parallel)


def expected_serve_moe(n_layers: int, *,
                       ep_axis: Optional[str] = None,
                       tp_axis: Optional[str] = None,
                       vocab_parallel: bool = False) -> CensusDict:
    """One compiled serving program (any of prefill/decode/verify) of
    an MoE family whose experts are sharded over ``ep_axis``: the
    dense tp census unchanged (the router, attention and lm_head are
    ep-replicated; expert FFN tp psums fold into the same 2-per-layer
    count) PLUS exactly **2 all_to_alls per MoE layer** — dispatch
    (tokens to their experts' owner ranks) and combine (expert
    outputs back, nn/moe.py) — and nothing else: the capacity-bounded
    scatter/gather is local, the router replicated. ``ep_axis=None``
    (ep=1 or no mesh) is the dense-replicated program: the MoE math
    runs everywhere identically, ZERO ep collectives — the census
    face of the ep=1 == dense-replication bit-identity contract.
    Independent of bucket width, top_k and capacity, so every bucket
    of every program kind must match this same spec."""
    c = expected_serve_prefill(n_layers, tp_axis=tp_axis,
                               vocab_parallel=vocab_parallel)
    if ep_axis is not None:
        c = dict(c)
        c[ep_axis] = dict(c.get(ep_axis, {}))
        c[ep_axis]["all_to_all"] = (
            c[ep_axis].get("all_to_all", 0) + 2 * n_layers)
    return c


def expected_serve_sp_prefill(n_layers: int, sp: int, *,
                              sp_axis: str = "sp") -> CensusDict:
    """One compiled SEQUENCE-PARALLEL prefill bucket (long-context
    serving, serve/longctx.py + nn/attention.ring_paged_prefill), per
    layer:

    - ``2 * sp`` **ppermutes** — the ring: the stacked chunk K/V pair
      and its position vector each rotate once per scan step, ``sp``
      steps (scan body x trip count, exactly how the 1F1B ppermutes
      are counted);
    - one **all_gather** — the chunk's K/V reassembled in rank order
      for the (sp-replicated) pool scatter;

    plus ONE program-wide **all_reduce**: the masked psum that
    replicates position ``t0 - 1``'s hidden row for the logits read.
    Independent of the bucket width (sp shards it, never changes the
    collective count), so every bucket program must match this same
    spec — and the count is a pure function of (n_layers, sp): any
    extra collective XLA or a refactor sneaks in fails the census test
    with a named diff."""
    return {sp_axis: {"ppermute": 2 * sp * n_layers,
                      "all_gather": n_layers,
                      "all_reduce": 1}}


def kv_layout_policies() -> Tuple[str, ...]:
    """THE canonical KV-pool layout-policy ladder (serve/kv_quant.py):
    ``f32``/``bf16`` passthrough, ``int8`` with per-block-per-head
    absmax scales, ``fp8`` unscaled float8_e4m3fn passthrough (scales
    are OPTIONAL in the shared LayoutPolicy protocol — the read path
    is one upcast in the gathered view), and the ``fake_quant``
    identity-scale proof policy. Pinned here for the same reason the
    bucket ladders are: the policy must NOT change the
    compiled-program census. Per policy the engine compiles exactly
    the same sentinel set — ``len(prefill_buckets)`` prefill programs,
    1 decode (or one per LoRA rank bucket), and ``len(verify_buckets)``
    verify programs — because a scaled policy only widens the pool
    operand list (k, v -> k, v, k_scale, v_scale) inside the SAME
    programs; it never adds a program, a collective, or a recompile
    (tests/test_kv_quant.py pins the compile counts,
    tests/test_qtcheck.py the collective + dtype censuses)."""
    return ("f32", "bf16", "int8", "fp8", "fake_quant")


def weight_layout_policies() -> Tuple[str, ...]:
    """THE canonical weight layout-policy ladder
    (serve/weight_quant.py): ``f32`` identity (the param tree passes
    through untouched), ``bf16`` passthrough narrowing, ``int8``/
    ``fp8`` with per-output-channel absmax scales, and the
    ``fake_quant`` identity-scale proof policy (bit-identical to f32).
    Pinned for the zero-new-programs promise: the policy is baked into
    the param tree at engine BUILD (packed ``w`` + ``w_scale`` leaves,
    nn/layers.quantized_matmul dequants inside the existing dots), so
    per policy the engine compiles exactly the same sentinel set, with
    the same collective census — the per-channel scale multiply is
    rank-local elementwise math (tests/test_weight_quant.py pins the
    zero-backend-compile trace, tests/test_qtcheck.py the censuses)."""
    return ("f32", "bf16", "int8", "fp8", "fake_quant")


def attn_kernels() -> Tuple[str, ...]:
    """THE canonical serving attention-backend ladder
    (ops/paged_attention.py): ``xla`` is the gathered-view reference
    oracle, ``pallas`` the fused block-table-walking kernel. Pinned
    here for the same reason the policy ladder is: the backend must
    NOT change any census or bound — per backend the engine compiles
    exactly the same sentinel set, and every ``expected_serve_*``
    census above holds verbatim (the kernel lives strictly inside the
    per-layer attention; the RowParallel psums, the vocab-parallel
    collectives, and the sp ring all sit outside it, and a
    ``pallas_call`` carries no collectives at all). What DOES differ
    is structural and audited separately:
    ``jaxpr_audit.gathered_view_gathers`` must be > 0 for xla programs
    and exactly 0 for pallas ones (tests/test_qtcheck.py,
    tests/test_serve_bench.py)."""
    return ("xla", "pallas")


def lora_rank_buckets(max_rank: int, *, floor: int = 4) -> Tuple[int, ...]:
    """THE canonical adapter-rank ladder for multi-tenant LoRA serving
    (serve/adapters.py): powers of two from ``floor`` up to (and capped
    at) ``max_rank``. The packed per-slot adapter tensors a decode step
    ships ride a rank dimension padded to the smallest bucket covering
    the batch's largest bound adapter, so adapters of ANY rank <=
    ``max_rank`` join and leave with zero recompiles: the engine
    compiles AT MOST one decode program per bucket (RecompileSentinel,
    ``max_compiles=1`` each), and the bounded-compile invariant becomes
    ``<= len(prefill_buckets) + len(verify_buckets) + 1 decode per rank
    bucket``. Prefill and verify always run at the TOP bucket (one
    request / already the widest program — re-bucketing them would
    multiply their program count for no win), so their ladders are
    unchanged. The per-slot low-rank deltas add NO collectives under tp
    (column-target deltas are rank-local; row-target deltas ride the
    existing RowParallel psum), so the expected_serve_* censuses above
    hold for LoRA-enabled programs unchanged. Pinned here so engine,
    census and compile-count tests derive the same ladder from the same
    place."""
    if max_rank < 1:
        raise ValueError(f"max_rank must be >= 1; got {max_rank}")
    out = []
    b = floor
    while b < max_rank:
        out.append(b)
        b *= 2
    out.append(max_rank)
    return tuple(out)


# ---------------------------------------------------------------------------
# thread-spawn census (quintnet_tpu/analysis/threads.py, rule QT203)

# THE canonical expected-spawn spec for the fleet/serve/obs tree — the
# concurrency mirror of the collective censuses above. Every
# ``threading.Thread``/``Timer`` construction site in the audited tree
# must appear here, keyed (module, spawning symbol, target), with its
# shutdown story: ``daemon`` (does process exit reap it) and ``joined``
# (does some code path wait for it). qtcheck-threads fails BOTH
# directions — a spawn the spec lacks (new thread landed without a
# shutdown story) and a spec entry the tree lacks (thread removed,
# spec stale) — so the fleet's thread population changes only with a
# named diff here, never silently.
#
# MUST stay a pure literal: the zero-jax qtcheck CLI reads it with
# ``ast.literal_eval`` (threads.load_thread_specs) because this module
# imports jax at the top.
THREAD_SPAWN_SPECS = {
    "quintnet_tpu/fleet/fleet.py": [
        # in-process fleet dispatcher; close() joins it.
        {"symbol": "ServeFleet.__init__", "target": "self._dispatch_loop",
         "daemon": True, "joined": True},
    ],
    "quintnet_tpu/fleet/frontdoor.py": [
        # asyncio event-loop carrier thread; stop() joins it.
        {"symbol": "FrontDoor.start", "target": "run",
         "daemon": True, "joined": True},
        # per-stream disconnect watcher; self-terminates with the
        # stream (bounded by the request), daemon as backstop.
        {"symbol": "FrontDoor._generate_stream", "target": "watch",
         "daemon": True, "joined": False},
    ],
    "quintnet_tpu/fleet/proc.py": [
        # child-side stdin reader + heartbeat: live for the worker
        # process's lifetime, reaped by process exit.
        {"symbol": "replica_main", "target": "reader",
         "daemon": True, "joined": False},
        {"symbol": "replica_main", "target": "heartbeat",
         "daemon": True, "joined": False},
        # parent-side per-replica socket reader; exits on EOF when the
        # child dies or close() shuts the socket.
        {"symbol": "ProcReplica.attach", "target": "self._read_loop",
         "daemon": True, "joined": False},
        # fleet accept + dispatch loops; close() joins both.
        {"symbol": "ProcessFleet.__init__", "target": "self._accept_loop",
         "daemon": True, "joined": True},
        {"symbol": "ProcessFleet.__init__", "target": "self._dispatch_loop",
         "daemon": True, "joined": True},
        # async prefix-handoff push (PR 12); bounded by the RPC
        # timeout, daemon so a hung peer can't block close().
        {"symbol": "ProcessFleet._finish", "target": "self._run_handoff",
         "daemon": True, "joined": False},
        # tiered-KV peer-fetch daemon (PR 15); same bounded-RPC story.
        {"symbol": "ProcessFleet._dispatch_loop",
         "target": "self._run_peer_fetch",
         "daemon": True, "joined": False},
        # warmup fan-out: non-daemon worker threads joined in-call.
        {"symbol": "ProcessFleet.warmup", "target": "one",
         "daemon": False, "joined": True},
    ],
    "quintnet_tpu/fleet/replica.py": [
        # per-replica worker; stop() joins it.
        {"symbol": "Replica.__init__", "target": "self._worker",
         "daemon": True, "joined": True},
    ],
}

"""Jaxpr-level auditor: collective census, dtype promotion, donation.

QuintNet-TPU's contract is that each parallel strategy compiles to a
*predictable* communication pattern on the mesh (parallel/dp.py shards
the batch and pmeans grads; parallel/tp.py psums row-parallel partials;
parallel/zero.py reduce-scatters into chunks). Nothing used to check
that: a stray resharding or an accidental extra all-gather lands in the
jitted step and only ever shows up — if it shows up at all — as a perf
regression in a BENCH_*.json weeks later. This module turns the
expected pattern into data that tests can pin exactly:

- :func:`collective_census` lowers any traceable function against its
  (abstract or concrete) inputs and walks the ClosedJaxpr — including
  every sub-jaxpr under ``scan``/``while``/``cond``/``pjit``/
  ``shard_map``/``custom_*`` — counting collective primitives per mesh
  axis. ``psum``/``pmin``/``pmax`` count as ``all_reduce`` (``pmean``
  lowers to psum + divide-by-constant, so it is an all_reduce here
  too). Collectives inside a ``lax.scan`` body are multiplied by the
  static trip count: a RowParallel psum inside a depth-L block scan is
  L psums on the wire, and the census says so.
- :func:`dtype_report` walks the same jaxprs for silent precision
  changes: f32->f64 upcasts (an accidental Python float or x64 flag
  widening a hot buffer 2x) and reductions/contractions carried out
  entirely in 16-bit dtypes (bf16/f16 accumulation — fine for storage,
  usually wrong for sums).
- :func:`donation_report` inspects a jitted function's lowering
  (``Lowered.args_info``) and reports per-argument donation: which
  buffers are donated, which undonated buffers could alias an output
  of identical shape/dtype (params/opt-state in a train step — the
  classic missed ``donate_argnums`` that doubles peak memory), and how
  many bytes each decision covers.

The census's shape is plain nested dicts (axis -> op -> count) so
expected values can be written declaratively — see analysis/specs.py
for the shipped specs of the dp/tp/zero/3D train steps and the serve
prefill/decode programs, and tests/test_qtcheck.py for the pinned
golden counts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax

# jaxpr primitive name -> census op name. pmean does not appear: it
# lowers to psum + div by the (static) axis size.
COLLECTIVE_OPS = {
    "psum": "all_reduce",
    "pmin": "all_reduce",
    "pmax": "all_reduce",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "ppermute": "ppermute",
    "all_to_all": "all_to_all",
}

_16BIT = ("bfloat16", "float16")


def _eqn_axis_names(eqn) -> Tuple[str, ...]:
    """Named mesh axes a collective eqn reduces/gathers over. psum's
    ``axes`` may mix named axes with positional ints — ints are local
    reductions, not communication, and are dropped."""
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


@dataclass
class Census:
    """Per-axis collective counts of one lowered program.

    ``counts[axis][op]`` is the number of times ``op`` executes over
    mesh axis ``axis`` in one call of the program (scan bodies
    multiplied by trip count). ``dynamic`` counts collectives under a
    ``while_loop`` whose trip count is unknowable statically — they are
    counted ONCE in ``counts`` and tallied here so a spec can assert
    there are none (every QuintNet train/serve program is while-free).
    """

    counts: Dict[str, Dict[str, int]] = field(default_factory=dict)
    dynamic: int = 0

    def add(self, axis: str, op: str, n: int = 1) -> None:
        per_axis = self.counts.setdefault(axis, {})
        per_axis[op] = per_axis.get(op, 0) + n

    def total(self) -> int:
        return sum(n for per in self.counts.values() for n in per.values())

    def as_dict(self) -> Dict[str, Dict[str, int]]:
        return {a: dict(sorted(ops.items()))
                for a, ops in sorted(self.counts.items())}

    def diff(self, expected: Dict[str, Dict[str, int]]) -> List[str]:
        """Human-readable mismatches vs a declarative expected census
        (empty list == exact match). Zero-count entries on either side
        are ignored so specs can write explicit zeros."""
        lines = []
        keys = set()
        for side in (self.counts, expected):
            for a, ops in side.items():
                keys.update((a, op) for op, n in ops.items() if n)
        for a, op in sorted(keys):
            got = self.counts.get(a, {}).get(op, 0)
            want = expected.get(a, {}).get(op, 0)
            if got != want:
                lines.append(f"{a}.{op}: expected {want}, got {got}")
        return lines


def _subjaxprs(params) -> List[Any]:
    """Every jaxpr-valued entry of an eqn's params (ClosedJaxpr or raw
    Jaxpr, single or sequence) — covers pjit/scan/while/custom_* and
    whatever primitive grows one next."""
    found = []
    for v in params.values():
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            found.append(v)
        elif isinstance(v, (tuple, list)):
            found.extend(vv for vv in v
                         if hasattr(vv, "eqns") or hasattr(vv, "jaxpr"))
    return found


def _as_open(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _walk(jaxpr, census: Census, mult: int, dyn: bool,
          visit: Optional[Callable] = None) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if visit is not None:
            visit(eqn, mult, dyn)
        if name in COLLECTIVE_OPS:
            for axis in _eqn_axis_names(eqn):
                census.add(axis, COLLECTIVE_OPS[name], mult)
                if dyn:
                    census.dynamic += mult
            continue
        if name == "scan":
            body = _as_open(eqn.params["jaxpr"])
            _walk(body, census, mult * int(eqn.params["length"]), dyn,
                  visit)
        elif name == "while":
            for key in ("cond_jaxpr", "body_jaxpr"):
                _walk(_as_open(eqn.params[key]), census, mult, True, visit)
        elif name == "cond":
            # mutually exclusive branches: a collective runs on at most
            # one path — take the elementwise max over branches so the
            # census reports the worst case, not the sum
            branches = [Census() for _ in eqn.params["branches"]]
            for b, bj in zip(branches, eqn.params["branches"]):
                _walk(_as_open(bj), b, 1, dyn, visit)
            merged: Dict[str, Dict[str, int]] = {}
            for b in branches:
                for a, ops in b.counts.items():
                    for op, n in ops.items():
                        cur = merged.setdefault(a, {})
                        cur[op] = max(cur.get(op, 0), n)
            for a, ops in merged.items():
                for op, n in ops.items():
                    census.add(a, op, n * mult)
            census.dynamic += mult * max((b.dynamic for b in branches),
                                         default=0)
        else:
            for sub in _subjaxprs(eqn.params):
                _walk(_as_open(sub), census, mult, dyn, visit)


def collective_census(fn: Callable, *args, **kwargs) -> Census:
    """Trace ``fn`` against ``args``/``kwargs`` (concrete arrays or
    ShapeDtypeStructs — nothing executes) and count its collectives.

    ``fn`` may be a plain function, a ``jax.jit``-wrapped one, or a
    shard_map'd program; jit boundaries show up as ``pjit`` eqns and
    are walked through."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    census = Census()
    _walk(closed.jaxpr, census, 1, False)
    return census


# ---------------------------------------------------------------------------
# dtype promotion report


@dataclass(frozen=True)
class DtypeIssue:
    kind: str        # "f64-upcast" | "half-accum"
    primitive: str
    detail: str
    count: int       # occurrences on the wire (scan-multiplied)


def dtype_report(fn: Callable, *args,
                 allow_half_accum_primitives: Tuple[str, ...] = (),
                 **kwargs) -> List[DtypeIssue]:
    """Silent-precision audit of one traced program.

    Flags (a) any eqn producing float64 from narrower float inputs
    (or an explicit convert to f64) — the classic accidental-x64 2x
    memory/bandwidth tax, and (b) ``reduce_sum``/``dot_general``/
    ``cumsum`` eqns whose output stays 16-bit — accumulation carried
    out in bf16/f16 truncates every partial sum, which is exactly the
    failure mode mixed-precision recipes exist to avoid (accumulate in
    f32, store in bf16)."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    found: Dict[Tuple[str, str, str], int] = {}

    def visit(eqn, mult, _dyn):
        name = eqn.primitive.name
        out_dtypes = [v.aval.dtype for v in eqn.outvars
                      if hasattr(v.aval, "dtype")]
        in_dtypes = [v.aval.dtype for v in eqn.invars
                     if hasattr(v, "aval") and hasattr(v.aval, "dtype")]
        for od in out_dtypes:
            if od == np.float64 and any(
                    np.issubdtype(d, np.floating) and d != np.float64
                    for d in in_dtypes):
                key = ("f64-upcast", name,
                       f"{[str(d) for d in in_dtypes]} -> float64")
                found[key] = found.get(key, 0) + mult
        if (name in ("reduce_sum", "dot_general", "cumsum")
                and name not in allow_half_accum_primitives):
            for od in out_dtypes:
                if str(od) in _16BIT:
                    key = ("half-accum", name, f"accumulates in {od}")
                    found[key] = found.get(key, 0) + mult

    census = Census()
    _walk(closed.jaxpr, census, 1, False, visit)
    return [DtypeIssue(kind=k, primitive=p, detail=d, count=n)
            for (k, p, d), n in sorted(found.items())]


# ---------------------------------------------------------------------------
# donation report


@dataclass(frozen=True)
class ArgDonation:
    path: str
    shape: Tuple[int, ...]
    dtype: str
    bytes: int
    donated: bool
    aliasable: bool   # an output leaf of identical shape+dtype exists


@dataclass
class DonationReport:
    args: List[ArgDonation]

    @property
    def donated_bytes(self) -> int:
        return sum(a.bytes for a in self.args if a.donated)

    @property
    def undonated_aliasable(self) -> List[ArgDonation]:
        """The headline finding: buffers a caller is almost certainly
        discarding (an identically-shaped output replaces them — the
        params/opt-state pattern) that the program does not donate.
        Each one is peak-memory paid twice."""
        return [a for a in self.args if a.aliasable and not a.donated]

    def summary(self) -> str:
        flagged = self.undonated_aliasable
        lines = [f"{len(self.args)} array args, "
                 f"{self.donated_bytes} bytes donated, "
                 f"{len(flagged)} undonated-but-aliasable"]
        lines += [f"  MISSED {a.path}: {a.shape} {a.dtype} ({a.bytes} B)"
                  for a in flagged]
        return "\n".join(lines)


def _path_str(path) -> str:
    return jax.tree_util.keystr(path) or "<arg>"


def donation_report(jitted: Callable, *args, **kwargs) -> DonationReport:
    """Lower a jitted function and report per-argument donation.

    ``aliasable`` marks undonated inputs for which an output leaf of
    the same shape+dtype is still UNCLAIMED — each output slot can
    alias at most one donated input, so donated args consume matching
    slots first (a decode step with two int32[S] inputs and one
    int32[S] output flags nothing once one of them is donated). The
    flagged set is the train-step params/opt-state shape of missed
    donation: peak memory paid twice. Buffers that cannot alias any
    output (an eval batch feeding scalar metrics) still benefit from
    donation (freed during the computation instead of after), but only
    aliasable ones are definite misses."""
    from collections import Counter

    lowered = jitted.lower(*args, **kwargs)
    info_flat = jax.tree_util.tree_flatten_with_path(lowered.args_info)[0]
    out_shape = jax.eval_shape(jitted, *args, **kwargs)
    slots = Counter((tuple(l.shape), str(l.dtype))
                    for l in jax.tree_util.tree_leaves(out_shape))
    entries = []
    for path, info in info_flat:
        # public .aval on newer jax; _aval on 0.4.x ArgInfo
        aval = getattr(info, "aval", None) or info._aval
        if not hasattr(aval, "shape"):
            continue
        sig = (tuple(aval.shape), str(aval.dtype))
        entries.append((path, aval, sig, bool(info.donated)))
    aliasable = [False] * len(entries)
    for i, (_, _, sig, donated) in enumerate(entries):
        if donated and slots[sig] > 0:   # donated args claim slots first
            slots[sig] -= 1
            aliasable[i] = True
    for i, (_, _, sig, donated) in enumerate(entries):
        if not donated and slots[sig] > 0:
            slots[sig] -= 1
            aliasable[i] = True
    rows = []
    for i, (path, aval, sig, donated) in enumerate(entries):
        nbytes = int(np.prod(aval.shape, dtype=np.int64)
                     * np.dtype(aval.dtype).itemsize)
        rows.append(ArgDonation(
            path=_path_str(path), shape=tuple(aval.shape),
            dtype=str(aval.dtype), bytes=nbytes,
            donated=donated, aliasable=aliasable[i]))
    return DonationReport(args=rows)


# ---------------------------------------------------------------------------
# gathered-view audit (fused paged attention, ops/paged_attention.py)


def _walk_skip_kernels(jaxpr, visit) -> None:
    """Walk every eqn (scan/cond/pjit bodies included) EXCEPT inside
    ``pallas_call`` kernels: kernel-internal memory ops act on VMEM
    blocks by construction, which is exactly the property the
    gathered-view audit exists to distinguish from HBM traffic."""
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            continue
        visit(eqn)
        for sub in _subjaxprs(eqn.params):
            _walk_skip_kernels(_as_open(sub), visit)


def gathered_view_gathers(fn: Callable, *args, num_blocks: int,
                          table_width: int, **kwargs) -> int:
    """Count the XLA ``gather`` eqns that materialize a FULL
    block-table row view: operand 0 is a pool-shaped array (leading
    dim == ``num_blocks``) and the output carries a ``table_width``
    dim — the `paged_gather`/`paged_gather_scales` signature, the HBM
    round-trip the fused Pallas kernels exist to delete.

    The count is structural (one per eqn occurrence; a scan body
    counts once, not per trip), and the table dim is positional: a
    pool gather indexed by an [.., W]-wide table slice lands W at
    OUTPUT DIM 1 ([rows, W, slots-or-heads, ...]), so only dim 1 is
    compared — a head/feature dim that happens to equal
    ``table_width`` cannot alias. An ``attn_kernel="xla"`` serving
    program shows >= 2 per layer (k + v, plus both scale gathers under
    a scaled KV policy); an ``attn_kernel="pallas"`` program must show
    ZERO — its only pool gathers are the touched-block windows of
    ``paged_quant_window_update``, whose table dim is the requant
    span. CALLER CONTRACT: audit a program whose requant span is
    strictly below ``table_width`` (decode's span is 1; for prefill
    pick a bucket well under the row length) — a run covering the
    whole row must legitimately touch every block it wrote.
    ``pallas_call`` interiors are skipped — VMEM block moves are the
    kernel doing its job."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    found = 0

    def visit(eqn):
        nonlocal found
        if eqn.primitive.name != "gather":
            return
        op = eqn.invars[0]
        if not (hasattr(op, "aval") and hasattr(op.aval, "shape")):
            return
        shape = tuple(op.aval.shape)
        if not shape or shape[0] != num_blocks:
            return
        out = tuple(eqn.outvars[0].aval.shape)
        if len(out) >= 2 and out[1] == table_width:
            found += 1

    _walk_skip_kernels(closed.jaxpr, visit)
    return found

"""qtcheck-threads: static lock-discipline auditor for the fleet.

The fleet/serve/obs layers are a real concurrent system — dispatcher,
reader, heartbeat, handoff, tier-fetch and warmup threads all touching
routing state — and the discipline that keeps them correct ("journaled
-before-callback under the fleet lock", "the ring has its OWN lock")
has so far lived in comments. This pass makes it machine-checked, the
same move qtcheck's collective census made for the jaxpr layer: parse
the tree (AST only, zero jax imports — this module is loadable by file
path exactly like ``lint.py``), build the lock/thread model, and fail
CI on violations.

Rules
-----
- **QT201 lock-order-cycle** — every ``with self._lock:``-style
  acquisition becomes a node keyed (module, class, attr); holding A
  while acquiring B (lexically nested, or via a resolvable call into a
  method that acquires B) is an edge A→B. Any cycle in the resulting
  graph is a potential deadlock and the finding names every edge's
  call chain, so the two inverted stacks are readable from the CI log.
- **QT202 unguarded-shared-state** — an attribute WRITTEN under a lock
  in at least one (non-``__init__``) method is classified as guarded
  by that lock; any read or write of it WITHOUT the lock, in a method
  reachable from a thread entry point (``threading.Thread`` targets,
  ``threading.Timer`` callbacks, ``run_in_executor`` targets, or an
  ``async def`` front-door handler), is a finding. ``__init__`` is
  exempt on both sides: construction happens-before every thread that
  can see the object.
- **QT203 thread-spawn-census** — every ``threading.Thread(...)`` /
  ``threading.Timer(...)`` spawn site (resolved ``target=``, literal
  ``daemon=`` flag, join-or-shutdown heuristic) is compared against
  the declarative expected-spawn spec (``THREAD_SPAWN_SPECS`` in
  :mod:`~quintnet_tpu.analysis.specs`, a pure literal read back via
  ``ast.literal_eval`` so the jax-free CLI can load it). Census and
  spec must match exactly — an unexpected spawn AND a spec entry the
  tree no longer produces both fail, mirroring the collective census.

Interprocedural model (bounded on purpose):

- calls resolve through ``self.m()``, ``self.attr.m()`` where ``attr``
  was assigned a class constructed in the analyzed set, locals
  assigned from such constructors, and — as a last resort — method
  names defined by exactly ONE analyzed class (unique-name
  resolution); anything ambiguous is skipped, never guessed;
- held-lock state propagates two ways: effective-acquire sets flow
  bottom-up (holding A while calling a method that acquires B is an
  A→B edge), and an AMBIENT held set flows top-down as the
  intersection of held sets across every observed call site — this is
  what makes the repo's ``*_locked`` convention (methods called with
  the fleet lock already held) analyzable without annotations.

Findings flow through the same committed-baseline contract as the
lint rules (``tools/qtcheck_threads_baseline.json``; new violations
AND stale entries both fail) and honor the same ``# qtcheck: ok[RULE]``
pragmas. The runtime twin of this pass is
:mod:`~quintnet_tpu.analysis.lockrt`.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple


# Reuse lint.py's Violation dataclass + baseline machinery WITHOUT
# importing the package (`import quintnet_tpu` pulls in jax; this
# module's contract, like lint.py's, is zero-jax when loaded by file
# path). Prefer whichever incarnation is already loaded.
def _load_lint():
    for name in ("quintnet_tpu.analysis.lint", "_qtcheck_lint"):
        if name in sys.modules:
            return sys.modules[name]
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint.py")
    spec = importlib.util.spec_from_file_location("_qtcheck_lint", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_qtcheck_lint"] = mod
    spec.loader.exec_module(mod)
    return mod


_lint = _load_lint()
Violation = _lint.Violation
compare_baseline = _lint.compare_baseline
load_baseline = _lint.load_baseline
violations_to_baseline = _lint.violations_to_baseline
collect_sources = _lint.collect_sources
_PRAGMA = _lint._PRAGMA
_dotted = _lint._dotted

RULES = {
    "QT201": "lock-order cycle between acquisition sites (potential "
             "deadlock)",
    "QT202": "unguarded access to a lock-guarded attribute on a "
             "thread-reachable path",
    "QT203": "thread-spawn census does not match the declarative spec",
}

# the subsystems the concurrency pass audits by default (the ISSUE's
# scope: everything that spawns threads or takes locks in serving)
THREAD_PATHS = ("quintnet_tpu/fleet", "quintnet_tpu/serve",
                "quintnet_tpu/obs")

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def load_thread_specs(path: Optional[str] = None) -> Dict:
    """``THREAD_SPAWN_SPECS`` from analysis/specs.py WITHOUT importing
    it (specs.py imports jax at module top for the collective-census
    specs; the spawn spec is a pure literal exactly so this reader can
    ``ast.literal_eval`` it jax-free)."""
    path = path or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "specs.py")
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "THREAD_SPAWN_SPECS":
                return ast.literal_eval(node.value)
    return {}


# ---------------------------------------------------------------------------
# model extraction


# a lock node: (module rel path, class name or "" for module level,
# attribute/variable name)
LockKey = Tuple[str, str, str]
# a function node: (module rel path, class name or "", def name)
FnKey = Tuple[str, str, str]


def _lock_label(k: LockKey) -> str:
    mod, cls, attr = k
    return f"{mod}:{cls + '.' if cls else ''}{attr}"


def _fn_label(k: FnKey) -> str:
    mod, cls, name = k
    return f"{mod}:{cls + '.' if cls else ''}{name}"


def _is_lock_ctor(node: ast.AST) -> bool:
    # an `instrumented if audited else stock` conditional (the fleets'
    # lock_audit swap) is a lock if either arm is one
    if isinstance(node, ast.IfExp):
        return _is_lock_ctor(node.body) or _is_lock_ctor(node.orelse)
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func) or ""
    parts = name.split(".")
    if parts[-1] in _LOCK_CTORS and (
            len(parts) == 1 or parts[0] == "threading"):
        return True
    # the lockrt minting API: <...audit...>.lock/rlock/condition(name)
    # returns an InstrumentedLock (or a Condition over one) — the
    # receiver must mention "audit" so unrelated `.lock()` methods
    # (e.g. a file lock helper) don't get promoted
    return (len(parts) >= 2
            and parts[-1] in ("lock", "rlock", "condition")
            and any("audit" in p for p in parts[:-1]))


@dataclass
class _Spawn:
    module: str
    symbol: str              # enclosing def, dotted like lint symbols
    line: int
    target: str              # resolved target= as written ("self._worker")
    daemon: Optional[bool]   # literal kwarg, None when absent/dynamic
    joined: bool             # join-or-shutdown heuristic
    kind: str                # "Thread" | "Timer"


@dataclass
class _ClassModel:
    module: str
    name: str
    locks: Set[str] = field(default_factory=set)         # lock attrs
    attr_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)


@dataclass
class _FnScanOut:
    key: FnKey
    line: int
    # lock -> first acquisition line in this fn
    acquire_lines: Dict[LockKey, int] = field(default_factory=dict)
    # (outer, inner, line): lexically nested acquisitions
    nested: List[Tuple[LockKey, LockKey, int]] = field(
        default_factory=list)
    # (callee ref, line, held-at-site): for edge + ambient propagation
    calls: List[Tuple[object, int, Tuple[LockKey, ...]]] = field(
        default_factory=list)
    # (attr, "load"/"store", line, held-at-site)
    accesses: List[Tuple[str, str, int, Tuple[LockKey, ...]]] = field(
        default_factory=list)
    # thread roots introduced here (Thread targets, executor fns)
    root_refs: List[object] = field(default_factory=list)
    spawns: List[_Spawn] = field(default_factory=list)
    is_async: bool = False


class _CallRef:
    """An unresolved callee: resolution happens once the whole file
    set's class table exists."""

    __slots__ = ("kind", "cls", "name", "var")

    def __init__(self, kind: str, name: str, cls: str = "",
                 var: str = ""):
        self.kind = kind      # "self" | "typed" | "name" | "free"
        self.cls = cls        # class name for "typed"
        self.name = name      # method / function name
        self.var = var


class _FnScanner(ast.NodeVisitor):
    """One pass over one def's body: lock regions, calls, self-attr
    accesses, thread spawns. Does NOT descend into nested defs — a
    closure runs on whichever thread calls it, not necessarily under
    the locks lexically around its definition, so charging the
    enclosing region to it would be wrong in both directions."""

    def __init__(self, model: "_TreeModel", module: str, cls: str,
                 fn: ast.AST, lines: List[str]):
        self.model = model
        self.module = module
        self.cls = cls
        self.fn = fn
        self.lines = lines
        self.out = _FnScanOut(
            key=(module, cls, fn.name), line=fn.lineno,
            is_async=isinstance(fn, ast.AsyncFunctionDef))
        self._held: List[LockKey] = []
        self._locals: Dict[str, str] = {}    # var -> class name

    # ---- lock resolution --------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[LockKey]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.cls):
            cm = self.model.classes.get((self.module, self.cls))
            if cm and expr.attr in cm.locks:
                return (self.module, self.cls, expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.model.module_locks.get(self.module, ()):
                return (self.module, "", expr.id)
        return None

    # ---- traversal ---------------------------------------------------
    def _scan(self) -> _FnScanOut:
        for stmt in self.fn.body:
            self.visit(stmt)
        return self.out

    def visit_FunctionDef(self, node):     # nested def: skip body
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    def _do_with(self, node):
        acquired: List[LockKey] = []
        for item in node.items:
            lk = self._lock_of(item.context_expr)
            if lk is not None:
                self.out.acquire_lines.setdefault(
                    lk, item.context_expr.lineno)
                for outer in self._held:
                    if outer != lk:
                        self.out.nested.append(
                            (outer, lk, item.context_expr.lineno))
                self._held.append(lk)
                acquired.append(lk)
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._held.pop()

    visit_With = _do_with
    visit_AsyncWith = _do_with

    def visit_Assign(self, node):
        # local type inference: x = ClassName(...)
        if (isinstance(node.value, ast.Call)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            callee = _dotted(node.value.func) or ""
            cls = callee.split(".")[-1]
            if self.model.class_names.get(cls):
                self._locals[node.targets[0].id] = cls
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and self.cls):
            ctx = "store" if isinstance(
                node.ctx, (ast.Store, ast.Del)) else "load"
            self.out.accesses.append(
                (node.attr, ctx, node.lineno, tuple(self._held)))
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        # self.x += 1 parses the target as a Load-ctx Attribute in some
        # versions and Store in others; record it explicitly as a store
        if (isinstance(node.target, ast.Attribute)
                and isinstance(node.target.value, ast.Name)
                and node.target.value.id == "self" and self.cls):
            self.out.accesses.append(
                (node.target.attr, "store", node.lineno,
                 tuple(self._held)))
            self.visit(node.value)
            return
        self.generic_visit(node)

    def _callee_ref(self, func: ast.AST) -> Optional[_CallRef]:
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id == "self":
                    return _CallRef("self", func.attr)
                if base.id in self._locals:
                    return _CallRef("typed", func.attr,
                                    cls=self._locals[base.id])
                return _CallRef("name", func.attr, var=base.id)
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self" and self.cls):
                cm = self.model.classes.get((self.module, self.cls))
                typ = cm.attr_types.get(base.attr) if cm else None
                if typ:
                    return _CallRef("typed", func.attr, cls=typ)
                return _CallRef("name", func.attr, var=base.attr)
        if isinstance(func, ast.Name):
            return _CallRef("free", func.id)
        return None

    def _target_ref(self, expr: ast.AST) -> Tuple[str, Optional[_CallRef]]:
        """A function-valued argument (Thread target=, executor fn)."""
        return (_dotted(expr) or "<dynamic>", self._callee_ref(expr))

    def visit_Call(self, node):
        name = _dotted(node.func) or ""
        parts = name.split(".")
        tail = parts[-1]
        # thread spawn census sites
        if tail in ("Thread", "Timer") and (
                len(parts) == 1 or parts[0] == "threading"):
            self._note_spawn(node, tail)
        # run_in_executor(None, fn, ...): fn runs on an executor thread
        elif tail == "run_in_executor" and len(node.args) >= 2:
            txt, ref = self._target_ref(node.args[1])
            if ref is not None:
                self.out.root_refs.append(ref)
        ref = self._callee_ref(node.func)
        if ref is not None:
            self.out.calls.append((ref, node.lineno, tuple(self._held)))
        self.generic_visit(node)

    # ---- spawn census ------------------------------------------------
    def _note_spawn(self, node: ast.Call, kind: str) -> None:
        target_expr = None
        daemon: Optional[bool] = None
        if kind == "Timer" and len(node.args) >= 2:
            target_expr = node.args[1]
        for kw in node.keywords:
            if kw.arg == "target" or (kind == "Timer"
                                      and kw.arg == "function"):
                target_expr = kw.value
            elif kw.arg == "daemon" and isinstance(kw.value,
                                                   ast.Constant):
                daemon = bool(kw.value.value)
        txt, ref = (self._target_ref(target_expr)
                    if target_expr is not None else ("<dynamic>", None))
        if ref is not None:
            self.out.root_refs.append(ref)
        sym = (f"{self.cls}.{self.fn.name}" if self.cls
               else self.fn.name)
        self.out.spawns.append(_Spawn(
            module=self.module, symbol=sym, line=node.lineno,
            target=txt, daemon=daemon,
            joined=self._join_nearby(node), kind=kind))

    def _join_nearby(self, node: ast.Call) -> bool:
        """Join-or-shutdown heuristic: the spawned handle is joined if
        a ``.join(`` call appears in the same function (locals, loop
        collections) or — when the handle lands on ``self.X`` — on
        ``self.X`` anywhere in the class."""
        for n in ast.walk(self.fn):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "join"):
                return True
        # self.X = threading.Thread(...): look for self.X.join in class
        attr = self._spawn_attr(node)
        if attr and self.cls:
            cm = self.model.classes.get((self.module, self.cls))
            for meth in (cm.methods.values() if cm else ()):
                for n in ast.walk(meth):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr == "join"
                            and isinstance(n.func.value, ast.Attribute)
                            and n.func.value.attr == attr):
                        return True
        return False

    def _spawn_attr(self, call: ast.Call) -> Optional[str]:
        for n in ast.walk(self.fn):
            if (isinstance(n, ast.Assign) and n.value is call
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Attribute)
                    and isinstance(n.targets[0].value, ast.Name)
                    and n.targets[0].value.id == "self"):
                return n.targets[0].attr
        return None


class _TreeModel:
    """The whole analyzed file set: class table, lock nodes, per-def
    scans, resolved call graph."""

    def __init__(self):
        self.classes: Dict[Tuple[str, str], _ClassModel] = {}
        self.module_locks: Dict[str, Set[str]] = {}
        self.class_names: Dict[str, List[Tuple[str, str]]] = {}
        self.method_index: Dict[str, List[FnKey]] = {}
        self.fns: Dict[FnKey, _FnScanOut] = {}
        self.sources: Dict[str, List[str]] = {}   # rel -> lines

    # ---- construction ------------------------------------------------
    def add_module(self, rel: str, source: str, tree: ast.Module) -> None:
        self.sources[rel] = source.splitlines()
        mlocks: Set[str] = set()
        for node in tree.body:
            if (isinstance(node, ast.Assign) and _is_lock_ctor(node.value)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                mlocks.add(node.targets[0].id)
        self.module_locks[rel] = mlocks
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._add_class(rel, node)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                key = (rel, "", node.name)
                self.classes.setdefault(
                    (rel, ""), _ClassModel(module=rel, name=""))
                self.classes[(rel, "")].methods[node.name] = node
                self.method_index.setdefault(node.name, []).append(key)

    def _add_class(self, rel: str, node: ast.ClassDef) -> None:
        cm = _ClassModel(module=rel, name=node.name)
        self.classes[(rel, node.name)] = cm
        self.class_names.setdefault(node.name, []).append(
            (rel, node.name))
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cm.methods[item.name] = item
                self.method_index.setdefault(item.name, []).append(
                    (rel, node.name, item.name))
        # lock attrs + attr types from self.X = ... assignments in ANY
        # method (the conditional lock_audit wiring assigns the same
        # attr on both branches; every assignment is inspected)
        for meth in cm.methods.values():
            for n in ast.walk(meth):
                if not (isinstance(n, ast.Assign)
                        and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Attribute)
                        and isinstance(n.targets[0].value, ast.Name)
                        and n.targets[0].value.id == "self"):
                    continue
                attr = n.targets[0].attr
                if _is_lock_ctor(n.value):
                    cm.locks.add(attr)
                elif isinstance(n.value, ast.Call):
                    callee = (_dotted(n.value.func) or "").split(".")[-1]
                    if callee and callee[:1].isupper():
                        cm.attr_types.setdefault(attr, callee)

    def scan_all(self) -> None:
        for (rel, cls), cm in self.classes.items():
            for name, fn in cm.methods.items():
                out = _FnScanner(self, rel, cls, fn,
                                 self.sources[rel])._scan()
                self.fns[out.key] = out

    # ---- resolution --------------------------------------------------
    def resolve(self, ref: _CallRef, site: FnKey) -> Optional[FnKey]:
        mod, cls, _ = site
        if ref.kind == "self" and cls:
            key = (mod, cls, ref.name)
            return key if key in self.fns else None
        if ref.kind == "typed":
            for crel, cname in self.class_names.get(ref.cls, ()):
                key = (crel, cname, ref.name)
                if key in self.fns:
                    return key
            return None
        if ref.kind == "free":
            key = (mod, "", ref.name)
            return key if key in self.fns else None
        if ref.kind == "name":
            # unique-name fallback: resolve only when exactly one
            # analyzed class defines a method with this name AND that
            # method touches locks (an ambiguous or lock-free callee
            # adds nothing to the graph — skipping is safe)
            cands = [k for k in self.method_index.get(ref.name, ())
                     if len(k) == 3 and k in self.fns and k[1]]
            if len(cands) == 1:
                return cands[0]
        return None


# ---------------------------------------------------------------------------
# analysis passes


def _suppressed(model: _TreeModel, rel: str, line: int,
                rule: str) -> bool:
    lines = model.sources.get(rel, [])
    for ln in (line, line - 1):
        if 1 <= ln <= len(lines):
            m = _PRAGMA.search(lines[ln - 1])
            if m and (m.group(1) is None
                      or rule in m.group(1).replace(" ", "").split(",")):
                return True
    return False


def _resolved_calls(model: _TreeModel):
    """(caller, callee, line, held-at-site) for every resolvable call."""
    for key, out in model.fns.items():
        for ref, line, held in out.calls:
            callee = model.resolve(ref, key)
            if callee is not None and callee != key:
                yield key, callee, line, held


def _thread_roots(model: _TreeModel) -> Set[FnKey]:
    roots: Set[FnKey] = set()
    for key, out in model.fns.items():
        if out.is_async:
            roots.add(key)            # front-door asyncio handlers
        for ref in out.root_refs:
            r = model.resolve(ref, key)
            if r is not None:
                roots.add(r)
    return roots


def _reachable(model: _TreeModel, roots: Set[FnKey],
               calls) -> Set[FnKey]:
    adj: Dict[FnKey, Set[FnKey]] = {}
    for caller, callee, _line, _held in calls:
        adj.setdefault(caller, set()).add(callee)
    seen = set(roots)
    work = list(roots)
    while work:
        k = work.pop()
        for nxt in adj.get(k, ()):
            if nxt not in seen:
                seen.add(nxt)
                work.append(nxt)
    return seen


def _ambient_held(model: _TreeModel, roots: Set[FnKey],
                  calls) -> Dict[FnKey, Set[LockKey]]:
    """Top-down held-lock propagation: ambient(fn) = the intersection
    of (lexical held ∪ ambient(caller)) over every observed call site.
    Thread roots start from nothing. Methods nobody calls keep an
    empty ambient (conservative: may over-report, never under)."""
    sites: Dict[FnKey, List[Tuple[FnKey, Tuple[LockKey, ...]]]] = {}
    for caller, callee, _line, held in calls:
        sites.setdefault(callee, []).append((caller, held))
    ambient: Dict[FnKey, Set[LockKey]] = {
        k: set() for k in model.fns}
    # iterate to a fixed point (graph is small; depth is bounded)
    for _ in range(len(model.fns)):
        changed = False
        for key in model.fns:
            if key in roots or key not in sites:
                new: Set[LockKey] = set()
            else:
                new = None
                for caller, held in sites[key]:
                    s = set(held) | ambient.get(caller, set())
                    new = s if new is None else (new & s)
                new = new or set()
            if new != ambient[key]:
                ambient[key] = new
                changed = True
        if not changed:
            break
    return ambient


def _effective_acquires(model: _TreeModel, calls
                        ) -> Tuple[Dict[FnKey, Set[LockKey]],
                                   Dict[FnKey, Dict[LockKey, str]]]:
    """Bottom-up: which locks does calling fn (transitively) acquire,
    and via which call chain (for the finding's message)."""
    eff: Dict[FnKey, Set[LockKey]] = {}
    chain: Dict[FnKey, Dict[LockKey, str]] = {}
    for key, out in model.fns.items():
        eff[key] = set(out.acquire_lines)
        chain[key] = {lk: f"{_fn_label(key)}:{ln}"
                      for lk, ln in out.acquire_lines.items()}
    call_list = list(calls)
    for _ in range(len(model.fns)):
        changed = False
        for caller, callee, _line, _held in call_list:
            for lk in eff.get(callee, ()):
                if lk not in eff[caller]:
                    eff[caller].add(lk)
                    chain[caller][lk] = (f"{_fn_label(caller)} -> "
                                         f"{chain[callee][lk]}")
                    changed = True
        if not changed:
            break
    return eff, chain


def _lock_order_edges(model: _TreeModel, calls, ambient, eff, chain):
    """edge (A, B) -> (module, line, human chain) provenance."""
    edges: Dict[Tuple[LockKey, LockKey], Tuple[str, int, str]] = {}

    def note(a: LockKey, b: LockKey, mod: str, line: int,
             how: str) -> None:
        if a == b:
            return
        if _suppressed(model, mod, line, "QT201"):
            return
        edges.setdefault((a, b), (mod, line, how))

    for key, out in model.fns.items():
        amb = ambient.get(key, set())
        for outer, inner, line in out.nested:
            note(outer, inner, key[0], line,
                 f"{_fn_label(key)}:{line}")
        # ambient locks held around this fn's own direct acquisitions
        for lk, ln in out.acquire_lines.items():
            for outer in amb:
                note(outer, lk, key[0], ln,
                     f"[callers hold {_lock_label(outer)}] "
                     f"{_fn_label(key)}:{ln}")
    for caller, callee, line, held in calls:
        outer_set = set(held) | ambient.get(caller, set())
        for outer in outer_set:
            for lk in eff.get(callee, ()):
                note(outer, lk, caller[0], line,
                     f"{_fn_label(caller)}:{line} -> "
                     f"{chain[callee][lk]}")
    return edges


def _cycles(edges) -> List[List[Tuple[LockKey, LockKey]]]:
    """Strongly connected components with >= 2 nodes, reported as the
    list of their internal edges (every cycle lives inside one SCC)."""
    adj: Dict[LockKey, Set[LockKey]] = {}
    nodes: Set[LockKey] = set()
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        nodes.update((a, b))
    index: Dict[LockKey, int] = {}
    low: Dict[LockKey, int] = {}
    on: Set[LockKey] = set()
    stack: List[LockKey] = []
    sccs: List[Set[LockKey]] = []
    counter = [0]

    def strongconnect(v: LockKey) -> None:
        # iterative Tarjan (explicit stack; the graph is tiny but a
        # recursion limit failure in a linter is unacceptable)
        work = [(v, iter(sorted(adj.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp: Set[LockKey] = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    out = []
    for comp in sccs:
        out.append(sorted((a, b) for (a, b) in edges
                          if a in comp and b in comp))
    return out


# ---------------------------------------------------------------------------
# rule drivers


def _qt201(model: _TreeModel, edges) -> List[Violation]:
    out: List[Violation] = []
    for comp_edges in _cycles(edges):
        names = sorted({_lock_label(n) for e in comp_edges for n in e})
        detail = "; ".join(
            f"{_lock_label(a)} -> {_lock_label(b)} via {edges[(a, b)][2]}"
            for a, b in comp_edges)
        mod, line, _ = edges[comp_edges[0]]
        out.append(Violation(
            rule="QT201", path=mod, line=line,
            symbol=" <-> ".join(names),
            message=f"lock-order cycle ({detail})"))
    return out


def _qt202(model: _TreeModel, roots, calls, ambient) -> List[Violation]:
    # classify: attr -> guarding lock, per class (written under exactly
    # one lock of its own class in >= 1 non-__init__ method)
    guards: Dict[Tuple[str, str], Dict[str, Set[LockKey]]] = {}
    for key, out in model.fns.items():
        mod, cls, name = key
        if not cls or name == "__init__":
            continue
        cm = model.classes[(mod, cls)]
        amb = ambient.get(key, set())
        for attr, ctx, _line, held in out.accesses:
            if ctx != "store" or attr in cm.locks:
                continue
            own = {lk for lk in (set(held) | amb)
                   if lk[0] == mod and lk[1] == cls}
            if own:
                guards.setdefault((mod, cls), {}).setdefault(
                    attr, set()).update(own)
    reach = _reachable(model, roots, calls)
    out_v: List[Violation] = []
    for key in sorted(reach):
        mod, cls, name = key
        if not cls or name == "__init__":
            continue
        scan = model.fns[key]
        amb = ambient.get(key, set())
        cls_guards = guards.get((mod, cls), {})
        seen_lines: Set[Tuple[str, int]] = set()
        for attr, ctx, line, held in scan.accesses:
            gset = cls_guards.get(attr)
            if not gset or len(gset) != 1:
                continue     # unguarded or ambiguously guarded: skip
            guard = next(iter(gset))
            if guard in set(held) | amb:
                continue
            if (attr, line) in seen_lines:
                continue
            seen_lines.add((attr, line))
            if _suppressed(model, mod, line, "QT202"):
                continue
            out_v.append(Violation(
                rule="QT202", path=mod, line=line,
                symbol=f"{cls}.{name}",
                message=f"{ctx} of self.{attr} without "
                        f"{_lock_label(guard)} (guarded-by inference: "
                        f"written under it elsewhere) on a "
                        f"thread-reachable path"))
    return out_v


def _qt203(model: _TreeModel, specs: Dict) -> List[Violation]:
    observed: Dict[Tuple[str, str, str], _Spawn] = {}
    for out in model.fns.values():
        for sp in out.spawns:
            observed[(sp.module, sp.symbol, sp.target)] = sp
    expected: Dict[Tuple[str, str, str], Dict] = {}
    for mod, entries in (specs or {}).items():
        for e in entries:
            expected[(mod, e["symbol"], e["target"])] = e

    out_v: List[Violation] = []
    for key in sorted(set(observed) | set(expected)):
        mod, symbol, target = key
        sp = observed.get(key)
        e = expected.get(key)
        sym = f"{symbol}[{target}]"
        if sp is not None and _suppressed(model, mod, sp.line, "QT203"):
            continue
        if e is None:
            out_v.append(Violation(
                rule="QT203", path=mod, line=sp.line, symbol=sym,
                message=f"unexpected {sp.kind} spawn (daemon="
                        f"{sp.daemon}, joined={sp.joined}) — add it to "
                        f"THREAD_SPAWN_SPECS in analysis/specs.py with "
                        f"its shutdown story, or remove the spawn"))
            continue
        if sp is None:
            out_v.append(Violation(
                rule="QT203", path=mod, line=0, symbol=sym,
                message="spec expects this thread spawn but the tree "
                        "no longer has it — update THREAD_SPAWN_SPECS"))
            continue
        mismatches = []
        if "daemon" in e and bool(e["daemon"]) != bool(sp.daemon):
            mismatches.append(
                f"daemon: spec {e['daemon']}, tree {sp.daemon}")
        if "joined" in e and bool(e["joined"]) != sp.joined:
            mismatches.append(
                f"joined: spec {e['joined']}, tree {sp.joined}")
        if mismatches:
            out_v.append(Violation(
                rule="QT203", path=mod, line=sp.line, symbol=sym,
                message="spawn census mismatch: " + "; ".join(
                    mismatches)))
    return out_v


def thread_spawn_census(parsed) -> List[Dict]:
    """The raw census (JSON-able), for --json consumers and tests."""
    model = _build_model(parsed)
    out = []
    for scan in model.fns.values():
        for sp in scan.spawns:
            out.append({"module": sp.module, "symbol": sp.symbol,
                        "line": sp.line, "target": sp.target,
                        "daemon": sp.daemon, "joined": sp.joined,
                        "kind": sp.kind})
    return sorted(out, key=lambda d: (d["module"], d["line"]))


# ---------------------------------------------------------------------------
# entry points


def _build_model(parsed) -> _TreeModel:
    model = _TreeModel()
    for sf in parsed:
        if sf.tree is None:
            continue
        model.add_module(sf.rel, sf.source, sf.tree)
    model.scan_all()
    return model


def audit_parsed(parsed, *, rules: Optional[Sequence[str]] = None,
                 specs: Optional[Dict] = None) -> List[Violation]:
    """Run the concurrency pass over pre-parsed sources (the shared
    parse from :func:`analysis.lint.collect_sources` — each file is
    read and parsed ONCE for all passes)."""
    active = set(rules) if rules else set(RULES)
    model = _build_model(parsed)
    calls = list(_resolved_calls(model))
    roots = _thread_roots(model)
    ambient = _ambient_held(model, roots, calls)
    out: List[Violation] = []
    if "QT201" in active:
        eff, chain = _effective_acquires(model, calls)
        edges = _lock_order_edges(model, calls, ambient, eff, chain)
        out.extend(_qt201(model, edges))
    if "QT202" in active:
        out.extend(_qt202(model, roots, calls, ambient))
    if "QT203" in active:
        out.extend(_qt203(model, specs if specs is not None
                          else load_thread_specs()))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def audit_paths(paths: Sequence[str] = THREAD_PATHS, *,
                root: str = ".",
                rules: Optional[Sequence[str]] = None,
                specs: Optional[Dict] = None) -> List[Violation]:
    return audit_parsed(collect_sources(paths, root=root),
                        rules=rules, specs=specs)


def audit_sources(named_sources: Sequence[Tuple[str, str]], *,
                  rules: Optional[Sequence[str]] = None,
                  specs: Optional[Dict] = None) -> List[Violation]:
    """Test-facing: audit in-memory (rel_path, source) pairs as one
    file set. ``specs`` defaults to EMPTY here (synthetic sources
    should not be judged against the repo's spawn spec)."""
    parsed = [_lint.SourceFile(rel, src, ast.parse(src, filename=rel))
              for rel, src in named_sources]
    return audit_parsed(parsed, rules=rules,
                        specs=specs if specs is not None else {})

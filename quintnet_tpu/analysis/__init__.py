"""qtcheck: static analysis for QuintNet-TPU's compiled programs.

Three passes, one CI gate (``python -m quintnet_tpu.tools.qtcheck``):

- :mod:`~quintnet_tpu.analysis.jaxpr_audit` — lower any jitted function
  and walk its jaxpr: per-axis collective census, dtype-promotion
  report, buffer-donation report;
- :mod:`~quintnet_tpu.analysis.recompile` — count lowerings by abstract
  signature; enforce "exactly N compiled programs" (the serve engine's
  one-prefill-one-decode promise, the trainer's one-step promise);
- :mod:`~quintnet_tpu.analysis.lint` — AST rules for JAX footguns
  (host numpy / Python RNG in traced code, tracer branching, step-loop
  host syncs, array defaults, unsynced wall-clock timing) with a
  committed baseline (tools/qtcheck_baseline.json).

Expected-census specs for the shipped programs live in
:mod:`~quintnet_tpu.analysis.specs`; tests/test_qtcheck.py pins them.
"""

from quintnet_tpu.analysis.jaxpr_audit import (
    Census,
    collective_census,
    donation_report,
    dtype_report,
    gathered_view_gathers,
)
from quintnet_tpu.analysis.lint import (
    RULES,
    Violation,
    compare_baseline,
    lint_paths,
    lint_source,
    load_baseline,
    violations_to_baseline,
)
from quintnet_tpu.analysis.recompile import (
    RecompileError,
    RecompileSentinel,
    abstract_signature,
    assert_compile_count,
    check_serving_compile_counts,
)

__all__ = [
    "Census",
    "collective_census",
    "donation_report",
    "dtype_report",
    "gathered_view_gathers",
    "RULES",
    "Violation",
    "compare_baseline",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "violations_to_baseline",
    "RecompileError",
    "RecompileSentinel",
    "abstract_signature",
    "assert_compile_count",
    "check_serving_compile_counts",
]

"""qtcheck: static analysis for QuintNet-TPU's compiled programs.

Four passes, one CI gate (``python -m quintnet_tpu.tools.qtcheck``):

- :mod:`~quintnet_tpu.analysis.jaxpr_audit` — lower any jitted function
  and walk its jaxpr: per-axis collective census, dtype-promotion
  report, buffer-donation report;
- :mod:`~quintnet_tpu.analysis.recompile` — count lowerings by abstract
  signature; enforce "exactly N compiled programs" (the serve engine's
  one-prefill-one-decode promise, the trainer's one-step promise);
- :mod:`~quintnet_tpu.analysis.lint` — AST rules for JAX footguns
  (host numpy / Python RNG in traced code, tracer branching, step-loop
  host syncs, array defaults, unsynced wall-clock timing) with a
  committed baseline (tools/qtcheck_baseline.json);
- :mod:`~quintnet_tpu.analysis.threads` — AST concurrency rules for the
  serving fleet (lock-order cycles, guarded-by inference, thread-spawn
  census vs the declarative spec) with its own committed baseline
  (tools/qtcheck_threads_baseline.json). Its runtime twin,
  :mod:`~quintnet_tpu.analysis.lockrt`, wraps ``threading`` locks with
  order/hold/contention instrumentation behind the fleets'
  ``lock_audit=`` flag.

Expected-census specs for the shipped programs (and the thread-spawn
spec) live in :mod:`~quintnet_tpu.analysis.specs`; tests/test_qtcheck.py
and tests/test_qtcheck_threads.py pin them.
"""

from quintnet_tpu.analysis.jaxpr_audit import (
    Census,
    collective_census,
    donation_report,
    dtype_report,
    gathered_view_gathers,
)
from quintnet_tpu.analysis.lint import (
    RULES,
    Violation,
    collect_sources,
    compare_baseline,
    lint_parsed,
    lint_paths,
    lint_source,
    load_baseline,
    violations_to_baseline,
)
from quintnet_tpu.analysis.lockrt import (
    InstrumentedLock,
    LockAudit,
    LockOrderError,
)
from quintnet_tpu.analysis.recompile import (
    RecompileError,
    RecompileSentinel,
    abstract_signature,
    assert_compile_count,
    check_serving_compile_counts,
)
from quintnet_tpu.analysis.threads import (
    RULES as THREAD_RULES,
    THREAD_PATHS,
    audit_parsed,
    audit_paths,
    audit_sources,
    load_thread_specs,
    thread_spawn_census,
)

__all__ = [
    "Census",
    "collective_census",
    "donation_report",
    "dtype_report",
    "gathered_view_gathers",
    "RULES",
    "Violation",
    "collect_sources",
    "compare_baseline",
    "lint_parsed",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "violations_to_baseline",
    "InstrumentedLock",
    "LockAudit",
    "LockOrderError",
    "RecompileError",
    "RecompileSentinel",
    "abstract_signature",
    "assert_compile_count",
    "check_serving_compile_counts",
    "THREAD_PATHS",
    "THREAD_RULES",
    "audit_parsed",
    "audit_paths",
    "audit_sources",
    "load_thread_specs",
    "thread_spawn_census",
]

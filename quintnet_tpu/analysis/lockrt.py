"""Instrumented-lock runtime: the dynamic twin of the static pass in
:mod:`~quintnet_tpu.analysis.threads`.

The static auditor proves lock ORDER over the paths it can resolve;
this module watches the orders that actually happen. An opt-in
:class:`InstrumentedLock` is a drop-in ``threading.Lock``/``RLock``
wrapper (context manager, ``acquire``/``release``, and the full
``threading.Condition`` protocol — ``_is_owned``/``_release_save``/
``_acquire_restore`` — so ``Condition(audit.rlock("x"))`` behaves
exactly like ``Condition()``) that records, per thread, the stack of
locks currently held. Every first-time ordered pair (held A, acquiring
B) becomes an edge in a process-local order graph with the acquiring
call stack attached; the moment the REVERSE direction is observed the
acquire raises a typed :class:`LockOrderError` naming both stacks —
the deadlock is reported at the first inverted acquisition, not on the
unlucky interleaving that would actually wedge.

Ledgers per lock: acquisitions, contended acquisitions, cumulative
wait and hold seconds, max hold, and held-too-long counts against an
optional ``hold_budget_s`` — all exported by
:meth:`LockAudit.summary` as a JSON-able dict the fleet renders into
the ``quintnet_lock_*`` Prometheus families (obs/prom.py) and embeds
in crash dumps. A held-too-long WATCHDOG is available two ways:
deterministically via :meth:`LockAudit.check_held` (tests drive it
with an injected clock) or as a daemon thread
(``watchdog_interval_s=``) for long-lived fleets.

Inert by design: ``ServeFleet``/``ProcessFleet`` grow a
``lock_audit=`` flag that swaps their locks for instrumented ones;
with the flag off nothing here is constructed and the fleet's code
path is byte-identical to before this module existed. With it on, the
bookkeeping is a few dict operations per acquisition — and the
kill-migration goldens pin that audited output is token-identical to
unaudited (tests/test_qtcheck_threads.py).

No jax imports — loadable by file path like lint.py/threads.py.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Callable, Dict, List, Optional, Tuple


def _stack(skip: int = 2, limit: int = 8) -> str:
    """A short formatted stack for edge provenance: the acquiring
    frames, with this module's own frames trimmed."""
    frames = traceback.extract_stack()[:-skip]
    frames = [f for f in frames if "lockrt" not in f.filename][-limit:]
    return "".join(traceback.format_list(frames)).rstrip()


class LockOrderError(RuntimeError):
    """Two locks were acquired in both orders. Raised BEFORE the
    inverting acquisition blocks, carrying both acquisition stacks —
    the would-be deadlock as a readable report instead of a hang."""

    def __init__(self, first: str, second: str, *, forward_stack: str,
                 reverse_stack: str, thread: str):
        self.first = first
        self.second = second
        self.forward_stack = forward_stack
        self.reverse_stack = reverse_stack
        self.thread = thread
        super().__init__(
            f"lock-order inversion: {first} -> {second} was recorded "
            f"earlier, and thread {thread!r} now holds {second} while "
            f"acquiring {first}.\n"
            f"--- earlier {first} -> {second} acquisition ---\n"
            f"{forward_stack}\n"
            f"--- current {second} -> {first} acquisition ---\n"
            f"{reverse_stack}")


class _Ledger:
    __slots__ = ("acquisitions", "contended", "wait_s", "hold_s",
                 "max_hold_s", "held_too_long")

    def __init__(self):
        self.acquisitions = 0
        self.contended = 0
        self.wait_s = 0.0
        self.hold_s = 0.0
        self.max_hold_s = 0.0
        self.held_too_long = 0


class _Held:
    """One entry on a thread's held stack."""

    __slots__ = ("lock", "since", "depth")

    def __init__(self, lock: "InstrumentedLock", since: float):
        self.lock = lock
        self.since = since
        self.depth = 1


class LockAudit:
    """Process-local registry: the observed lock-order graph plus the
    per-lock ledgers. One audit per fleet; every lock it mints shares
    the graph, so cross-subsystem inversions (fleet lock vs a
    replica's ring lock) are visible."""

    def __init__(self, *, clock: Callable[[], float] = time.monotonic,
                 hold_budget_s: Optional[float] = None,
                 on_violation: Optional[Callable[[Dict], None]] = None,
                 watchdog_interval_s: Optional[float] = None):
        self.clock = clock
        self.hold_budget_s = hold_budget_s
        self.on_violation = on_violation
        # graph + ledgers are mutated under their own private lock (a
        # plain one — the audit must not audit itself)
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], str] = {}   # (a, b) -> stack
        self._locks: Dict[str, InstrumentedLock] = {}
        self.order_violations = 0
        self._tls = threading.local()
        self._watchdog: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()
        if watchdog_interval_s is not None:
            self._watchdog = threading.Thread(
                target=self._watch_loop, args=(float(watchdog_interval_s),),
                name="lock-audit-watchdog", daemon=True)
            self._watchdog.start()

    # ---- lock minting -----------------------------------------------
    def lock(self, name: str) -> "InstrumentedLock":
        return self._mint(name, threading.Lock(), reentrant=False)

    def rlock(self, name: str) -> "InstrumentedLock":
        return self._mint(name, threading.RLock(), reentrant=True)

    def condition(self, name: str) -> threading.Condition:
        """A ``Condition`` over an instrumented RLock — the drop-in
        for ``threading.Condition()`` (whose default lock IS an
        RLock)."""
        return threading.Condition(self.rlock(name))

    def _mint(self, name: str, inner,
              reentrant: bool) -> "InstrumentedLock":
        with self._mu:
            have = self._locks.get(name)
            if have is not None:
                if have.reentrant != reentrant:
                    raise ValueError(
                        f"lock name {name!r} already minted with "
                        f"reentrant={have.reentrant} — names key the "
                        f"ledgers and the order graph, reuse across "
                        f"kinds would merge two locks' stories")
                # same name, same kind: the SAME lock (a re-armed
                # subsystem replacing its predecessor keeps the node,
                # its ledger, and its edges — one story per name)
                return have
            lk = InstrumentedLock(self, name, inner, reentrant)
            self._locks[name] = lk
            return lk

    # ---- per-thread held stack --------------------------------------
    def _held(self) -> List[_Held]:
        st = getattr(self._tls, "held", None)
        if st is None:
            st = self._tls.held = []
        return st

    # ---- order graph -------------------------------------------------
    def _note_acquire(self, lock: "InstrumentedLock") -> None:
        """Called BEFORE blocking on ``lock``; raises on an inversion."""
        held = self._held()
        for entry in held:
            if entry.lock is lock:
                if lock.reentrant:
                    return        # re-entrant re-acquire: no new edges
                self.order_violations += 1
                raise LockOrderError(
                    lock.name, lock.name,
                    forward_stack="(self-deadlock: non-reentrant lock "
                                  "re-acquired by its owner)",
                    reverse_stack=_stack(),
                    thread=threading.current_thread().name)
        if not held:
            return
        stack = None
        with self._mu:
            for entry in held:
                a, b = entry.lock.name, lock.name
                rev = self._edges.get((b, a))
                if rev is not None:
                    self.order_violations += 1
                    info = {
                        "first": b, "second": a,
                        "thread": threading.current_thread().name,
                        "forward_stack": rev,
                        "reverse_stack": _stack(),
                    }
                    cb = self.on_violation
                    err = LockOrderError(
                        b, a, forward_stack=rev,
                        reverse_stack=info["reverse_stack"],
                        thread=info["thread"])
                    break
                if (a, b) not in self._edges:
                    if stack is None:
                        stack = _stack()
                    self._edges[(a, b)] = stack
            else:
                return
        if cb is not None:
            try:
                cb(info)
            except Exception:
                pass              # observability must not mask the error
        raise err

    def _push(self, lock: "InstrumentedLock", now: float) -> None:
        held = self._held()
        for entry in held:
            if entry.lock is lock:
                entry.depth += 1
                return
        held.append(_Held(lock, now))

    def _pop(self, lock: "InstrumentedLock", now: float,
             full: bool = False) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            entry = held[i]
            if entry.lock is not lock:
                continue
            entry.depth -= 1
            if entry.depth > 0 and not full:
                return
            del held[i]
            hold = max(now - entry.since, 0.0)
            led = lock.ledger
            with self._mu:
                led.hold_s += hold
                led.max_hold_s = max(led.max_hold_s, hold)
                if (self.hold_budget_s is not None
                        and hold > self.hold_budget_s):
                    led.held_too_long += 1
            return

    # ---- watchdog ----------------------------------------------------
    def check_held(self, now: Optional[float] = None) -> List[Dict]:
        """Held-too-long check over every lock currently held by ANY
        thread (each acquisition stamps ``holder``/``held_since`` on
        its lock). Returns the offenders; deterministic with an
        injected clock, also what the watchdog thread runs."""
        if self.hold_budget_s is None:
            return []
        now = self.clock() if now is None else now
        out = []
        with self._mu:
            for name, lk in self._locks.items():
                since = lk.held_since
                if since is None:
                    continue
                age = now - since
                if age > self.hold_budget_s:
                    lk.ledger.held_too_long += 1
                    out.append({"lock": name, "held_s": age,
                                "holder": lk.holder,
                                "budget_s": self.hold_budget_s})
        return out

    def _watch_loop(self, interval: float) -> None:
        while not self._watchdog_stop.wait(interval):
            self.check_held()

    def close(self) -> None:
        self._watchdog_stop.set()

    # ---- export ------------------------------------------------------
    def summary(self) -> Dict:
        """JSON-able ledgers: what obs/prom.py renders as the
        ``quintnet_lock_*`` families and crash dumps embed."""
        with self._mu:
            locks = {
                name: {
                    "acquisitions": lk.ledger.acquisitions,
                    "contended": lk.ledger.contended,
                    "wait_s": round(lk.ledger.wait_s, 6),
                    "hold_s": round(lk.ledger.hold_s, 6),
                    "max_hold_s": round(lk.ledger.max_hold_s, 6),
                    "held_too_long": lk.ledger.held_too_long,
                }
                for name, lk in sorted(self._locks.items())}
            return {"order_edges": len(self._edges),
                    "order_violations": self.order_violations,
                    "locks": locks}

    def edges(self) -> Dict[Tuple[str, str], str]:
        with self._mu:
            return dict(self._edges)


class InstrumentedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper wired to a
    :class:`LockAudit` (mint via ``audit.lock(name)`` /
    ``audit.rlock(name)``). Supports the full Condition protocol so it
    can back a ``threading.Condition`` — ``wait()`` pops the audit's
    held-stack entry on the way to sleep and re-pushes on wake, so a
    waiting thread is correctly modeled as holding nothing."""

    __slots__ = ("audit", "name", "_inner", "reentrant", "ledger",
                 "holder", "held_since")

    def __init__(self, audit: LockAudit, name: str, inner,
                 reentrant: bool):
        self.audit = audit
        self.name = name
        self._inner = inner
        self.reentrant = reentrant
        self.ledger = _Ledger()
        self.holder: Optional[str] = None
        self.held_since: Optional[float] = None

    # ---- Lock protocol ----------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self.audit._note_acquire(self)
        clock = self.audit.clock
        t0 = clock()
        got = self._inner.acquire(False)
        if not got:
            if not blocking:
                return False
            with self.audit._mu:
                self.ledger.contended += 1
            got = self._inner.acquire(True, timeout)
            if not got:
                return False
        now = clock()
        with self.audit._mu:
            self.ledger.acquisitions += 1
            self.ledger.wait_s += max(now - t0, 0.0)
        if self.holder is None:
            self.holder = threading.current_thread().name
            self.held_since = now
        self.audit._push(self, now)
        return True

    def release(self) -> None:
        self.audit._pop(self, self.audit.clock())
        if not any(e.lock is self for e in self.audit._held()):
            self.holder = None
            self.held_since = None
        self._inner.release()

    def locked(self) -> bool:
        if hasattr(self._inner, "locked"):
            return self._inner.locked()
        return self.held_since is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # ---- Condition protocol -----------------------------------------
    def _is_owned(self) -> bool:
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return any(e.lock is self for e in self.audit._held())

    def _release_save(self):
        """Condition.wait: fully release (RLock: unwind every level)
        and clear the audit's held entry — a sleeping waiter holds
        nothing."""
        self.audit._pop(self, self.audit.clock(), full=True)
        self.holder = None
        self.held_since = None
        if hasattr(self._inner, "_release_save"):
            return ("r", self._inner._release_save())
        self._inner.release()
        return ("l", None)

    def _acquire_restore(self, state) -> None:
        kind, inner_state = state
        clock = self.audit.clock
        t0 = clock()
        if kind == "r":
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        now = clock()
        with self.audit._mu:
            self.ledger.acquisitions += 1
            self.ledger.wait_s += max(now - t0, 0.0)
        self.holder = threading.current_thread().name
        self.held_since = now
        self.audit._push(self, now)

    def __repr__(self) -> str:
        return (f"<InstrumentedLock {self.name!r} "
                f"{'rlock' if self.reentrant else 'lock'} "
                f"holder={self.holder!r}>")

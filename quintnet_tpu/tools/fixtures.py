"""Shared deterministic fixtures for the verifier CLIs.

Every ground-truth verifier (verify_gpt2, verify_llama, and the parity
harness they anchor) must score BOTH frameworks on the SAME batch, and
two runs of the same verifier must score the same batch again — so the
token fixture is a seeded ``default_rng`` draw, not ``np.random``
global state. It used to be copy-pasted per verifier; one copy
drifting (a different seed, an int64 dtype reaching an int32 embedding
lookup) would silently turn a parity check into a comparison of two
different inputs.
"""

from __future__ import annotations

import numpy as np


def random_token_ids(vocab_size: int, batch: int, seq: int, *,
                     seed: int = 0) -> np.ndarray:
    """Deterministic [batch, seq] int32 token ids in [0, vocab_size) —
    the common eval batch of the HF cross-check verifiers."""
    return np.random.default_rng(seed).integers(
        0, vocab_size, (batch, seq), dtype=np.int32)

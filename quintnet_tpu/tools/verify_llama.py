"""Single-command Llama ground-truth verifier vs HF transformers.

Builds a random-weight HF ``LlamaForCausalLM`` locally (no network),
imports its state dict through :func:`llama_from_hf_state`, and compares
logits + CLM loss between this framework and torch on the same batch —
the same oracle tests/test_llama.py pins in CI, packaged as a CLI
(reference analogue: test.py:28-113, which verifies merged GPT-2
checkpoints against HF).

  python -m quintnet_tpu.tools.verify_llama            # tiny geometry
  python -m quintnet_tpu.tools.verify_llama --rope-scaling  # llama3 rope
  python -m quintnet_tpu.tools.verify_llama --hf-dir /path/to/llama
      # a real downloaded checkpoint directory, when one is available
"""

from __future__ import annotations

import argparse
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf-dir", default=None,
                    help="local HF Llama checkpoint dir (optional; "
                         "default builds a random tiny model)")
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--rope-scaling", action="store_true",
                    help="exercise llama3 rope scaling in the tiny model")
    ap.add_argument("--tol", type=float, default=2e-4)
    args = ap.parse_args()

    import numpy as np
    import torch
    import transformers

    import jax

    # ground truth is single-device CPU math; also this environment's
    # sitecustomize pins an experimental TPU platform that may be
    # tunnelled/down — the verifier must not depend on it
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from quintnet_tpu.models.gpt2 import clm_loss
    from quintnet_tpu.models.llama import (LlamaConfig, llama_apply,
                                           llama_from_hf_state)

    if args.hf_dir:
        hf = transformers.LlamaForCausalLM.from_pretrained(
            args.hf_dir, torch_dtype=torch.float32).eval()
        hf_cfg = hf.config
    else:
        tiny = LlamaConfig.tiny()
        scaling = ({"rope_type": "llama3", "factor": 8.0,
                    "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                    "original_max_position_embeddings": 32}
                   if args.rope_scaling else None)
        hf_cfg = transformers.LlamaConfig(
            vocab_size=tiny.vocab_size, hidden_size=tiny.dim,
            intermediate_size=tiny.intermediate_size,
            num_hidden_layers=tiny.n_layers,
            num_attention_heads=tiny.n_heads,
            num_key_value_heads=tiny.n_kv_heads,
            max_position_embeddings=max(64, args.seq + 1),
            rope_theta=tiny.rope_theta, rms_norm_eps=tiny.rms_eps,
            tie_word_embeddings=False, attention_bias=False,
            mlp_bias=False, rope_scaling=scaling)
        torch.manual_seed(0)
        hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = LlamaConfig.from_hf_config(hf_cfg)
    params = llama_from_hf_state(hf.state_dict(), cfg)

    # shared seeded fixture: both frameworks must score the SAME batch
    from quintnet_tpu.tools.fixtures import random_token_ids

    ids = random_token_ids(cfg.vocab_size, args.batch, args.seq)
    with torch.no_grad():
        t = torch.from_numpy(ids).long()
        out = hf(t, labels=t)
        ref_logits = out.logits.numpy()
        ref_loss = float(out.loss)

    logits = np.asarray(llama_apply(params, jnp.asarray(ids), cfg))
    loss = float(clm_loss(jnp.asarray(logits), jnp.asarray(ids)))

    max_abs = float(np.max(np.abs(logits - ref_logits)))
    denom = float(np.max(np.abs(ref_logits))) or 1.0
    rel = max_abs / denom
    print(f"logits: max|diff| {max_abs:.3e} (rel {rel:.3e}); "
          f"loss here {loss:.6f} vs torch {ref_loss:.6f} "
          f"(diff {abs(loss - ref_loss):.2e})")
    ok = rel < args.tol and abs(loss - ref_loss) < 1e-3
    print("VERIFY", "PASS" if ok else "FAIL",
          f"(tol {args.tol}, rope_scaling="
          f"{cfg.rope_scaling is not None})")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()

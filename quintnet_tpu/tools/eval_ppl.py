"""Perplexity of a GPT-2 or Llama checkpoint over a text file.

The packed-stride evaluation standard: tokenise the whole file, pack
into windows of ``--seq`` with no padding (data/datasets.pack_documents),
mean CLM loss -> ppl = exp(loss). Works offline with the byte-level
fallback tokenizer; pass an HF tokenizer dir for real BPE.

  python -m quintnet_tpu.tools.eval_ppl --text file.txt \
      [--family gpt2|llama] [--checkpoint model.safetensors] \
      [--tokenizer tok_dir] [--seq 512] [--batch 8]

Without --checkpoint a random tiny model runs (plumbing smoke; the
number is meaningless). Reference analogue: none — the reference
evaluates perplexity only inside its training loop.
"""

from __future__ import annotations

import argparse
import math


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", required=True)
    ap.add_argument("--family", default="gpt2", choices=["gpt2", "llama"])
    ap.add_argument("--checkpoint", default=None,
                    help="HF safetensors (gpt2) — random tiny model if "
                         "omitted")
    ap.add_argument("--tokenizer", default=None,
                    help="HF tokenizer dir; default byte-level")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--platform", default="cpu",
                    help="'cpu' (default) or e.g. 'tpu'")
    ap.add_argument("--isolate-docs", action="store_true",
                    help="mask cross-document attention in the packed "
                         "windows (segment_eos_id on the model config) — "
                         "match this to how the model was TRAINED")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np

    from quintnet_tpu.data import ByteTokenizer
    from quintnet_tpu.data.datasets import pack_documents
    from quintnet_tpu.models.gpt2 import clm_loss

    if args.tokenizer:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.tokenizer)
    else:
        tok = ByteTokenizer()

    text = open(args.text, encoding="utf-8").read()
    eos = getattr(tok, "eos_token_id", 0) or 0
    enc = tok.encode(text)
    rows = pack_documents([enc], args.seq, eos_id=eos,
                          drop_remainder=False)
    # pack_documents EOS-pads the final window; those synthetic
    # positions must not count in the loss (they bias ppl downward on
    # repetitive EOS runs). Labels mask the tail with IGNORE_INDEX —
    # inputs keep the padding (shapes stay static).
    from quintnet_tpu.models.gpt2 import IGNORE_INDEX

    if not enc:
        raise SystemExit(f"--text {args.text}: no tokens to evaluate")
    labels = rows.copy()
    n_real = len(enc) + 1  # + the appended EOS separator
    rem = n_real % args.seq
    if rem:
        labels[-1, rem:] = IGNORE_INDEX
    print(f"{len(rows)} windows x {args.seq} tokens "
          f"({n_real} real tokens)")

    if args.family == "gpt2":
        from quintnet_tpu.models.gpt2 import (GPT2Config, gpt2_apply,
                                              gpt2_init)

        if args.checkpoint:
            from quintnet_tpu.models.gpt2_io import load_hf_gpt2

            params, cfg = load_hf_gpt2(args.checkpoint)
        else:
            v = -(-max(getattr(tok, "vocab_size", 257), 128) // 8) * 8
            cfg = GPT2Config.tiny(vocab_size=v,
                                  n_positions=max(64, args.seq))
            params = gpt2_init(jax.random.key(0), cfg)
        if args.isolate_docs:
            import dataclasses

            cfg = dataclasses.replace(cfg, segment_eos_id=eos)
        apply_fn = lambda p, ids: gpt2_apply(p, ids, cfg)  # noqa: E731
    else:
        from quintnet_tpu.models.llama import (LlamaConfig, llama_apply,
                                               llama_init)

        if args.checkpoint:
            # llama loading takes an HF DIRECTORY (config + weights);
            # load via transformers, import through llama_from_hf_state
            import os as _os

            if not _os.path.isdir(args.checkpoint):
                raise SystemExit(
                    f"--family llama --checkpoint wants an HF model "
                    f"DIRECTORY, got {args.checkpoint!r}")
            import torch
            import transformers

            from quintnet_tpu.models.llama import llama_from_hf_state

            hf = transformers.LlamaForCausalLM.from_pretrained(
                args.checkpoint, torch_dtype=torch.float32).eval()
            cfg = LlamaConfig.from_hf_config(hf.config)
            params = llama_from_hf_state(hf.state_dict(), cfg)
            if args.isolate_docs:
                import dataclasses

                cfg = dataclasses.replace(cfg, segment_eos_id=eos)
            apply_fn = lambda p, ids: llama_apply(p, ids, cfg)  # noqa: E731
            _run_eval(args, jax, jnp, np, clm_loss, IGNORE_INDEX, rows,
                      labels, params, apply_fn)
            return
        v = -(-max(getattr(tok, "vocab_size", 257), 128) // 8) * 8
        cfg = LlamaConfig.tiny(vocab_size=v,
                               n_positions=max(64, args.seq))
        if args.isolate_docs:
            import dataclasses

            cfg = dataclasses.replace(cfg, segment_eos_id=eos)
        params = llama_init(jax.random.key(0), cfg)
        apply_fn = lambda p, ids: llama_apply(p, ids, cfg)  # noqa: E731

    _run_eval(args, jax, jnp, np, clm_loss, IGNORE_INDEX, rows, labels,
              params, apply_fn)


def _run_eval(args, jax, jnp, np, clm_loss, IGNORE_INDEX, rows, labels,
              params, apply_fn):
    import warnings
    from functools import partial

    # donate each batch: it is rebuilt per iteration and dead after the
    # loss — freeing it during the forward instead of after the call
    @partial(jax.jit, donate_argnums=(1, 2))
    def batch_loss(p, ids, lab):
        return clm_loss(apply_fn(p, ids), lab)

    losses, weights = [], []
    with warnings.catch_warnings():
        # scalar output -> the donation frees rather than aliases and
        # XLA warns; expected here (docs/static_analysis.md), scoped so
        # genuine donation mistakes elsewhere still warn
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        for i in range(0, len(rows), args.batch):
            b, lb = rows[i:i + args.batch], labels[i:i + args.batch]
            losses.append(float(batch_loss(params, jnp.asarray(b),
                                           jnp.asarray(lb))))
            # weight by REAL (unmasked) shifted targets, not row count —
            # the final window contributes only its real tokens
            weights.append(int(np.sum(lb[:, 1:] != IGNORE_INDEX)))
    loss = float(np.average(losses, weights=weights))
    print(f"loss {loss:.4f}  perplexity {math.exp(min(loss, 20.0)):.2f}")


if __name__ == "__main__":
    main()

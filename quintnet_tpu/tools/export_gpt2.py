"""Export a trained GPT-2 checkpoint to HF-layout safetensors.

Reference: merge_checkpoints.py — an offline CLI that re-assembles
per-(pp,tp)-shard .pt files (TP concat by dim, PP layer renumber, Conv1D
transposes) into a HF GPT2LMHeadModel state dict. Orbax checkpoints are
already logically whole (sharding lives in metadata, restore gathers),
so this "merge" is a restore + layout conversion:

  python -m quintnet_tpu.tools.export_gpt2 \
      --checkpoint-dir ckpts/ --out gpt2_merged.safetensors \
      [--step N] [--tp-layout TP]

--tp-layout: pass the tp size the model was trained with so fused-QKV
columns are unpermuted from the tp-blocked layout back to HF's [q|k|v].
"""

from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--step", type=int, default=None)
    ap.add_argument("--tp-layout", type=int, default=1)
    ap.add_argument("--n-layer", type=int, default=12)
    ap.add_argument("--n-embd", type=int, default=768)
    ap.add_argument("--n-head", type=int, default=12)
    ap.add_argument("--vocab-size", type=int, default=50257)
    ap.add_argument("--n-positions", type=int, default=1024)
    args = ap.parse_args()

    import jax

    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_init
    from quintnet_tpu.models.gpt2_io import save_hf_gpt2
    from quintnet_tpu.train.checkpoint import CheckpointManager

    cfg = GPT2Config(vocab_size=args.vocab_size,
                     n_positions=args.n_positions, n_embd=args.n_embd,
                     n_layer=args.n_layer, n_head=args.n_head)
    template = jax.eval_shape(lambda: gpt2_init(jax.random.key(0), cfg))
    template = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), template)

    mgr = CheckpointManager(args.checkpoint_dir)
    state = mgr.restore({"params": template, "opt": None, "epoch": 0},
                        step=args.step)
    save_hf_gpt2(state["params"], cfg, args.out, tp_layout=args.tp_layout)
    print(f"wrote {args.out} (step {args.step or mgr.latest_step()})")


if __name__ == "__main__":
    main()

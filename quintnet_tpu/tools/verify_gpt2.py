"""Single-device ground-truth verifier for distributed GPT-2 training.

Reference: test.py:28-113 — load the merged checkpoint into HF
GPT2LMHeadModel on ONE device with no distributed code and recompute
loss/perplexity; metric parity with the distributed run is the
acceptance criterion. Here both paths run from the same process:

  python -m quintnet_tpu.tools.verify_gpt2 --hf-file merged.safetensors

Computes (a) framework single-device loss, (b) torch/transformers loss
on the same batch, and reports the delta.
"""

from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hf-file", required=True)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--n-head", type=int, default=12)
    args = ap.parse_args()

    import numpy as np

    import jax.numpy as jnp

    from quintnet_tpu.models.gpt2 import clm_loss, gpt2_apply
    from quintnet_tpu.models.gpt2_io import load_hf_gpt2
    from quintnet_tpu.tools.fixtures import random_token_ids

    params, cfg = load_hf_gpt2(args.hf_file)
    if cfg.n_head != args.n_head:
        from dataclasses import replace

        cfg = replace(cfg, n_head=args.n_head)
    # shared seeded fixture: both frameworks must score the SAME batch
    ids = random_token_ids(cfg.vocab_size, args.batch, args.seq)

    logits = gpt2_apply(params, jnp.asarray(ids), cfg)
    loss_jax = float(clm_loss(logits, jnp.asarray(ids)))
    print(f"quintnet_tpu single-device loss: {loss_jax:.6f} "
          f"ppl {np.exp(min(loss_jax, 20)):.2f}")

    try:
        import torch
        import transformers

        hf_cfg = transformers.GPT2Config(
            vocab_size=cfg.vocab_size, n_positions=cfg.n_positions,
            n_embd=cfg.n_embd, n_layer=cfg.n_layer, n_head=cfg.n_head)
        model = transformers.GPT2LMHeadModel(hf_cfg).eval()
        sd = model.state_dict()
        from quintnet_tpu.utils.safetensors_io import SafeTensorFile

        with SafeTensorFile(args.hf_file) as f:
            loaded = {k: torch.tensor(np.array(f.tensor(k)))
                      for k in f.keys()}
        # file may or may not carry the transformer. prefix
        fixed = {}
        for k, v in loaded.items():
            kk = k if k.startswith("transformer.") else "transformer." + k
            fixed[kk] = v
        fixed["lm_head.weight"] = fixed["transformer.wte.weight"]
        missing, unexpected = model.load_state_dict(fixed, strict=False)
        t_ids = torch.tensor(ids, dtype=torch.long)
        with torch.no_grad():
            out = model(t_ids, labels=t_ids)
        loss_t = float(out.loss)
        print(f"transformers reference loss:   {loss_t:.6f} "
              f"ppl {np.exp(min(loss_t, 20)):.2f}")
        print(f"abs diff: {abs(loss_jax - loss_t):.2e}")
    except ImportError:
        print("torch/transformers unavailable; skipped cross-check")


if __name__ == "__main__":
    main()

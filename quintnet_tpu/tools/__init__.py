"""Offline CLIs: checkpoint export/merge, single-device verification."""

"""Mesh planner: recommend (dp, tp, pp, sp) for a model + chip budget.

The scaling-book recipe is "pick a mesh, annotate shardings, let XLA
insert collectives, profile, iterate" — this tool automates the *first*
pick. Given a GPT-2 config, global batch, sequence length and a chip
budget, it enumerates every legal axis assignment, estimates per-chip
memory from the framework's actual sharding rules
(parallel/strategy.py / models/gpt2.py partition specs), rejects plans
that blow HBM, and ranks survivors by a simple comm-volume heuristic
(ICI-bytes moved per step — all estimates are order-of-magnitude
planning aids, not measurements; profile the top pick).

The reference has no planning tooling at all — mesh shapes are
hand-written YAML (examples/config.yaml:16-24) and a bad pick fails at
NCCL-init or OOM time. Here a bad pick is rejected on the host in
milliseconds.

CLI:
    python -m quintnet_tpu.tools.plan_mesh --model gpt2-medium \
        --devices 16 --batch 64 --seq 1024 [--hbm-gb 16] [--zero1] \
        [--vocab-parallel] [--top 5]
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass
from typing import Dict, List, Optional

from quintnet_tpu.models.gpt2 import GPT2Config

GB = 1 << 30


def _geometry(cfg):
    """(d, L, V, block_params, embed_params, pos_params, n_head) for a
    GPT2Config or LlamaConfig — the planner's memory model is geometry-
    driven, so both families share one estimator. Llama: GQA shrinks
    k/v projections by n_kv/n_heads, SwiGLU is 3 matmuls of width
    ``intermediate_size``, RMSNorm has no bias, no position table, and
    an UNTIED lm head doubles the embedding bytes."""
    if hasattr(cfg, "n_layers"):  # LlamaConfig
        d, L, V = cfg.dim, cfg.n_layers, cfg.table_vocab_size
        r = cfg.n_kv_heads / cfg.n_heads
        block = int(d * d * (2 + 2 * r)) + 3 * d * cfg.intermediate_size             + 2 * d
        embed = V * d * (1 if cfg.tie_embeddings else 2)
        return d, L, V, block, embed, 0, cfg.n_heads
    d, L, V = cfg.n_embd, cfg.n_layer, cfg.table_vocab_size
    return (d, L, V, 12 * d * d + 13 * d, V * d,
            cfg.n_positions * d, cfg.n_head)

# v5e per-chip figures; overridable on the CLI. ICI bandwidth only sets
# the relative weight of comm vs memory in ranking, so precision is not
# critical.
DEFAULT_HBM_GB = 16.0


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


@dataclass(frozen=True)
class Plan:
    mesh: Dict[str, int]              # {'dp':..,'tp':..,'pp':..,'sp':..}
    bytes_per_chip: int               # peak-ish resident bytes
    comm_bytes_per_step: int          # ICI traffic heuristic
    breakdown: Dict[str, int]         # component -> bytes

    def describe(self, hbm_bytes: float) -> str:
        m = self.mesh
        parts = ", ".join(f"{k}{v}" for k, v in m.items() if v > 1) or "1chip"
        pct = 100.0 * self.bytes_per_chip / hbm_bytes
        bd = " + ".join(f"{k} {v / GB:.2f}" for k, v in
                        sorted(self.breakdown.items(),
                               key=lambda kv: -kv[1]))
        return (f"[{parts:>16}] mem {self.bytes_per_chip / GB:6.2f} GiB "
                f"({pct:5.1f}% HBM) = {bd}; "
                f"comm ~{self.comm_bytes_per_step / GB:.2f} GiB/step")


def estimate(cfg: GPT2Config, mesh: Dict[str, int], *, batch: int,
             seq: int, zero1: bool = False, zero_stage: int = 1,
             remat: bool = True, fsdp: bool = False) -> Plan:
    """Per-chip memory + per-step ICI-traffic estimate for one mesh.

    Mirrors the real sharding rules: blocks are [tp column/row] x
    [pp stacked-depth] sharded; embeddings/head replicate over tp
    unless ``cfg.vocab_parallel`` (then wte and the CE shard over tp);
    optimizer m/v shard over dp when ``zero1``; activations shard batch
    over dp and sequence over sp. f32 master params + bf16 compute
    (the shipped default), Adam m+v f32.
    """
    zero1 = zero1 or zero_stage >= 2   # zero2 implies the stage-1 shard
    dp, tp, pp, sp = (mesh.get(a, 1) for a in ("dp", "tp", "pp", "sp"))
    d, L, V, blk, emb, pos, H = _geometry(cfg)

    block_params = L * blk // (tp * pp)
    embed_params = emb // (tp if cfg.vocab_parallel else 1) + pos
    local_params = block_params + embed_params + 2 * d

    b_loc = max(batch // dp, 1)
    s_loc = max(seq // sp, 1)

    if fsdp:
        # ZeRO-3 (training.fsdp): BLOCK params/grads/opt stored over dp;
        # embeddings/head replicate (vp is their knob). Transient
        # full-layer gathers live in the activation working set.
        resident = block_params // dp + embed_params + 2 * d
        master = 4 * resident
        compute = 2 * resident + 2 * (block_params * pp // max(L, 1))
        opt = 8 * resident
        grads = 4 * resident
    else:
        master = 4 * local_params                  # f32 master copy
        compute = 2 * local_params                 # bf16 cast-at-use copy
        opt = 8 * (local_params // dp if zero1 else local_params)  # m+v
        # ZeRO-2 (zero_stage=2): gradients reduce-scatter into the
        # rank's chunk and the grad-accumulation buffer is chunk-sized
        # too (parallel/zero.py accumulate_grads_zero2)
        grads = 4 * (local_params // dp if (zero1 and zero_stage == 2)
                     else local_params)
    # activations: the scan stores one residual-stream tensor per layer
    # (bf16) even under full remat (carry boundaries), plus the block
    # working set; dense CE materialises f32 logits unless vp/sp/chunked
    acts = (L // pp) * b_loc * s_loc * d * 2
    if remat:
        work = 4 * b_loc * s_loc * d * 2          # one block's live set
    else:
        work = (L // pp) * b_loc * s_loc * (13 * d) * 2  # qkv+mlp saved
    logits = (0 if (cfg.vocab_parallel or getattr(cfg, "loss_chunk", 0)
                    or sp > 1)
              else 4 * b_loc * s_loc * V)
    breakdown = {"master": master, "opt": opt, "grads": grads,
                 "compute": compute, "acts": acts + work, "logits": logits}
    total = sum(breakdown.values())

    # ICI bytes/step (order of magnitude): tp does 4 allreduces of the
    # [b, s, d] residual per layer (2 fwd + 2 bwd); dp one grad
    # allreduce (reduce-scatter+gather when zero1 — same volume); sp
    # rotates K/V per layer (ring) or two all-to-alls (ulysses ~ same);
    # pp passes boundaries per microbatch (small) — counted once.
    act_bytes = b_loc * s_loc * d * 2
    comm = 0
    if tp > 1:
        comm += 4 * (L // pp) * act_bytes * 2 * (tp - 1) // tp
    if dp > 1:
        # fsdp: per-layer all-gather fwd + (remat) bwd re-gather +
        # reduce-scatter grads ~ 3x the one grad allreduce's volume
        comm += (3 if fsdp else 2) * 4 * local_params * (dp - 1) // dp
    if sp > 1:
        comm += (L // pp) * 2 * act_bytes * 2 * (sp - 1) // sp
    if pp > 1:
        comm += 2 * act_bytes * pp
    return Plan(mesh=dict(mesh), bytes_per_chip=total,
                comm_bytes_per_step=comm, breakdown=breakdown)


def plan(cfg: GPT2Config, *, n_devices: int, batch: int, seq: int,
         hbm_gb: float = DEFAULT_HBM_GB, zero1: bool = False,
         zero_stage: int = 1, fsdp: bool = False,
         remat: bool = True, max_pp: Optional[int] = None,
         use_sp: bool = True) -> List[Plan]:
    """All legal meshes over ``n_devices``, fitting ones first, each
    group sorted by the comm heuristic (less ICI traffic first)."""
    hbm = hbm_gb * GB
    n_head = getattr(cfg, "n_head", None) or cfg.n_heads
    n_kv = getattr(cfg, "n_kv_heads", n_head)
    n_layer = getattr(cfg, "n_layer", None) or cfg.n_layers
    out = []
    for tp in _divisors(n_devices):
        if n_head % tp or n_kv % tp:
            continue
        if cfg.vocab_parallel and cfg.table_vocab_size % tp:
            continue
        for pp in _divisors(n_devices // tp):
            if n_layer % pp or (max_pp and pp > max_pp):
                continue
            for sp in _divisors(n_devices // (tp * pp)):
                if not use_sp and sp > 1:
                    continue
                if seq % sp or (sp > 1 and (seq // sp) % 2):
                    continue  # zigzag needs even local chunks
                dp = n_devices // (tp * pp * sp)
                if batch % (dp * max(1, pp)):  # pp needs microbatches
                    continue
                out.append(estimate(cfg, {"dp": dp, "tp": tp,
                                          "pp": pp, "sp": sp},
                                    batch=batch, seq=seq, zero1=zero1,
                                    zero_stage=zero_stage, remat=remat,
                                    fsdp=fsdp))
    out.sort(key=lambda p: (p.bytes_per_chip > hbm,
                            p.comm_bytes_per_step, p.bytes_per_chip))
    return out


_PRESETS = {"gpt2": GPT2Config.base, "gpt2-base": GPT2Config.base,
            "gpt2-medium": GPT2Config.medium, "gpt2-large": GPT2Config.large,
            "gpt2-xl": GPT2Config.xl}


def _llama_presets():
    from quintnet_tpu.models.llama import LlamaConfig

    return {"llama-160m": LlamaConfig.llama_160m,
            "llama32-1b": LlamaConfig.llama32_1b,
            "llama3-8b": LlamaConfig.llama3_8b}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    presets = {**_PRESETS, **_llama_presets()}
    ap.add_argument("--model", default="gpt2",
                    choices=sorted(presets))
    ap.add_argument("--devices", type=int, required=True)
    ap.add_argument("--batch", type=int, required=True,
                    help="GLOBAL batch size")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--hbm-gb", type=float, default=DEFAULT_HBM_GB)
    ap.add_argument("--zero1", action="store_true",
                    help="shard adam m/v over dp (parallel/zero.py)")
    ap.add_argument("--zero2", action="store_true",
                    help="additionally shard gradients/accumulators "
                         "over dp (implies --zero1)")
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--fsdp", action="store_true",
                    help="ZeRO-3 (training.fsdp): block params stored "
                         "dp-sharded, per-layer gather in the scan")
    ap.add_argument("--vocab-parallel", action="store_true")
    ap.add_argument("--top", type=int, default=5)
    args = ap.parse_args(argv)

    cfg = presets[args.model]()
    if args.seq > cfg.n_positions:
        cfg = dataclasses.replace(cfg, n_positions=args.seq)
    if args.vocab_parallel:
        # gpt2's 50257 needs Megatron-style padding to divide tp;
        # llama's 128256 (and the 160m geometry's 32000) already do
        pad = 50304 if cfg.vocab_size == 50257 else None
        cfg = dataclasses.replace(cfg, vocab_parallel=True,
                                  padded_vocab_size=pad)
    plans = plan(cfg, n_devices=args.devices, batch=args.batch,
                 seq=args.seq, hbm_gb=args.hbm_gb,
                 zero1=args.zero1 or args.zero2,
                 zero_stage=2 if args.zero2 else 1, fsdp=args.fsdp,
                 remat=not args.no_remat)
    hbm = args.hbm_gb * GB
    fitting = [p for p in plans if p.bytes_per_chip <= hbm]
    print(f"{args.model} | {args.devices} chips x {args.hbm_gb} GiB | "
          f"global batch {args.batch} seq {args.seq} | "
          f"{len(fitting)}/{len(plans)} legal meshes fit")
    for p in plans[: args.top]:
        tag = "  " if p.bytes_per_chip <= hbm else "✗ "
        print(tag + p.describe(hbm))
    if not fitting:
        print("nothing fits — add chips, enable --zero1 / "
              "--vocab-parallel, or shrink the batch")
    return plans


if __name__ == "__main__":
    main()

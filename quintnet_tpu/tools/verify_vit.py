"""Single-device reload verifier for distributed ViT training.

Reference: examples/verify_model.py:23-60 — reload the trained
checkpoint with NO distributed code, strip the wrapper prefixes, and
re-compute accuracy on one device as ground truth; parity with the
distributed run's reported val accuracy is the acceptance criterion.
(GPT-2 has tools/verify_gpt2.py; this is the classification analogue.)

  python -m quintnet_tpu.tools.verify_vit --checkpoint-dir ckpt \
      [--tp 2] [--expected-accuracy 0.93] [--data-dir data]

Restores the latest orbax step as plain host arrays (no Strategy, no
mesh, no shard_map anywhere in this module), un-permutes the tp-blocked
fused-QKV layout when the checkpoint came from a tp>1 run (--tp; see
parallel/tp.py layout convention), and evaluates accuracy over the test
split with a plain ``vit_apply``.
"""

from __future__ import annotations

import argparse
from typing import Optional, Tuple

import numpy as np


def verify_vit(checkpoint_dir: str, cfg, *, tp: int = 1,
               data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
               data_dir: Optional[str] = None,
               batch_size: int = 256) -> dict:
    """Reload latest checkpoint -> single-device accuracy/loss dict."""
    import jax
    import jax.numpy as jnp

    from quintnet_tpu.models.vit import (accuracy, cross_entropy_loss,
                                         vit_apply)
    from quintnet_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(checkpoint_dir)
    state = mgr.restore()  # host numpy, no mesh involved
    params = state["params"]

    if tp > 1:
        # invert the tp-blocked fused-QKV column permutation the sharded
        # run trains in (parallel/tp.py:111-137) back to standard [q|k|v]
        from quintnet_tpu.parallel.tp import qkv_standard_from_blocked

        qkv = params["blocks"]["attn"]["qkv"]
        qkv["w"] = qkv_standard_from_blocked(qkv["w"], cfg.num_heads, tp)
        if "b" in qkv:
            qkv["b"] = qkv_standard_from_blocked(qkv["b"], cfg.num_heads, tp)

    if data is None:
        from quintnet_tpu.data.datasets import load_mnist

        data = load_mnist(data_dir, split="test")
    x, y = data

    # donate the image batch: fresh per iteration, dead after the
    # forward
    import warnings

    apply_fn = jax.jit(lambda p, xb: vit_apply(p, xb, cfg),
                       donate_argnums=(1,))
    losses, accs, n = [], [], 0
    with warnings.catch_warnings():
        # logits can't alias the image batch -> expected "not usable"
        # warning, scoped to this loop
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        for i in range(0, len(x) - (len(x) % batch_size) or len(x),
                       batch_size):
            xb = jnp.asarray(x[i:i + batch_size])
            yb = jnp.asarray(y[i:i + batch_size])
            logits = apply_fn(params, xb)
            losses.append(float(cross_entropy_loss(logits, yb)) * len(xb))
            accs.append(float(accuracy(logits, yb)) * len(xb))
            n += len(xb)
    return {
        "epoch": int(state.get("epoch", -1)),
        "loss": sum(losses) / max(n, 1),
        "accuracy": sum(accs) / max(n, 1),
        "n_examples": n,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--tp", type=int, default=1,
                    help="tp size of the run that wrote the checkpoint "
                         "(un-permutes the blocked QKV layout)")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--hidden-dim", type=int, default=64)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--num-heads", type=int, default=4)
    ap.add_argument("--patch-size", type=int, default=7)
    ap.add_argument("--expected-accuracy", type=float, default=None,
                    help="val accuracy the distributed trainer reported; "
                         "exit 1 if the reloaded model misses it by >1%")
    args = ap.parse_args()

    from quintnet_tpu.models.vit import ViTConfig

    cfg = ViTConfig(hidden_dim=args.hidden_dim, depth=args.depth,
                    num_heads=args.num_heads, patch_size=args.patch_size)
    res = verify_vit(args.checkpoint_dir, cfg, tp=args.tp,
                     data_dir=args.data_dir)
    print(f"reloaded epoch {res['epoch']}: "
          f"loss {res['loss']:.4f} accuracy {res['accuracy']:.4f} "
          f"({res['n_examples']} examples)")
    if args.expected_accuracy is not None:
        diff = abs(res["accuracy"] - args.expected_accuracy)
        ok = diff <= 0.01
        print(f"distributed-run accuracy {args.expected_accuracy:.4f} "
              f"-> |diff| {diff:.4f} {'PASS' if ok else 'FAIL'} (bar 1%)")
        raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()

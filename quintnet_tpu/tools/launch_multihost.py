"""Multi-process launcher — the torchrun role for this framework.

The reference is launched with ``torchrun --nproc_per_node=8 -m
QuintNet.examples.full_3d`` (reference README.md:93-97): torchrun spawns
one process per rank and injects the rendezvous env. Here the analogue
spawns N copies of any entry command and appends the flags every example
already accepts (examples/common.py add_multihost_args):

    --coordinator localhost:<port> --num-processes N --process-id i

Usage (2-process CPU demo, 4 virtual devices each -> one 8-device mesh):

    python -m quintnet_tpu.tools.launch_multihost --nproc 2 -- \\
        python -m quintnet_tpu.examples.full_3d --simulate 4 --epochs 1

On a real TPU pod this tool is NOT needed per-host process spawning —
run the SAME command on every host with ``--multihost`` and
jax.distributed discovers the slice topology from TPU metadata
(core/runtime.py:initialize); your pod process manager (GKE, xmanager,
gcloud compute ssh loop) plays the role this script plays locally. This
launcher covers single-host multi-process dev/CI runs and is the
documented template for what each pod host must execute.

Output of every rank is streamed line-by-line with a ``[rank i]``
prefix (torchrun behavior); first nonzero exit kills the others and
becomes this process's exit code.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _stream(proc: subprocess.Popen, rank: int, out) -> None:
    for line in proc.stdout:
        out.write(f"[rank {rank}] {line.decode(errors='replace')}")
        out.flush()


def launch(cmd, nproc: int, *, port: int | None = None,
           out=sys.stdout) -> int:
    """Spawn ``cmd`` nproc times with coordinator flags appended; return
    the first nonzero exit code (0 if all succeed)."""
    port = port or free_port()
    procs = []
    threads = []
    env = dict(os.environ)
    for i in range(nproc):
        full = list(cmd) + ["--coordinator", f"localhost:{port}",
                            "--num-processes", str(nproc),
                            "--process-id", str(i)]
        p = subprocess.Popen(full, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, env=env)
        t = threading.Thread(target=_stream, args=(p, i, out), daemon=True)
        t.start()
        procs.append(p)
        threads.append(t)

    rc = 0
    try:
        for p in procs:
            code = p.wait()
            if code != 0 and rc == 0:
                rc = code
                for q in procs:  # fail fast: no point waiting on a
                    if q.poll() is None:  # half-dead rendezvous
                        q.send_signal(signal.SIGTERM)
    except KeyboardInterrupt:
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)
        rc = 130
    for t in threads:
        t.join(timeout=5)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Spawn N local processes of an example with "
                    "coordinator flags appended (the torchrun role).",
        usage="%(prog)s --nproc N [--port P] -- <command> [args...]")
    ap.add_argument("--nproc", type=int, required=True,
                    help="number of processes (one per would-be host)")
    ap.add_argument("--port", type=int, default=None,
                    help="coordinator port (default: a free one)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to spawn; separate with --")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (put it after --)")
    return launch(cmd, args.nproc, port=args.port)


if __name__ == "__main__":
    sys.exit(main())

"""qtcheck CLI: lint the tree for JAX footguns and concurrency-
discipline violations, gated by committed baselines.

  python -m quintnet_tpu.tools.qtcheck                        # lint all
  python -m quintnet_tpu.tools.qtcheck quintnet_tpu/serve     # subset
  python -m quintnet_tpu.tools.qtcheck \
      --baseline tools/qtcheck_baseline.json                  # CI gate
  python -m quintnet_tpu.tools.qtcheck \
      --baseline tools/qtcheck_baseline.json --write-baseline # refresh
  python -m quintnet_tpu.tools.qtcheck --select QT2 \
      --threads-baseline tools/qtcheck_threads_baseline.json  # threads

Exit codes: 0 = clean or exactly baseline-matched; 1 = NEW violations
(fix them or, for a deliberate pattern, add a ``# qtcheck: ok[RULE]``
pragma with a justifying comment) or STALE baseline entries (you fixed
legacy violations — rerun with ``--write-baseline`` and commit the
shrunken file; notes on surviving entries are preserved).

Two source-level passes share ONE parse of the tree
(analysis/lint.collect_sources):

- the **lint pass** (QT1xx, analysis/lint.py) runs by default over the
  whole tree and gates on ``--baseline``;
- the **concurrency pass** (QT2xx, analysis/threads.py — lock-order
  graph, guarded-by inference, thread-spawn census) is opt-in: it runs
  when ``--threads-baseline`` is given or when ``--select``/``--rules``
  names a QT2xx rule, and audits ``fleet/``+``serve/``+``obs/`` unless
  explicit paths are given. It gates on ``--threads-baseline`` with the
  identical both-directions contract.

``--select`` filters by rule-ID prefix (``--select QT2`` = the whole
concurrency family, ``--select QT104,QT2`` mixes passes), so CI gates
can target one family without string-grepping stdout.

The baseline keys violations by (rule, file, enclosing function) with a
count, so line drift never churns it, and CI
(tests/test_qtcheck.py::test_lint_baseline_gate,
tests/test_qtcheck_threads.py) fails whenever a committed file and the
tree disagree in EITHER direction — the same no-drift discipline
tests/test_bench_stale.py applies to benchmark artifacts.

The jaxpr-level passes (collective census, recompile sentinel,
donation/dtype reports) are not CLI passes — they need lowered
programs, so they live in tests/test_qtcheck.py against the real
train/serve builders. This CLI is the pure-source half of qtcheck:
run as a FILE (``python quintnet_tpu/tools/qtcheck.py``) it imports no
jax at all (analysis/lint.py and analysis/threads.py are loaded by
path, bypassing the package __init__), so it works in a lint-only
environment; ``python -m quintnet_tpu.tools.qtcheck`` behaves
identically but initialises the package (and therefore jax) as any
``-m`` run must.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

# Load analysis/lint.py and analysis/threads.py by FILE PATH, not
# through the package: `import quintnet_tpu` pulls in jax (core/compat
# installs shims at import), and this CLI's contract is to lint source
# with zero jax — it must work (and stay instant) in a lint-only
# environment. Order matters: threads.py reuses whichever lint module
# is already in sys.modules, so registering "_qtcheck_lint" first
# guarantees both passes share ONE Violation class (baseline dicts and
# isinstance checks stay coherent).
_ANALYSIS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "analysis")


def _load_by_path(name: str, filename: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ANALYSIS_DIR, filename))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod   # dataclasses needs it registered
    spec.loader.exec_module(mod)
    return mod


_lint = _load_by_path("_qtcheck_lint", "lint.py")
_threads = _load_by_path("_qtcheck_threads", "threads.py")

RULES = _lint.RULES
THREAD_RULES = _threads.RULES
ALL_RULES = {**RULES, **THREAD_RULES}
compare_baseline = _lint.compare_baseline
collect_sources = _lint.collect_sources
lint_parsed = _lint.lint_parsed
lint_paths = _lint.lint_paths
load_baseline = _lint.load_baseline
violations_to_baseline = _lint.violations_to_baseline

DEFAULT_PATHS = ("quintnet_tpu", "tools", "bench.py")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _select_rules(available, rules, select):
    """The subset of ``available`` rule IDs matching --rules (exact,
    comma-separated) and --select (prefix, comma-separated)."""
    ids = set(available)
    if rules:
        ids &= {r.strip() for r in rules}
    if select:
        prefixes = tuple(p.strip() for p in select if p.strip())
        ids = {r for r in ids if r.startswith(prefixes)}
    return ids


def _under(rel: str, roots) -> bool:
    return any(rel == r or rel.startswith(r + "/") for r in roots)


def _write_baseline_file(path: str, violations) -> None:
    notes = {}
    if os.path.exists(path):
        for e in load_baseline(path).get("violations", []):
            if "note" in e:
                notes[(e["rule"], e["path"], e["symbol"])] = e["note"]
    data = violations_to_baseline(violations, notes)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {path}: {len(data['violations'])} entries "
          f"({len(violations)} violations)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="qtcheck", description="JAX-footgun + concurrency linter "
        "(see docs/static_analysis.md for the rules and the baseline "
        "workflow)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS}; "
                         f"the concurrency pass defaults to "
                         f"{_threads.THREAD_PATHS})")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths (default: "
                         "autodetected from this file)")
    ap.add_argument("--baseline", default=None,
                    help="committed lint baseline JSON; new violations "
                         "and stale entries both fail")
    ap.add_argument("--threads-baseline", default=None,
                    help="committed concurrency baseline JSON (same "
                         "both-directions contract); also turns the "
                         "concurrency pass on")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the given baseline file(s) from "
                         "the current tree (preserving notes) instead "
                         "of checking")
    ap.add_argument("--rules", default=None,
                    help="comma-separated exact subset, e.g. QT104,QT202")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule-ID prefixes, e.g. QT2 "
                         "(concurrency family) or QT104,QT2")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(ALL_RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    root = args.root or repo_root()
    rules = args.rules.split(",") if args.rules else None
    select = args.select.split(",") if args.select else None

    lint_rules = _select_rules(RULES, rules, select)
    thread_rules = _select_rules(THREAD_RULES, rules, select)
    # The concurrency pass is opt-in: a filter naming a QT2xx rule, or
    # a threads baseline, arms it. A bare `qtcheck` run stays the lint
    # pass alone (its baseline is the committed contract CI pins). A
    # filter that excludes a pass's every rule disarms that pass — and
    # its baseline comparison with it.
    run_lint = bool(lint_rules)
    run_threads = bool(thread_rules) and (
        bool(args.threads_baseline) or bool(rules or select))

    # ONE parse shared by both passes: each file is read and parsed
    # exactly once however many passes (or rules) consume it.
    if args.paths:
        sources = collect_sources(args.paths, root=root)
        thread_sources = sources
    elif run_lint:
        sources = collect_sources(list(DEFAULT_PATHS), root=root)
        thread_sources = [s for s in sources
                          if _under(s.rel, _threads.THREAD_PATHS)]
    else:
        sources = collect_sources(list(_threads.THREAD_PATHS), root=root)
        thread_sources = sources

    lint_violations = (lint_parsed(sources, rules=sorted(lint_rules))
                       if run_lint else [])
    thread_violations = (
        _threads.audit_parsed(thread_sources,
                              rules=sorted(thread_rules))
        if run_threads else [])

    if args.write_baseline:
        if not (args.baseline or args.threads_baseline):
            print("--write-baseline needs --baseline and/or "
                  "--threads-baseline", file=sys.stderr)
            return 2
        if args.baseline and run_lint:
            _write_baseline_file(args.baseline, lint_violations)
        if args.threads_baseline and run_threads:
            _write_baseline_file(args.threads_baseline,
                                 thread_violations)
        return 0

    if args.baseline or args.threads_baseline:
        new, stale = [], []
        if args.baseline and run_lint:
            n, s = compare_baseline(lint_violations,
                                    load_baseline(args.baseline))
            new += n
            stale += s
        if args.threads_baseline and run_threads:
            n, s = compare_baseline(thread_violations,
                                    load_baseline(args.threads_baseline))
            new += n
            stale += s
        total = len(lint_violations) + len(thread_violations)
        if args.as_json:
            print(json.dumps({"new": new, "stale": stale,
                              "total": total}))
        else:
            for line in new:
                print(f"NEW   {line}")
            for line in stale:
                print(f"STALE {line}")
            status = "clean" if not (new or stale) else "FAIL"
            print(f"qtcheck: {total} violation(s), "
                  f"{len(new)} new, {len(stale)} stale vs baseline "
                  f"— {status}")
        return 1 if (new or stale) else 0

    violations = sorted(lint_violations + thread_violations,
                        key=lambda v: (v.path, v.line, v.rule))
    if args.as_json:
        print(json.dumps([v.__dict__ for v in violations]))
    else:
        for v in violations:
            print(v.render())
        print(f"qtcheck: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

"""qtcheck CLI: lint the tree for JAX footguns, gated by a baseline.

  python -m quintnet_tpu.tools.qtcheck                        # lint all
  python -m quintnet_tpu.tools.qtcheck quintnet_tpu/serve     # subset
  python -m quintnet_tpu.tools.qtcheck \
      --baseline tools/qtcheck_baseline.json                  # CI gate
  python -m quintnet_tpu.tools.qtcheck \
      --baseline tools/qtcheck_baseline.json --write-baseline # refresh

Exit codes: 0 = clean or exactly baseline-matched; 1 = NEW violations
(fix them or, for a deliberate pattern, add a ``# qtcheck: ok[RULE]``
pragma with a justifying comment) or STALE baseline entries (you fixed
legacy violations — rerun with ``--write-baseline`` and commit the
shrunken file; notes on surviving entries are preserved).

The baseline keys violations by (rule, file, enclosing function) with a
count, so line drift never churns it, and CI
(tests/test_qtcheck.py::test_lint_baseline_gate) fails whenever the
committed file and the tree disagree in EITHER direction — the same
no-drift discipline tests/test_bench_stale.py applies to benchmark
artifacts.

The jaxpr-level passes (collective census, recompile sentinel,
donation/dtype reports) are not CLI passes — they need lowered
programs, so they live in tests/test_qtcheck.py against the real
train/serve builders. This CLI is the pure-source half of qtcheck:
run as a FILE (``python quintnet_tpu/tools/qtcheck.py``) it imports no
jax at all (analysis/lint.py is loaded by path, bypassing the package
__init__), so it works in a lint-only environment; ``python -m
quintnet_tpu.tools.qtcheck`` behaves identically but initialises the
package (and therefore jax) as any ``-m`` run must.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

# Load analysis/lint.py by FILE PATH, not through the package:
# `import quintnet_tpu` pulls in jax (core/compat installs shims at
# import), and this CLI's contract is to lint source with zero jax —
# it must work (and stay instant) in a lint-only environment.
_LINT_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "analysis", "lint.py")
_spec = importlib.util.spec_from_file_location("_qtcheck_lint", _LINT_PATH)
_lint = importlib.util.module_from_spec(_spec)
sys.modules["_qtcheck_lint"] = _lint   # dataclasses needs it registered
_spec.loader.exec_module(_lint)

RULES = _lint.RULES
compare_baseline = _lint.compare_baseline
lint_paths = _lint.lint_paths
load_baseline = _lint.load_baseline
violations_to_baseline = _lint.violations_to_baseline

DEFAULT_PATHS = ("quintnet_tpu", "tools", "bench.py")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="qtcheck", description="JAX-footgun linter (see docs/"
        "static_analysis.md for the rules and the baseline workflow)")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths (default: "
                         "autodetected from this file)")
    ap.add_argument("--baseline", default=None,
                    help="committed baseline JSON; new violations and "
                         "stale entries both fail")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate --baseline from the current tree "
                         "(preserving notes) instead of checking")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. QT104,QT106")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    root = args.root or repo_root()
    rules = args.rules.split(",") if args.rules else None
    violations = lint_paths(args.paths or list(DEFAULT_PATHS),
                            root=root, rules=rules)

    if args.baseline and args.write_baseline:
        notes = {}
        if os.path.exists(args.baseline):
            for e in load_baseline(args.baseline).get("violations", []):
                if "note" in e:
                    notes[(e["rule"], e["path"], e["symbol"])] = e["note"]
        data = violations_to_baseline(violations, notes)
        with open(args.baseline, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.baseline}: "
              f"{len(data['violations'])} entries "
              f"({len(violations)} violations)")
        return 0

    if args.baseline:
        baseline = load_baseline(args.baseline)
        new, stale = compare_baseline(violations, baseline)
        if args.as_json:
            print(json.dumps({"new": new, "stale": stale,
                              "total": len(violations)}))
        else:
            for line in new:
                print(f"NEW   {line}")
            for line in stale:
                print(f"STALE {line}")
            status = "clean" if not (new or stale) else "FAIL"
            print(f"qtcheck: {len(violations)} violation(s), "
                  f"{len(new)} new, {len(stale)} stale vs baseline "
                  f"— {status}")
        return 1 if (new or stale) else 0

    if args.as_json:
        print(json.dumps([v.__dict__ for v in violations]))
    else:
        for v in violations:
            print(v.render())
        print(f"qtcheck: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

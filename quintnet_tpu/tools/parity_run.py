"""Convergence-parity runs: sharded == unsharded over FULL trainer runs.

BASELINE.md's acceptance criterion is matching the reference's published
curves within 1% (ViT-MNIST 93.24% val acc, GPT-2 val PPL 27.21 —
/root/reference/README.md:199-238). Those numbers need the real MNIST /
CNN-DailyMail files, which this zero-egress environment does not have;
what CAN be demonstrated end-to-end here — and is the part no
single-step golden test covers — is that the full trainer+data+schedule
loop converges IDENTICALLY sharded and unsharded over many epochs:

  python -m quintnet_tpu.tools.parity_run --task vit  --mode single
  python -m quintnet_tpu.tools.parity_run --task vit  --mode 3d
  python -m quintnet_tpu.tools.parity_run --task gpt2 --mode single
  python -m quintnet_tpu.tools.parity_run --task gpt2 --mode 3d
  python -m quintnet_tpu.tools.parity_run --report   # -> PARITY.md

Each run writes artifacts/parity/{task}_{mode}.json (per-epoch losses +
metrics). --report merges them into PARITY.md with the per-epoch deltas.
Runs use the same init seed, the same global batch order, and a 2x2x2
dp x tp x pp mesh (1F1B) for '3d' — the reference's headline topology.
With real data dropped in (data/ mnist.npz, --csv for gpt2), the same
commands reproduce the reference's task for direct curve comparison.
"""

from __future__ import annotations

import argparse
import json
import os

ART_DIR = "artifacts/parity"

VIT_EPOCHS = 10
GPT2_EPOCHS = 3


def _fingerprint(*arrays) -> str:
    """Stable hash of the dataset tensors feeding a leg. The report
    refuses to compare legs with different fingerprints — a stale
    artifact from an older synthetic-data generation otherwise produces
    a bogus parity verdict (this bit round 4: a timed-out 3d leg left a
    round-2 file behind and the report happily diffed across dataset
    versions)."""
    import hashlib

    h = hashlib.sha1()
    for a in arrays:
        import numpy as np

        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:12]


def _setup(mode: str):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


def run_vit(mode: str) -> dict:
    """mode: 'single' | '3d' | 'control'.

    'control' is the chaos-sensitivity control for the late-epoch drift
    seen between single and 3d (round-4 verdict: 10.1% at epoch 8,
    "asserted, not demonstrated"): the SAME single-device program with a
    one-off 1e-7 relative perturbation of the initial params — the
    magnitude of a single step's float-reassociation noise between two
    XLA programs. If single-vs-control drifts as much as single-vs-3d by
    epochs 8-9, the 3d drift is demonstrated to be chaotic float
    divergence, not a sharding bug; the report computes this band.
    """
    _setup(mode)
    from quintnet_tpu.core.config import Config
    from quintnet_tpu.data import ArrayDataset, make_batches
    from quintnet_tpu.data.datasets import synthetic_mnist
    from quintnet_tpu.models.vit import ViTConfig, vit_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy
    from quintnet_tpu.train.trainer import Trainer

    mesh = ([2, 2, 2], ["dp", "tp", "pp"]) if mode == "3d" else ([1], ["dp"])
    cfg = Config.from_dict({
        "mesh_dim": mesh[0], "mesh_name": mesh[1],
        "training": {
            "batch_size": 64,  # reference effective batch (README:218-222)
            "gradient_accumulation_steps": 2,
            "schedule": "1f1b",
            "optimizer": "adam",
            "learning_rate": 1e-3,
            "grad_clip_norm": None,
            "epochs": VIT_EPOCHS,
            "log_every": 0,
        },
    })
    # reference ViT widths (hidden 64, depth 8, heads 4)
    vcfg = ViTConfig(hidden_dim=64, depth=8, num_heads=4)
    model = vit_model_spec(vcfg)
    strategy = get_strategy("3d" if mode == "3d" else "single", cfg)

    xtr, ytr = synthetic_mnist(8192, seed=0)
    xte, yte = synthetic_mnist(1024, seed=1)
    train, test = ArrayDataset(xtr, ytr), ArrayDataset(xte, yte)

    trainer = Trainer(cfg, model, strategy=strategy,
                      task_type="classification")
    params = opt_state = None
    if mode == "control":
        import jax
        import jax.numpy as jnp

        params, opt_state = trainer.init_state()
        ks = iter(jax.random.split(jax.random.key(1234),
                                   len(jax.tree.leaves(params))))
        params = jax.tree.map(
            lambda x: x * (1.0 + 1e-7 * jax.random.rademacher(
                next(ks), x.shape).astype(x.dtype))
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params)
    hist = trainer.fit(
        lambda ep: make_batches(train, 64, seed=ep),
        val_batches_fn=lambda ep: make_batches(test, 64, shuffle=False),
        params=params, opt_state=opt_state,
    )
    return {
        "task": "vit", "mode": mode, "mesh": dict(strategy.mesh.shape),
        "data_fp": _fingerprint(xtr, ytr, xte, yte),
        "epochs": VIT_EPOCHS,
        "train_loss": hist.train_loss,
        "val_loss": hist.val_loss,
        "val_accuracy": hist.val_metric,
        "wall_time_s": round(hist.wall_time_s, 1),
    }


def run_gpt2(mode: str) -> dict:
    _setup(mode)
    from quintnet_tpu.core.config import Config
    from quintnet_tpu.data import ByteTokenizer, SummarizationDataset
    from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_model_spec
    from quintnet_tpu.parallel.strategy import get_strategy
    from quintnet_tpu.train.trainer import Trainer

    mesh = ([2, 2, 2], ["dp", "tp", "pp"]) if mode == "3d" else ([1], ["dp"])
    cfg = Config.from_dict({
        "mesh_dim": mesh[0], "mesh_name": mesh[1],
        "training": {
            "batch_size": 16,
            "gradient_accumulation_steps": 4,  # reference grad_acc shape
            "schedule": "1f1b",
            "optimizer": "adamw",
            "learning_rate": 5e-4,
            "weight_decay": 0.01,
            "grad_clip_norm": 1.0,
            "epochs": GPT2_EPOCHS,
            "log_every": 0,
        },
    })
    tok = ByteTokenizer()
    v = -(-max(tok.vocab_size, 128) // 8) * 8
    gcfg = GPT2Config.tiny(vocab_size=v, n_positions=128, n_embd=64,
                           n_layer=4, n_head=4)
    model = gpt2_model_spec(gcfg)
    strategy = get_strategy("3d" if mode == "3d" else "single", cfg)

    train = SummarizationDataset.synthetic(1024, tok, max_length=128)
    val = SummarizationDataset.synthetic(256, tok, max_length=128, seed=1)

    trainer = Trainer(cfg, model, strategy=strategy, task_type="clm")
    hist = trainer.fit(
        lambda ep: train.batches(16, seed=ep),
        val_batches_fn=lambda ep: val.batches(16, shuffle=False),
    )
    xb0, yb0 = next(iter(train.batches(16, seed=0)))
    return {
        "task": "gpt2", "mode": mode, "mesh": dict(strategy.mesh.shape),
        "data_fp": _fingerprint(xb0, yb0),
        "epochs": GPT2_EPOCHS,
        "train_loss": hist.train_loss,
        "val_loss": hist.val_loss,
        "val_perplexity": hist.val_metric,
        "wall_time_s": round(hist.wall_time_s, 1),
    }


def report() -> str:
    def load(task, mode):
        path = os.path.join(ART_DIR, f"{task}_{mode}.json")
        with open(path) as f:
            return json.load(f)

    lines = [
        "# PARITY — sharded vs single-device convergence",
        "",
        "Full multi-epoch Trainer runs (same seed, same batch order) on a",
        "2x2x2 dp x tp x pp mesh (1F1B — the reference's headline",
        "topology, README.md:199-238) vs single device. Bar: exact",
        "trajectory identity within 1% when it holds; otherwise curves",
        "must track >= half the run within 1% and the final quality",
        "metric agree within 2% (the sharded step is a different XLA",
        "float program, so per-step ~1e-7 reassociation noise amplifies",
        "chaotically once the loss is small — single-STEP parity is",
        "bit-level, see tests/). The runs",
        "below use the synthetic datasets (this environment has no",
        "network egress and no MNIST/CNN-DailyMail files — drop",
        "`data/mnist.npz` / `--csv` in and the same commands reproduce",
        "the reference's real-data task). Produced by",
        "`python -m quintnet_tpu.tools.parity_run`; raw JSON under",
        "`artifacts/parity/`.",
        "",
    ]
    for task, metric_key, metric_name in (
            ("vit", "val_accuracy", "val acc"),
            ("gpt2", "val_perplexity", "val ppl")):
        s = load(task, "single")
        d = load(task, "3d")
        if s.get("data_fp") != d.get("data_fp"):
            lines += [f"## {task.upper()}", "",
                      f"**INCOMPARABLE** — dataset fingerprints differ "
                      f"(single: {s.get('data_fp')}, 3d: "
                      f"{d.get('data_fp')}); one leg is stale. Rerun "
                      f"`python -m quintnet_tpu.tools.parity_run --task "
                      f"{task} --mode <stale mode>`.", ""]
            continue
        # optional chaos-sensitivity control (see run_vit docstring)
        ctl = None
        ctl_path = os.path.join(ART_DIR, f"{task}_control.json")
        if os.path.exists(ctl_path):
            ctl = load(task, "control")
            if (ctl.get("data_fp") != s.get("data_fp")
                    or len(ctl.get("train_loss", []))
                    != len(s["train_loss"])):
                ctl = None  # stale control (different data OR epochs)
        hdr_ctl = " ctl drift (1e-7 perturbation) |" if ctl else ""
        lines += [f"## {task.upper()} ({s['epochs']} epochs)", "",
                  f"| epoch | train loss (1 dev) | train loss (3D) | "
                  f"rel diff |{hdr_ctl} {metric_name} (1 dev) | "
                  f"{metric_name} (3D) |",
                  "|---|---|---|---|---|---|" + ("---|" if ctl else "")]
        max_rel = 0.0
        rels, ctl_rels = [], []
        for e in range(s["epochs"]):
            a, b = s["train_loss"][e], d["train_loss"][e]
            rel = abs(a - b) / max(abs(a), 1e-9)
            rels.append(rel)
            max_rel = max(max_rel, rel)
            ma, mb = s[metric_key][e], d[metric_key][e]
            ctl_cell = ""
            if ctl:
                cr = abs(a - ctl["train_loss"][e]) / max(abs(a), 1e-9)
                ctl_rels.append(cr)
                ctl_cell = f" {cr:.2%} |"
            lines.append(f"| {e} | {a:.4f} | {b:.4f} | {rel:.2%} |"
                         f"{ctl_cell} {ma:.4f} | {mb:.4f} |")
        # Verdict. Exact trajectory identity across the whole run is the
        # strong bar, but the sharded step is a DIFFERENT float program
        # (XLA fuses/reassociates per sharding), so ~1e-7 per-step noise
        # amplifies chaotically once the loss is small — late-epoch
        # relative drift on a shrinking denominator is expected, not a
        # correctness signal (single-step parity is covered bit-level by
        # tests/). Fallback bar: the curves track >= half the run within
        # 1% AND the final quality metric agrees within 2%.
        track = 0
        for r in rels:
            if r >= 0.01:
                break
            track += 1
        fa, fb = s[metric_key][-1], d[metric_key][-1]
        final_rel = abs(fa - fb) / max(abs(fa), 1e-9)
        # When a control leg exists, the chaos claim is MEASURED: the 3d
        # drift must sit within 2x the drift the same single-device
        # program shows from a one-off 1e-7 init perturbation (the
        # magnitude of per-step float reassociation between two XLA
        # programs). Without a control the 1%-tracking fallback applies.
        band_ok = None
        if ctl_rels:
            band = max(max(ctl_rels), 1e-4)
            band_ok = max_rel <= 2.0 * band
        if max_rel < 0.01:
            verdict = "PASS (exact trajectory)"
        elif band_ok and final_rel < 0.02:
            verdict = (f"PASS (3d drift {max_rel:.2%} is within the "
                       f"measured chaos band: the SAME single-device "
                       f"program drifts {max(ctl_rels):.2%} from a 1e-7 "
                       f"init perturbation; final {metric_name} within "
                       f"{final_rel:.2%})")
        elif band_ok is None and track * 2 >= s["epochs"] \
                and final_rel < 0.02:
            verdict = (f"PASS (tracks {track}/{s['epochs']} epochs within "
                       f"1%, final {metric_name} within {final_rel:.2%};"
                       f" late drift is chaotic float divergence — "
                       f"run --mode control to demonstrate)")
        else:
            verdict = "FAIL"
        lines += ["", f"Max relative train-loss difference: "
                  f"**{max_rel:.3%}**; tracked {track}/{s['epochs']} "
                  f"epochs; final {metric_name} diff {final_rel:.2%} "
                  f"-> **{verdict}**", ""]
    return "\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--task", choices=["vit", "gpt2"])
    ap.add_argument("--mode", choices=["single", "3d", "control"])
    ap.add_argument("--report", action="store_true")
    args = ap.parse_args()

    if args.report:
        md = report()
        with open("PARITY.md", "w") as f:
            f.write(md)
        print(md)
        return

    os.makedirs(ART_DIR, exist_ok=True)
    if args.task == "gpt2" and args.mode == "control":
        ap.error("--mode control is implemented for --task vit only "
                 "(gpt2 parity is an exact trajectory, PARITY.md — no "
                 "chaos band needed); run_gpt2 would silently produce "
                 "an unperturbed leg")
    res = run_vit(args.mode) if args.task == "vit" else run_gpt2(args.mode)
    out = os.path.join(ART_DIR, f"{args.task}_{args.mode}.json")
    with open(out, "w") as f:
        json.dump(res, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()

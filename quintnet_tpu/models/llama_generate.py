"""Llama KV-cache generation: jitted prefill + lax.scan decode.

Same decode-loop machinery as GPT-2 (models/gpt2_generate.autoregress —
sampling, EOS, one compiled program); the per-layer math lives in
models/llama.py (llama_block_prefill / llama_block_decode — the SAME
helpers the training block is built from, so a fix there fixes decode
too). GQA caches are stored UNrepeated ([L, B, H_kv, T, Dh] —
1/(H/H_kv) the memory of a repeated cache; kv-head repeat happens at
use).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from quintnet_tpu.models.gpt2_generate import autoregress
from quintnet_tpu.models.llama import (LlamaConfig, llama_block_decode,
                                       llama_block_prefill, llama_logits,
                                       llama_rope_tables)


def _embed(params, ids, cfg: LlamaConfig, tp_axis):
    """Token lookup; under vocab_parallel the table arrives vocab-
    sharded, so out-of-shard ids zero-contribute and one psum
    assembles the embedding (same as gpt2_generate's vp path)."""
    if tp_axis is not None and cfg.vocab_parallel:
        from quintnet_tpu.parallel.tp import vocab_parallel_embedding

        return vocab_parallel_embedding(
            {"table": params["embedding"]["tok"]}, ids, axis=tp_axis)
    return jnp.take(params["embedding"]["tok"], ids, axis=0)


def _full_logits(params, h, cfg: LlamaConfig, tp_axis):
    """Full-vocab logits for sampling/argmax. Under vocab_parallel the
    local [.., V/tp] shard is all-gathered and padded columns masked
    (decoding must never emit an id >= vocab_size)."""
    logits = llama_logits(params, h, cfg)
    if tp_axis is not None and cfg.vocab_parallel:
        from quintnet_tpu.core import collectives as cc
        from quintnet_tpu.models.gpt2 import mask_padded_cols

        logits = cc.all_gather(logits, tp_axis, gather_dim=-1)
        if cfg.padded_vocab_size:
            logits = mask_padded_cols(logits, cfg)
    return logits


def llama_prefill(params, input_ids, cfg: LlamaConfig, *, cache_len: int,
                  tp_axis=None):
    """[B, T0] -> (last-pos logits [B, V], (k, v) caches
    [L, B, H_kv(/tp), cache_len, Dh])."""
    B, T0 = input_ids.shape
    h = _embed(params, input_ids, cfg, tp_axis)
    cos, sin = llama_rope_tables(jnp.arange(T0), cfg)

    def body(x, blk):
        x, kv = llama_block_prefill(blk, x, cfg, cos, sin, tp_axis=tp_axis)
        return x, kv

    h, (ks, vs) = lax.scan(body, h, params["blocks"])
    pad = [(0, 0), (0, 0), (0, 0), (0, cache_len - T0), (0, 0)]
    return (_full_logits(params, h[:, -1:, :], cfg, tp_axis)[:, 0, :],
            (jnp.pad(ks, pad), jnp.pad(vs, pad)))


def llama_decode_step(params, tok, pos, caches, cfg: LlamaConfig,
                      tp_axis=None):
    """One cached step: tok [B], pos scalar -> (logits [B, V], caches)."""
    x = _embed(params, tok[:, None], cfg, tp_axis)              # [B,1,D]
    cos, sin = llama_rope_tables(
        pos[None] if jnp.ndim(pos) == 0 else pos, cfg)
    ks, vs = caches

    def body(x, layer):
        blk, kc, vc = layer
        x, (kc, vc) = llama_block_decode(blk, x, kc, vc, pos, cfg, cos, sin,
                                         tp_axis=tp_axis)
        return x, (kc, vc)

    h, (ks, vs) = lax.scan(body, x, (params["blocks"], ks, vs))
    return _full_logits(params, h, cfg, tp_axis)[:, 0, :], (ks, vs)


def _llama_generate_body(params, input_ids, key, cfg: LlamaConfig,
                         max_new_tokens: int, eos_token_id: Optional[int],
                         temperature: float, top_k: int = 0,
                         top_p: float = 1.0, tp_axis=None):
    cache_len = input_ids.shape[1] + max_new_tokens
    return autoregress(
        lambda ids: llama_prefill(params, ids, cfg, cache_len=cache_len,
                                  tp_axis=tp_axis),
        lambda tok, pos, caches: llama_decode_step(params, tok, pos,
                                                   caches, cfg,
                                                   tp_axis=tp_axis),
        input_ids, key, max_new_tokens=max_new_tokens,
        eos_token_id=eos_token_id, temperature=temperature,
        top_k=top_k, top_p=top_p)


_llama_generate_jit = partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "eos_token_id", "temperature",
    "top_k", "top_p"))(_llama_generate_body)


def llama_generate(params, input_ids, cfg: LlamaConfig, *,
                   max_new_tokens: int, eos_token_id: Optional[int] = None,
                   temperature: float = 0.0, top_k: int = 0,
                   top_p: float = 1.0, key=None) -> np.ndarray:
    """[B, T0] -> [B, T0 + max_new_tokens]; greedy when temperature==0,
    temperature/top-k/top-p otherwise. One jitted prefill+decode
    program per (shape, knobs)."""
    if max_new_tokens < 1:
        return np.asarray(input_ids)
    if input_ids.shape[1] + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt {input_ids.shape[1]} + max_new {max_new_tokens} "
            f"exceeds n_positions={cfg.n_positions}")
    key = key if key is not None else jax.random.key(0)
    out = _llama_generate_jit(params, jnp.asarray(input_ids, jnp.int32),
                              key, cfg, int(max_new_tokens), eos_token_id,
                              float(temperature), top_k=int(top_k),
                              top_p=float(top_p))
    return np.asarray(out)


def llama_generate_tp(params, input_ids, cfg: LlamaConfig, *, mesh,
                      tp_axis: str = "tp", max_new_tokens: int,
                      eos_token_id: Optional[int] = None,
                      temperature: float = 0.0, top_k: int = 0,
                      top_p: float = 1.0, key=None) -> np.ndarray:
    """TP-sharded Llama decode on a live mesh: params stay in their
    training layout (llama_partition_specs), whole prefill + decode
    scan under one shard_map — head-sharded GQA caches with the
    RowParallel psum per cached step. Output tokens replicated,
    token-for-token equal to single-device decode
    (tests/test_llama.py golden). Same capability gpt2_generate_tp
    gives GPT-2; the reference skips generation under any parallelism
    (GPT2_Trainer.py:509-555)."""
    if max_new_tokens < 1:
        return np.asarray(input_ids)
    if input_ids.shape[1] + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt {input_ids.shape[1]} + max_new {max_new_tokens} "
            f"exceeds n_positions={cfg.n_positions}")
    key = key if key is not None else jax.random.key(0)
    fn = _llama_tp_generate_fn(cfg, mesh, tp_axis, int(max_new_tokens),
                               eos_token_id, float(temperature),
                               int(top_k), float(top_p))
    return np.asarray(fn(params, jnp.asarray(input_ids, jnp.int32), key))


import functools


@functools.lru_cache(maxsize=32)
def _llama_tp_generate_fn(cfg: LlamaConfig, mesh, tp_axis: str,
                          max_new_tokens: int, eos_token_id: Optional[int],
                          temperature: float, top_k: int, top_p: float):
    """One cached jitted shard_map program per (cfg, mesh, knobs)."""
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core import collectives as cc
    from quintnet_tpu.models.llama import llama_partition_specs

    specs = llama_partition_specs(cfg, tp_axis=tp_axis)

    def local_gen(p, ids, k):
        return _llama_generate_body(p, ids, k, cfg, max_new_tokens,
                                    eos_token_id, temperature,
                                    top_k=top_k, top_p=top_p,
                                    tp_axis=tp_axis)

    return jax.jit(cc.shard_map_fn(
        local_gen, mesh,
        in_specs=(specs, P(), P()),
        out_specs=P()))


def llama_beam_search(params, input_ids, cfg: LlamaConfig, *,
                      beams: int = 4, max_new_tokens: int,
                      eos_token_id: Optional[int] = None,
                      length_penalty: float = 1.0) -> np.ndarray:
    """Beam-search decode for Llama on the shared beam machinery
    (models/gpt2_generate.beam_autoregress): GNMT length penalty,
    beams=1 reduces to greedy (tests/test_llama.py golden)."""
    if max_new_tokens < 1:
        return np.asarray(input_ids)
    if input_ids.shape[1] + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt {input_ids.shape[1]} + max_new {max_new_tokens} "
            f"exceeds n_positions={cfg.n_positions}")
    out = _llama_beam_jit(params, jnp.asarray(input_ids, jnp.int32), cfg,
                          int(beams), int(max_new_tokens), eos_token_id,
                          float(length_penalty))
    return np.asarray(out)


def _llama_beam_body(params, input_ids, cfg: LlamaConfig, beams: int,
                     max_new_tokens: int, eos_token_id,
                     length_penalty: float):
    from quintnet_tpu.models.gpt2_generate import beam_autoregress

    cache_len = input_ids.shape[1] + max_new_tokens
    return beam_autoregress(
        lambda ids: llama_prefill(params, ids, cfg, cache_len=cache_len),
        lambda tok, pos, caches: llama_decode_step(params, tok, pos,
                                                   caches, cfg),
        input_ids, beams=beams, vocab=cfg.vocab_size,
        max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
        length_penalty=length_penalty)


_llama_beam_jit = partial(jax.jit, static_argnames=(
    "cfg", "beams", "max_new_tokens", "eos_token_id",
    "length_penalty"))(_llama_beam_body)

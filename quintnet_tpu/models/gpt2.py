"""GPT-2 family (124M "base" through XL) for causal LM / finetuning.

TPU-native re-design of the reference's GPT-2 stack
(utils/GPT2/{gpt2_config,gpt2_embeddings,gpt2_attention,gpt2_mlp,
gpt2_block,gpt2_stage}.py). Notable differences:

- One whole-model definition (the reference has no full-model class —
  gpt2_model.py is a 3-line placeholder; GPT-2 exists only as pipeline
  stages). Pipelining here is a view over the same param tree.
- Weights stored [in, out] so forward is x @ w; HF GPT-2's Conv1D
  weights are already [in, out], so the import path needs NO transpose
  (the reference transposes every matrix to torch Linear layout —
  core/distributed_loading.py:295-306, 331-341).
- Weight tying: ``lm_head = wte`` is literally the same array. Under
  pipeline parallelism wte is replicated across pp; stage 0 produces the
  embedding grad, the last stage the lm-head grad, and the standard
  partial_axes psum (parallel/train_step.py) sums them — the reference
  needs a dedicated ``sync_tied_weights_grad`` allreduce after every
  backward (gpt2_stage.py:112-141, GPT2_Trainer.py:290-291, 347-348).
- Blocks reuse nn/transformer.py (same pytree schema as ViT): pre-LN,
  fused QKV, GELU(tanh) — matching HF gpt2's gelu_new.
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from quintnet_tpu.core.pytree import tree_stack
from quintnet_tpu.nn.layers import (
    cast_floating,
    keep_router_f32,
    embedding_init,
    gelu,
    layer_norm_apply,
    layer_norm_init,
)
from quintnet_tpu.nn.transformer import block_init, stacked_blocks_apply

IGNORE_INDEX = -100  # reference: CE ignore_index=-100 (GPT2_Trainer.py:109)


def _cast_tree(tree, dtype):
    """Mixed-precision cast keeping the MoE router at f32 (its gate
    ordering is bf16-sensitive — nn/moe.py, nn/layers.py)."""
    return cast_floating(tree, dtype, exclude=keep_router_f32)


@dataclass(frozen=True)
class GPT2Config:
    """Sizes follow the reference's presets (gpt2_config.py:22-168)."""

    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    layer_norm_epsilon: float = 1e-5
    dropout: float = 0.0
    # per-site rates (reference gpt2_config.yaml:31-33 attn_pdrop /
    # embd_pdrop / resid_pdrop); None falls back to ``dropout``
    embd_pdrop: Optional[float] = None
    attn_pdrop: Optional[float] = None
    resid_pdrop: Optional[float] = None
    # --- MoE (0 experts = dense; the reference has no MoE/EP at all,
    # SURVEY.md §2.2 "EP — Absent"). Every block's MLP becomes a top-k
    # routed MoE FFN (nn/moe.py), expert-shardable over the ``ep`` axis.
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    expert_capacity: Optional[int] = None
    aux_loss_weight: float = 1e-2
    router_z_weight: float = 0.0
    # "topk" (Switch/Mixtral) | "expert_choice" (perfect load balance,
    # no aux loss — nn/moe.py)
    router_type: str = "topk"
    # --- vocab parallelism: shard wte over tp (the reference DEFINES
    # VocabParallelEmbedding but never uses it, layers.py:224-297 —
    # GPT-2 replicates embeddings there). With it on, the lm-head loss
    # is a sharded cross-entropy (local logsumexp + psum) so full
    # [B, T, V] logits are NEVER materialised on any rank. Requires
    # vocab_size % tp == 0 (pad, e.g. 50257 -> 50304, Megatron-style;
    # padded columns are masked out of the softmax so the loss is
    # bit-comparable to the unpadded model). Ignored when tp is off.
    vocab_parallel: bool = False
    # wte table rows when padding the vocab to a tp multiple;
    # ``vocab_size`` stays the REAL vocab (labels/ids range, softmax
    # support). None = no padding (table rows == vocab_size).
    padded_vocab_size: Optional[int] = None
    # --- chunked CE (replicated-activation paths): compute the CLM loss
    # in sequence chunks of this many positions so full [B, S, V] f32
    # logits never materialise (clm_loss_chunked). 0 = off. Ignored
    # under sp (clm_loss_sp) / vocab_parallel (clm_loss_vp), which
    # already avoid full logits their own way.
    loss_chunk: int = 0
    # --- packed-document isolation: when set, attention segment ids are
    # derived on the fly from input_ids (a new segment starts AFTER each
    # occurrence of this token) and threaded into every attention layer
    # incl. the Pallas flash kernel (ops/flash_attention segment_ids) —
    # positions never attend across packed-document boundaries. None =
    # the GPT-2 convention (cross-document attention accepted).
    segment_eos_id: Optional[int] = None
    # --- lax.scan unroll factor for the layer stack (>1 lets XLA
    # software-pipeline adjacent layers; measured knob, see
    # artifacts/remat_unroll_r04.json)
    scan_unroll: int = 1

    @property
    def mlp_hidden(self) -> int:
        return 4 * self.n_embd

    @property
    def table_vocab_size(self) -> int:
        """wte rows (padded vocab when padding is configured)."""
        return self.padded_vocab_size or self.vocab_size

    @property
    def pdrops(self):
        """(embd, attn, resid) dropout rates with ``dropout`` fallback."""
        d = self.dropout
        return (d if self.embd_pdrop is None else self.embd_pdrop,
                d if self.attn_pdrop is None else self.attn_pdrop,
                d if self.resid_pdrop is None else self.resid_pdrop)

    @property
    def needs_dropout(self) -> bool:
        return any(p > 0.0 for p in self.pdrops)

    @property
    def moe_args(self):
        """nn/moe.py MoEArgs for this config, or None when dense."""
        if self.n_experts <= 0:
            return None
        if self.router_type == "expert_choice":
            # EC selects over the whole flattened sequence — position t
            # would see later positions (nn/moe.py MoEArgs.router docs).
            raise ValueError(
                "expert_choice routing is non-causal and unsupported "
                "for the causal LM families; use router_type='topk' "
                "(expert_choice remains available at the nn/moe.py "
                "layer for non-autoregressive models)")
        from quintnet_tpu.nn.moe import MoEArgs

        return MoEArgs(
            n_experts=self.n_experts,
            top_k=self.expert_top_k,
            capacity_factor=self.capacity_factor,
            capacity=self.expert_capacity,
            aux_weight=self.aux_loss_weight,
            z_weight=self.router_z_weight,
            router=self.router_type,
        )

    @staticmethod
    def base() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def medium() -> "GPT2Config":
        return GPT2Config(n_embd=1024, n_layer=24, n_head=16)

    @staticmethod
    def large() -> "GPT2Config":
        return GPT2Config(n_embd=1280, n_layer=36, n_head=20)

    @staticmethod
    def xl() -> "GPT2Config":
        return GPT2Config(n_embd=1600, n_layer=48, n_head=25)

    @staticmethod
    def tiny(**kw) -> "GPT2Config":
        """Test-scale config (not in the reference; used by the simulated-
        mesh test suite)."""
        d = dict(vocab_size=128, n_positions=64, n_embd=32, n_layer=4,
                 n_head=4)
        d.update(kw)
        return GPT2Config(**d)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "GPT2Config":
        names = {f.name for f in dataclasses.fields(GPT2Config)}
        return GPT2Config(**{k: v for k, v in d.items() if k in names})


def gpt2_init(key, cfg: GPT2Config, *, dtype=jnp.float32):
    k_wte, k_wpe, k_blocks = jax.random.split(key, 3)
    block_keys = jax.random.split(k_blocks, cfg.n_layer)
    blocks = tree_stack(
        [block_init(bk, cfg.n_embd, mlp_hidden=cfg.mlp_hidden, dtype=dtype,
                    moe=cfg.moe_args)
         for bk in block_keys]
    )
    return {
        "embedding": {
            "wte": embedding_init(k_wte, cfg.table_vocab_size, cfg.n_embd,
                                  dtype=dtype)["table"],
            "wpe": embedding_init(k_wpe, cfg.n_positions, cfg.n_embd,
                                  scale=0.01, dtype=dtype)["table"],
        },
        "blocks": blocks,
        "head": {"ln_f": layer_norm_init(cfg.n_embd, dtype)},
    }


def gpt2_upcycle_to_moe(params, cfg: GPT2Config, key=None):
    """Sparse upcycling: dense GPT-2 params -> MoE params for a config
    with ``n_experts > 0``. Every expert starts as a copy of the dense
    MLP; routers start near-zero so initial routing is ~uniform and the
    upcycled model's function approximates the dense one. Used by the
    finetune entry point when --experts is combined with --checkpoint
    (there is no reference analogue — the reference has no MoE)."""
    if cfg.n_experts <= 0:
        return params
    if "moe" in params["blocks"]:
        return params  # already MoE
    key = key if key is not None else jax.random.key(0)
    E = cfg.n_experts
    blocks = dict(params["blocks"])
    mlp = blocks.pop("mlp")
    L = mlp["fc"]["w"].shape[0]

    def per_expert(x):  # [L, ...] -> [L, E, ...]
        return jnp.repeat(x[:, None], E, axis=1)

    blocks["moe"] = {
        "router": {"w": 1e-2 * jax.random.normal(
            key, (L, cfg.n_embd, E), jnp.float32)},
        "w1": per_expert(mlp["fc"]["w"]),
        "b1": per_expert(mlp["fc"]["b"]),
        "w2": per_expert(mlp["proj"]["w"]),
        "b2": per_expert(mlp["proj"]["b"]),
    }
    return {**params, "blocks": blocks}


def gpt2_embed(params, input_ids, *, sp_axis: Optional[str] = None,
               embd_pdrop: float = 0.0, key=None,
               vp_axis: Optional[str] = None):
    """[B, T_local] ids -> [B, T_local, D] (reference GPT2Embedding,
    replicated across TP — gpt2_embeddings.py:16-103, including its
    post-sum embedding dropout :100-101 when ``key`` is given).

    With ``sp_axis`` the sequence dim is sharded: this rank's position
    embeddings start at axis_index * T_local. With ``vp_axis`` the wte
    VOCAB dim is sharded over that (tp) axis: out-of-shard ids
    contribute zeros and one psum assembles the embedding
    (parallel/tp.py:vocab_parallel_embedding semantics; the reference
    defined-but-unused VocabParallelEmbedding, layers.py:224-297)."""
    emb = params["embedding"]
    T = input_ids.shape[-1]
    if vp_axis is not None:
        from quintnet_tpu.parallel.tp import vocab_parallel_embedding

        tok = vocab_parallel_embedding({"table": emb["wte"]}, input_ids,
                                       axis=vp_axis)
    else:
        tok = jnp.take(emb["wte"], input_ids, axis=0)
    start = 0
    if sp_axis is not None:
        start = jax.lax.axis_index(sp_axis) * T
    pos = jax.lax.dynamic_slice_in_dim(emb["wpe"], start, T, axis=0)
    h = tok + pos[None, :, :]
    if key is not None and embd_pdrop > 0.0:
        from quintnet_tpu.nn.layers import dropout

        h = dropout(key, h, embd_pdrop, deterministic=False)
    return h


def gpt2_blocks(params_blocks, h, cfg: GPT2Config, *,
                tp_axis: Optional[str] = None,
                sp_axis: Optional[str] = None, sp_mode: str = "ring",
                ep_axis: Optional[str] = None,
                remat: "bool | str" = False, use_flash: bool = False,
                key=None, segment_ids=None, fsdp=None):
    """Returns ``h`` for dense configs, ``(h, moe_aux)`` when
    ``cfg.n_experts > 0``. ``key`` enables training dropout."""
    tp = 1 if tp_axis is None else jax.lax.axis_size(tp_axis)
    _, attn_p, resid_p = cfg.pdrops
    return stacked_blocks_apply(
        params_blocks, h,
        num_heads=cfg.n_head // tp,
        causal=True,
        act=gelu,
        tp_axis=tp_axis,
        sp_axis=sp_axis,
        sp_mode=sp_mode,
        remat=remat,
        use_flash=use_flash,
        moe_args=cfg.moe_args,
        ep_axis=ep_axis,
        attn_pdrop=attn_p,
        resid_pdrop=resid_p,
        key=key,
        scan_unroll=cfg.scan_unroll,
        segment_ids=segment_ids,
        fsdp=fsdp,
    )


def gpt2_logits(params, h, cfg: GPT2Config):
    """ln_f then tied lm_head: logits = ln_f(h) @ wte^T
    (reference: lm_head is a copy of wte synced by hand,
    gpt2_stage.py:112-141; here it IS wte).

    With a padded vocab and an UNSHARDED table (wte rows ==
    table_vocab_size: no-tp fallback of a vocab_parallel config, or
    single-device generation), the padded columns are masked to -inf
    here so they never enter any softmax and argmax-decoding can never
    emit an id >= vocab_size. Vocab-SHARDED tables (local rows under
    vp) are masked inside clm_loss_vp instead, which knows the shard
    offset."""
    h = layer_norm_apply(params["head"]["ln_f"], h, eps=cfg.layer_norm_epsilon)
    logits = jnp.dot(h, params["embedding"]["wte"].T).astype(jnp.float32)
    if (cfg.padded_vocab_size
            and params["embedding"]["wte"].shape[0] == cfg.table_vocab_size):
        logits = mask_padded_cols(logits, cfg)
    return logits


def mask_padded_cols(logits, cfg: "GPT2Config"):
    """-inf the vocab-padding columns of FULL-width logits so they never
    enter a softmax or win an argmax (single place for the semantics:
    used by gpt2_logits, clm_loss_chunked and the tp decoder)."""
    col = jnp.arange(logits.shape[-1])
    return jnp.where(col < cfg.vocab_size, logits,
                     jnp.finfo(jnp.float32).min)


def gpt2_hidden(params, input_ids, cfg: GPT2Config, *,
                tp_axis: Optional[str] = None,
                sp_axis: Optional[str] = None, sp_mode: str = "ring",
                ep_axis: Optional[str] = None,
                remat: "bool | str" = False, use_flash: bool = False,
                key=None, fsdp=None):
    """embed + blocks -> (final hidden states [B, T, D], moe_aux); the
    pre-lm-head half of :func:`gpt2_forward` (chunked-CE computes the
    loss straight from these, never building full logits)."""
    k_embd = k_blocks = None
    if key is not None and cfg.needs_dropout:
        k_embd, k_blocks = jax.random.split(key)
    vp_axis = tp_axis if (cfg.vocab_parallel and tp_axis) else None
    h = gpt2_embed(params, input_ids, sp_axis=sp_axis,
                   embd_pdrop=cfg.pdrops[0], key=k_embd, vp_axis=vp_axis)
    seg = segment_ids_from_input(input_ids, cfg, sp_axis=sp_axis)
    out = gpt2_blocks(params["blocks"], h, cfg, tp_axis=tp_axis,
                      sp_axis=sp_axis, sp_mode=sp_mode, ep_axis=ep_axis,
                      remat=remat, use_flash=use_flash, key=k_blocks,
                      segment_ids=seg, fsdp=fsdp)
    return out if cfg.n_experts > 0 else (out, jnp.zeros((), jnp.float32))


def gpt2_forward(params, input_ids, cfg: GPT2Config, *,
                 tp_axis: Optional[str] = None,
                 sp_axis: Optional[str] = None, sp_mode: str = "ring",
                 ep_axis: Optional[str] = None,
                 remat: "bool | str" = False, use_flash: bool = False,
                 key=None, fsdp=None):
    """-> (logits, moe_aux). ``moe_aux`` is 0.0 for dense configs.
    ``key``: training-dropout key (None -> deterministic/eval)."""
    h, aux = gpt2_hidden(params, input_ids, cfg, tp_axis=tp_axis,
                         sp_axis=sp_axis, sp_mode=sp_mode, ep_axis=ep_axis,
                         remat=remat, use_flash=use_flash, key=key,
                         fsdp=fsdp)
    return gpt2_logits(params, h, cfg), aux


def gpt2_apply(params, input_ids, cfg: GPT2Config, *,
               tp_axis: Optional[str] = None,
               sp_axis: Optional[str] = None, sp_mode: str = "ring",
               ep_axis: Optional[str] = None,
               remat: "bool | str" = False, use_flash: bool = False):
    logits, _ = gpt2_forward(params, input_ids, cfg, tp_axis=tp_axis,
                             sp_axis=sp_axis, sp_mode=sp_mode,
                             ep_axis=ep_axis, remat=remat,
                             use_flash=use_flash)
    return logits


def clm_loss(logits, labels):
    """Shifted causal-LM cross entropy with IGNORE_INDEX masking, mean
    over valid tokens (reference: HF-internal shift + CE ignore_index=-100,
    GPT2_Trainer.py:105-118)."""
    logits = logits[:, :-1]
    targets = labels[:, 1:]
    valid = targets != IGNORE_INDEX
    safe = jnp.where(valid, targets, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    count = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(nll) / count


def clm_loss_chunked(params, h, labels, cfg: "GPT2Config", *, chunk: int):
    """CLM loss computed in sequence chunks straight from the final
    hidden states: the full [B, S, V] logits / log-softmax (f32: ~823MB
    for the bs-8/seq-512 bench config) NEVER materialize — each scan
    step computes one [B, chunk, V] slab, reduces it to (nll_sum,
    count), and the jax.checkpoint'd body recomputes the slab in
    backward instead of storing it. Same math as clm_loss to float
    reassociation (tests/test_gpt2.py golden).

    Single-device / dp/tp-replicated-activation path only (sp shards
    the sequence -> clm_loss_sp; vocab_parallel -> clm_loss_vp)."""
    h = layer_norm_apply(params["head"]["ln_f"], h,
                         eps=cfg.layer_norm_epsilon)
    wte = params["embedding"]["wte"]
    h_pred = h[:, :-1]
    targets = labels[:, 1:]
    B, S, D = h_pred.shape
    pad = (-S) % chunk
    if pad:
        h_pred = jnp.pad(h_pred, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)),
                          constant_values=IGNORE_INDEX)
    nc = (S + pad) // chunk
    h_c = h_pred.reshape(B, nc, chunk, D).transpose(1, 0, 2, 3)
    t_c = targets.reshape(B, nc, chunk).transpose(1, 0, 2)
    mask_pad_cols = (cfg.padded_vocab_size
                     and wte.shape[0] == cfg.table_vocab_size)

    @jax.checkpoint
    def body(carry, xs):
        hc, tc = xs
        logits = jnp.dot(hc, wte.T).astype(jnp.float32)
        if mask_pad_cols:
            logits = mask_padded_cols(logits, cfg)
        valid = tc != IGNORE_INDEX
        safe = jnp.where(valid, tc, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        nll_sum, count = carry
        return (nll_sum + jnp.sum(jnp.where(valid, nll, 0.0)),
                count + jnp.sum(valid)), None

    (nll_sum, count), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
        (h_c, t_c))
    return nll_sum / jnp.maximum(count, 1)


def _sp_shift_targets(labels, sp_axis: str):
    """Next-token target shift when the sequence dim is sharded: each
    rank's last position targets the FIRST label of the next rank's
    chunk (one ppermute); the global-final position (last rank's last
    column) is invalidated. Shared by :func:`clm_loss_sp` and
    :func:`clm_loss_vp` so the shift semantics cannot diverge."""
    sp = jax.lax.axis_size(sp_axis)
    idx = jax.lax.axis_index(sp_axis)
    # rank i+1 sends its first label column to rank i
    perm = [(i + 1, i) for i in range(sp - 1)]
    first_next = jax.lax.ppermute(labels[:, :1], sp_axis, perm)
    targets = jnp.concatenate([labels[:, 1:], first_next], axis=1)
    col = jnp.arange(targets.shape[1])
    boundary = (idx == sp - 1) & (col == targets.shape[1] - 1)
    return jnp.where(boundary[None, :], IGNORE_INDEX, targets)


def clm_loss_sp(logits, labels, *, sp_axis: str):
    """CLM loss when the sequence dim is sharded over ``sp_axis``.

    The next-token shift crosses chunk boundaries
    (:func:`_sp_shift_targets`). Token-count normalisation is global
    (psum of sums / psum of counts), so the result equals
    :func:`clm_loss` on the gathered sequence exactly.
    """
    targets = _sp_shift_targets(labels, sp_axis)

    valid = targets != IGNORE_INDEX
    safe = jnp.where(valid, targets, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    total = jax.lax.psum(jnp.sum(nll), sp_axis)
    count = jax.lax.psum(jnp.sum(valid), sp_axis)
    return total / jnp.maximum(count, 1)


def clm_loss_vp(local_logits, labels, *, tp_axis: str,
                sp_axis: Optional[str] = None,
                vocab_size: Optional[int] = None):
    """CLM loss from VOCAB-SHARDED logits [B, T, V/tp] — the sharded
    cross-entropy: full logits are never materialised on any rank.

    Global logsumexp = log(psum(sum(exp(local - max)))) + max with the
    max pmax'd over tp (stop_gradient on the shift — the true softmax
    gradient flows through the exp/psum path). The target's logit is
    picked by the one rank whose shard holds it and psummed. Equals
    :func:`clm_loss` (resp. :func:`clm_loss_sp` when ``sp_axis``) on the
    gathered logits exactly. ``vocab_size`` masks padded vocab columns
    (Megatron-style padding to a tp multiple) out of the softmax so the
    padded and unpadded models give identical losses."""
    if sp_axis is None:
        logits = local_logits[:, :-1]
        targets = labels[:, 1:]
    else:
        targets = _sp_shift_targets(labels, sp_axis)
        logits = local_logits

    logits = logits.astype(jnp.float32)
    vp = logits.shape[-1]
    start = jax.lax.axis_index(tp_axis) * vp
    if vocab_size is not None:
        col_ids = start + jnp.arange(vp)
        logits = jnp.where(col_ids < vocab_size, logits,
                           jnp.finfo(jnp.float32).min)
    valid = targets != IGNORE_INDEX
    # stop_gradient BEFORE the pmax (pmax has no JVP rule; the shift is
    # a constant anyway — the true softmax grad flows via exp/psum)
    m = jax.lax.pmax(jax.lax.stop_gradient(jnp.max(logits, axis=-1)),
                     tp_axis)
    se = jnp.sum(jnp.exp(logits - m[..., None]), axis=-1)
    lse = jnp.log(jax.lax.psum(se, tp_axis)) + m
    local_t = jnp.where(valid, targets, 0) - start
    in_shard = (local_t >= 0) & (local_t < vp)
    safe = jnp.clip(local_t, 0, vp - 1)
    tl = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    tl = jax.lax.psum(jnp.where(in_shard, tl, 0.0), tp_axis)
    nll = jnp.where(valid, lse - tl, 0.0)
    total = jnp.sum(nll)
    count = jnp.sum(valid)
    if sp_axis is not None:
        total = jax.lax.psum(total, sp_axis)
        count = jax.lax.psum(count, sp_axis)
    return total / jnp.maximum(count, 1)


def perplexity(loss):
    """exp(loss) with the reference's overflow guard at 20
    (GPT2_Trainer.py:316-318, schedule.py:505-516)."""
    return jnp.exp(jnp.minimum(loss, 20.0))


def gpt2_partition_specs(cfg: Optional[GPT2Config] = None, *,
                         tp_axis: Optional[str] = "tp",
                         pp_axis: Optional[str] = None,
                         ep_axis: Optional[str] = None,
                         fsdp_axis: Optional[str] = None):
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.parallel.tp import block_specs

    bspecs = block_specs(tp_axis=tp_axis, stacked=True, pp_axis=pp_axis)
    if cfg is not None and cfg.n_experts > 0:
        from quintnet_tpu.nn.moe import moe_specs

        del bspecs["mlp"]
        bspecs["moe"] = moe_specs(ep_axis=ep_axis, tp_axis=tp_axis,
                                  stacked=True, pp_axis=pp_axis)
    if fsdp_axis is not None:
        from quintnet_tpu.parallel.tp import fsdp_shard_specs

        bspecs = fsdp_shard_specs(bspecs, fsdp_axis)
    wte_spec = P()
    if cfg is not None and cfg.vocab_parallel and tp_axis is not None:
        # vocab dim sharded over tp; grads stay un-psummed over tp by
        # reduce_grads' spec rule (train_step.py) — the vp loss/embed
        # psums supply the tp cotangent factor exactly once.
        wte_spec = P(tp_axis, None)
    return {
        "embedding": {"wte": wte_spec, "wpe": P()},
        "blocks": bspecs,
        "head": {"ln_f": {"scale": P(), "bias": P()}},
    }


def gpt2_to_tp_layout(params, cfg: GPT2Config, tp: int):
    """Standard [q|k|v] fused-QKV columns -> tp-blocked layout
    (parallel/tp.py docstring). Identity at tp=1."""
    from quintnet_tpu.parallel.tp import qkv_blocked_from_standard

    if cfg.vocab_parallel and tp > 1 and cfg.table_vocab_size % tp != 0:
        raise ValueError(
            f"vocab_parallel needs (padded_)vocab_size % tp == 0; got "
            f"{cfg.table_vocab_size} % {tp}. Set padded_vocab_size "
            f"(e.g. 50257 -> 50304); padded columns are masked out of "
            f"the loss.")
    if tp == 1:
        return params
    out = jax.tree.map(lambda x: x, params)
    qkv = out["blocks"]["attn"]["qkv"]
    qkv["w"] = qkv_blocked_from_standard(qkv["w"], cfg.n_head, tp)
    if "b" in qkv:
        qkv["b"] = qkv_blocked_from_standard(qkv["b"], cfg.n_head, tp)
    return out


def gpt2_from_tp_layout(params, cfg: GPT2Config, tp: int):
    """Inverse of :func:`gpt2_to_tp_layout` — back to the standard
    [q|k|v] fused-QKV column order (for export and for single-device
    generation on trained tp-sharded params)."""
    from quintnet_tpu.parallel.tp import qkv_standard_from_blocked

    if tp == 1:
        return params
    out = jax.tree.map(lambda x: x, params)
    qkv = out["blocks"]["attn"]["qkv"]
    qkv["w"] = qkv_standard_from_blocked(qkv["w"], cfg.n_head, tp)
    if "b" in qkv:
        qkv["b"] = qkv_standard_from_blocked(qkv["b"], cfg.n_head, tp)
    return out


def segment_ids_from_input(input_ids, cfg: GPT2Config, *,
                           sp_axis: Optional[str] = None):
    """[B, S] token ids -> [B, S] int32 attention segment ids, or None
    when ``cfg.segment_eos_id`` is unset. Device-side equivalent of
    data/datasets.segments_from_tokens: exclusive running count of the
    separator (each EOS closes its own document).

    ``sp_axis``: the sequence dim is a SHARD of the global sequence —
    the local count is offset by the total separator count of all
    earlier shards (one tiny [sp, B] all-gather), so ids are globally
    consistent and the sp attention modes can compare them across
    chunks."""
    if cfg.segment_eos_id is None:
        return None
    is_eos = (input_ids == cfg.segment_eos_id).astype(jnp.int32)
    seg = jnp.cumsum(is_eos, axis=1) - is_eos
    if sp_axis is not None:
        sp = jax.lax.axis_size(sp_axis)
        idx = jax.lax.axis_index(sp_axis)
        counts = jax.lax.all_gather(jnp.sum(is_eos, axis=1),
                                    sp_axis)               # [sp, B]
        prefix = jnp.sum(
            jnp.where(jnp.arange(sp)[:, None] < idx, counts, 0), axis=0)
        seg = seg + prefix[:, None]
    return seg


def gpt2_pipeline_fns(cfg: GPT2Config, *, tp_axis: Optional[str] = None,
                      sp_axis: Optional[str] = None, sp_mode: str = "ring",
                      ep_axis: Optional[str] = None,
                      remat: "bool | str" = False, use_flash: bool = False,
                      compute_dtype=None):
    """(embed_fn, stage_fn, head_loss_fn) for parallel/pp.py.

    ``compute_dtype=jnp.bfloat16``: params are cast at use (storage stays
    f32 master copies; the cast's transpose accumulates grads back in
    f32) — the TPU mixed-precision default. Softmax/LN/loss stay f32.

    MoE configs make ``stage_fn`` return ``(h, aux)`` — the schedules in
    parallel/pp.py accumulate each stage's aux into the loss.

    ``key`` kwargs on embed/stage enable training dropout; the schedules
    pass per-(microbatch, stage) keys (parallel/pp.py) so the 1F1B
    vjp-recompute reproduces the forward masks exactly.
    """
    if cfg.segment_eos_id is not None:
        raise NotImplementedError(
            "segment_eos_id under pipeline parallelism is not wired "
            "(stage fns receive hidden states, not token ids, so the "
            "segment vector cannot be derived mid-pipeline); use "
            "dp/tp/ep meshes for packed-document isolation")

    def embed_fn(params, input_ids, key=None):
        return gpt2_embed(_cast_tree(params, compute_dtype), input_ids,
                          sp_axis=sp_axis, embd_pdrop=cfg.pdrops[0],
                          key=key,
                          vp_axis=(tp_axis if cfg.vocab_parallel else None))

    def stage_fn(blocks_local, h, key=None):
        return gpt2_blocks(_cast_tree(blocks_local, compute_dtype), h, cfg,
                           tp_axis=tp_axis, sp_axis=sp_axis, sp_mode=sp_mode,
                           ep_axis=ep_axis, remat=remat, use_flash=use_flash,
                           key=key)

    vp = cfg.vocab_parallel and tp_axis is not None
    if vp or sp_axis is not None:
        # the loss contains collectives (vp lse psums / sp shift+psum),
        # which may not sit inside the schedules' lax.cond gate — split:
        # gated collective-free lm-head matmul, unconditional reduction
        # (parallel/pp.py SplitHead)
        from quintnet_tpu.parallel.pp import SplitHead

        def head_local_fn(params, h, labels):
            return gpt2_logits(_cast_tree(params, compute_dtype), h, cfg)

        def head_reduce_fn(logits, labels, valid):
            if vp:
                loss = clm_loss_vp(
                    logits, labels, tp_axis=tp_axis, sp_axis=sp_axis,
                    vocab_size=(cfg.vocab_size if cfg.padded_vocab_size
                                else None))
            else:
                loss = clm_loss_sp(logits, labels, sp_axis=sp_axis)
            return jnp.where(valid, loss, 0.0)

        return embed_fn, stage_fn, SplitHead(head_local_fn, head_reduce_fn)

    def head_loss_fn(params, h, labels):
        p = _cast_tree(params, compute_dtype)
        if cfg.loss_chunk > 0:
            return clm_loss_chunked(p, h, labels, cfg,
                                    chunk=cfg.loss_chunk)
        return clm_loss(gpt2_logits(p, h, cfg), labels)

    return embed_fn, stage_fn, head_loss_fn


def _fsdp_info(cfg: "GPT2Config", tp_axis, ep_axis, fsdp_axis):
    from quintnet_tpu.parallel.tp import fsdp_info

    return fsdp_info(functools.partial(gpt2_partition_specs, cfg),
                     fsdp_axis, tp_axis=tp_axis, ep_axis=ep_axis)


def gpt2_model_spec(cfg: GPT2Config, *, remat: "bool | str" = False,
                    use_flash: bool = False, sp_mode: str = "ring",
                    compute_dtype=None):
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.parallel.strategy import ModelSpec

    def loss_fn(params, batch, tp_axis=None, sp_axis=None, ep_axis=None,
                key=None, fsdp_axis=None):
        input_ids, labels = batch
        p = _cast_tree(params, compute_dtype)
        fsdp = _fsdp_info(cfg, tp_axis, ep_axis, fsdp_axis)
        vp = cfg.vocab_parallel and tp_axis is not None
        if cfg.loss_chunk > 0 and not vp and sp_axis is None:
            h, aux = gpt2_hidden(p, input_ids, cfg, tp_axis=tp_axis,
                                 sp_axis=sp_axis, sp_mode=sp_mode,
                                 ep_axis=ep_axis, remat=remat,
                                 use_flash=use_flash, key=key, fsdp=fsdp)
            return clm_loss_chunked(p, h, labels, cfg,
                                    chunk=cfg.loss_chunk) + aux
        logits, aux = gpt2_forward(p, input_ids, cfg, tp_axis=tp_axis,
                                   sp_axis=sp_axis, sp_mode=sp_mode,
                                   ep_axis=ep_axis, remat=remat,
                                   use_flash=use_flash, key=key,
                                   fsdp=fsdp)
        if vp:
            return clm_loss_vp(
                logits, labels, tp_axis=tp_axis, sp_axis=sp_axis,
                vocab_size=(cfg.vocab_size if cfg.padded_vocab_size
                            else None)) + aux
        if sp_axis is not None:
            return clm_loss_sp(logits, labels, sp_axis=sp_axis) + aux
        return clm_loss(logits, labels) + aux

    def pipeline_fns(tp_axis=None, sp_axis=None, ep_axis=None):
        return gpt2_pipeline_fns(cfg, tp_axis=tp_axis, sp_axis=sp_axis,
                                 sp_mode=sp_mode, ep_axis=ep_axis,
                                 remat=remat, use_flash=use_flash,
                                 compute_dtype=compute_dtype)

    def batch_specs(batch_axes, sp_axis=None):
        # (input_ids, labels): batch dim over dp (+ep), sequence dim over sp
        spec = P(tuple(batch_axes) if batch_axes else None, sp_axis)
        return (spec, spec)

    return ModelSpec(
        init=lambda key: gpt2_init(key, cfg),
        loss_fn=loss_fn,
        partition_specs=lambda tp_axis=None, pp_axis=None, ep_axis=None, \
                fsdp_axis=None:
            gpt2_partition_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis,
                                 ep_axis=ep_axis, fsdp_axis=fsdp_axis),
        pipeline_fns=pipeline_fns,
        to_tp_layout=lambda p, tp: gpt2_to_tp_layout(p, cfg, tp),
        depth=cfg.n_layer,
        batch_specs=batch_specs,
        needs_rng=cfg.needs_dropout,
    )

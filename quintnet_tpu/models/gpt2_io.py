"""GPT-2 checkpoint import/export: HF safetensors <-> quintnet_tpu trees.

Covers the reference's three checkpoint paths in one module:
- sharded pretrained load (core/distributed_loading.py:203-376) — here
  :func:`load_hf_gpt2` reads the HF file lazily (mmap) into the host
  tree and the Strategy places shards; per-(tp,pp) byte-level slicing
  is unnecessary on TPU hosts but the reader supports it (memmap views);
- per-shard save + offline merge to HF (GPT2_Trainer.py:453-507,
  merge_checkpoints.py:191-244) — here :func:`save_hf_gpt2` writes a
  standard HF-layout file directly from the (gathered) param tree;
- Conv1D transposes (distributed_loading.py:295-306): NOT needed —
  HF GPT-2 Conv1D weights are [in, out], which is this framework's
  native layout.

HF key schema handled: optional "transformer." prefix, "h.{i}." blocks,
attention mask buffers ("attn.bias"/"attn.masked_bias") skipped,
"lm_head.weight" skipped (tied to wte).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from quintnet_tpu.models.gpt2 import GPT2Config
from quintnet_tpu.core.pytree import tree_stack
from quintnet_tpu.utils import safetensors_io as st


def _norm_key(k: str) -> str:
    return k[len("transformer."):] if k.startswith("transformer.") else k


def load_hf_gpt2(path: str, cfg: Optional[GPT2Config] = None,
                 *, dtype=jnp.float32):
    """HF gpt2 safetensors file -> (params tree, GPT2Config).

    The returned tree uses the standard [q|k|v] fused-QKV layout;
    ``Strategy.shard_params`` applies the tp-blocked permutation.
    """
    def _skip(k: str) -> bool:
        # causal-mask buffers ("...attn.bias"/"...attn.masked_bias" — NOT
        # "c_attn.bias") and the tied lm_head
        tail = k.split(".")[-2:]
        return tail in (["attn", "bias"], ["attn", "masked_bias"]) \
            or _norm_key(k) == "lm_head.weight"

    with st.SafeTensorFile(path) as f:
        t = {_norm_key(k): f.tensor(k) for k in f.keys() if not _skip(k)}

    wte = t["wte.weight"]
    wpe = t["wpe.weight"]
    n_layer = 1 + max(int(k.split(".")[1]) for k in t if k.startswith("h."))
    if cfg is None:
        cfg = GPT2Config.from_dict({
            "vocab_size": wte.shape[0],
            "n_positions": wpe.shape[0],
            "n_embd": wte.shape[1],
            "n_layer": n_layer,
            "n_head": 12 if wte.shape[1] == 768 else
                      16 if wte.shape[1] == 1024 else
                      20 if wte.shape[1] == 1280 else 25,
        })

    def arr(x):
        return jnp.asarray(x, dtype)

    def block(i):
        p = f"h.{i}."
        return {
            "ln1": {"scale": arr(t[p + "ln_1.weight"]),
                    "bias": arr(t[p + "ln_1.bias"])},
            "attn": {
                "qkv": {"w": arr(t[p + "attn.c_attn.weight"]),
                        "b": arr(t[p + "attn.c_attn.bias"])},
                "proj": {"w": arr(t[p + "attn.c_proj.weight"]),
                         "b": arr(t[p + "attn.c_proj.bias"])},
            },
            "ln2": {"scale": arr(t[p + "ln_2.weight"]),
                    "bias": arr(t[p + "ln_2.bias"])},
            "mlp": {
                "fc": {"w": arr(t[p + "mlp.c_fc.weight"]),
                       "b": arr(t[p + "mlp.c_fc.bias"])},
                "proj": {"w": arr(t[p + "mlp.c_proj.weight"]),
                         "b": arr(t[p + "mlp.c_proj.bias"])},
            },
        }

    params = {
        "embedding": {"wte": arr(wte), "wpe": arr(wpe)},
        "blocks": tree_stack([block(i) for i in range(cfg.n_layer)]),
        "head": {"ln_f": {"scale": arr(t["ln_f.weight"]),
                          "bias": arr(t["ln_f.bias"])}},
    }
    return params, cfg


def save_hf_gpt2(params, cfg: GPT2Config, path: str,
                 *, prefix: str = "", tp_layout: int = 1) -> None:
    """Param tree -> HF-layout safetensors (merge_checkpoints.py
    semantics: one file loadable by transformers GPT2LMHeadModel).

    ``tp_layout``: if the tree is in tp-blocked QKV layout, pass the tp
    size used so columns are permuted back to standard [q|k|v].
    """
    from quintnet_tpu.parallel.tp import qkv_standard_from_blocked

    def n(x):
        return np.asarray(jnp.asarray(x, jnp.float32))

    out: Dict[str, np.ndarray] = {
        prefix + "wte.weight": n(params["embedding"]["wte"]),
        prefix + "wpe.weight": n(params["embedding"]["wpe"]),
        prefix + "ln_f.weight": n(params["head"]["ln_f"]["scale"]),
        prefix + "ln_f.bias": n(params["head"]["ln_f"]["bias"]),
    }
    blocks = params["blocks"]
    for i in range(cfg.n_layer):
        p = f"{prefix}h.{i}."
        blk = _index_block(blocks, i)
        qkv_w = blk["attn"]["qkv"]["w"]
        qkv_b = blk["attn"]["qkv"]["b"]
        if tp_layout > 1:
            qkv_w = qkv_standard_from_blocked(qkv_w, cfg.n_head, tp_layout)
            qkv_b = qkv_standard_from_blocked(qkv_b, cfg.n_head, tp_layout)
        out[p + "ln_1.weight"] = n(blk["ln1"]["scale"])
        out[p + "ln_1.bias"] = n(blk["ln1"]["bias"])
        out[p + "attn.c_attn.weight"] = n(qkv_w)
        out[p + "attn.c_attn.bias"] = n(qkv_b)
        out[p + "attn.c_proj.weight"] = n(blk["attn"]["proj"]["w"])
        out[p + "attn.c_proj.bias"] = n(blk["attn"]["proj"]["b"])
        out[p + "ln_2.weight"] = n(blk["ln2"]["scale"])
        out[p + "ln_2.bias"] = n(blk["ln2"]["bias"])
        out[p + "mlp.c_fc.weight"] = n(blk["mlp"]["fc"]["w"])
        out[p + "mlp.c_fc.bias"] = n(blk["mlp"]["fc"]["b"])
        out[p + "mlp.c_proj.weight"] = n(blk["mlp"]["proj"]["w"])
        out[p + "mlp.c_proj.bias"] = n(blk["mlp"]["proj"]["b"])
    st.save_file(out, path, metadata={"format": "pt"})


def _index_block(stacked, i: int):
    import jax

    return jax.tree.map(lambda x: x[i], stacked)

"""KV-cache autoregressive generation for GPT-2 (dense and MoE).

The reference generates by re-running the FULL prefix through the model
for every new token (greedy loop in utils/metrics.py:74-149) — O(T^2)
attention work per token and a fresh compile-sized dispatch each step.
Here decoding is TPU-shaped:

- **prefill**: one causal forward over the prompt that also emits every
  layer's (k, v) into a [L, B, H, T_max, Dh] cache (nn/transformer.py
  block_prefill);
- **decode**: a single jitted ``lax.scan`` over new-token steps, each
  step one cached block pass per layer (nn/attention.py mha_decode) —
  O(T) per token, static shapes throughout, one compilation total;
- **EOS** handling inside the scan: finished rows keep emitting
  ``eos_token_id`` (same observable behavior as the reference's early
  exit, without dynamic shapes).

Greedy by default; ``temperature > 0`` switches to sampling.
Generation runs single-device (the reference's generation eval is also
single-device and skipped under PP — GPT2_Trainer.py:509-555).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_logits
from quintnet_tpu.nn.layers import gelu
from quintnet_tpu.nn.transformer import block_decode, block_prefill


def gpt2_prefill(params, input_ids, cfg: GPT2Config, *, cache_len: int):
    """[B, T0] prompt -> (last-position logits [B, V],
    (k_cache, v_cache) each [L, B, H, cache_len, Dh])."""
    B, T0 = input_ids.shape
    emb = params["embedding"]
    h = (jnp.take(emb["wte"], input_ids, axis=0)
         + emb["wpe"][None, :T0, :])

    def body(x, blk):
        x, (k, v) = block_prefill(blk, x, num_heads=cfg.n_head, act=gelu,
                                  moe_args=cfg.moe_args)
        return x, (k, v)

    h, (ks, vs) = lax.scan(body, h, params["blocks"])
    pad = [(0, 0), (0, 0), (0, 0), (0, cache_len - T0), (0, 0)]
    return (gpt2_logits(params, h[:, -1:, :], cfg)[:, 0, :],
            (jnp.pad(ks, pad), jnp.pad(vs, pad)))


def gpt2_decode_step(params, tok, pos, caches, cfg: GPT2Config):
    """One cached decode step: tok [B] int32, pos scalar, caches
    [L, B, H, T, Dh] -> (logits [B, V], updated caches)."""
    emb = params["embedding"]
    x = (jnp.take(emb["wte"], tok[:, None], axis=0)
         + lax.dynamic_slice_in_dim(emb["wpe"], pos, 1, axis=0)[None])

    ks, vs = caches

    def body(h, layer):
        blk, kc, vc = layer
        h, kc, vc = block_decode(blk, h, kc, vc, pos,
                                 num_heads=cfg.n_head, act=gelu,
                                 moe_args=cfg.moe_args)
        return h, (kc, vc)

    h, (ks, vs) = lax.scan(body, x, (params["blocks"], ks, vs))
    return gpt2_logits(params, h, cfg)[:, 0, :], (ks, vs)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "eos_token_id",
                                   "temperature"))
def _generate_jit(params, input_ids, key, cfg: GPT2Config,
                  max_new_tokens: int, eos_token_id: Optional[int],
                  temperature: float):
    B, T0 = input_ids.shape
    cache_len = T0 + max_new_tokens
    logits0, caches = gpt2_prefill(params, input_ids, cfg,
                                   cache_len=cache_len)

    def pick(logits, k):
        if temperature > 0.0:
            return jax.random.categorical(k, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def step(carry, _):
        tok, pos, caches, done, k = carry
        k, sub = jax.random.split(k)
        logits, caches = gpt2_decode_step(params, tok, pos, caches, cfg)
        nxt = pick(logits, sub).astype(jnp.int32)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, pos + 1, caches, done, k), nxt

    key0, sub0 = jax.random.split(key)
    first = pick(logits0, sub0).astype(jnp.int32)
    done0 = jnp.zeros((B,), bool)
    if eos_token_id is not None:
        done0 = first == eos_token_id
    (_, _, _, _, _), rest = lax.scan(
        step, (first, jnp.int32(T0), caches, done0, key0),
        None, length=max_new_tokens - 1)
    return jnp.concatenate(
        [input_ids, first[:, None], rest.T.astype(jnp.int32)], axis=1)


def gpt2_generate(params, input_ids, cfg: GPT2Config, *,
                  max_new_tokens: int, eos_token_id: Optional[int] = None,
                  temperature: float = 0.0, key=None) -> np.ndarray:
    """input_ids [B, T0] -> [B, T0 + max_new_tokens] (greedy when
    ``temperature == 0``). One jitted program: prefill + scan decode."""
    if max_new_tokens < 1:
        return np.asarray(input_ids)
    if input_ids.shape[1] + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt {input_ids.shape[1]} + max_new {max_new_tokens} "
            f"exceeds n_positions={cfg.n_positions}")
    key = key if key is not None else jax.random.key(0)
    out = _generate_jit(params, jnp.asarray(input_ids, jnp.int32), key,
                        cfg, int(max_new_tokens), eos_token_id,
                        float(temperature))
    return np.asarray(out)

"""KV-cache autoregressive generation for GPT-2 (dense and MoE).

The reference generates by re-running the FULL prefix through the model
for every new token (greedy loop in utils/metrics.py:74-149) — O(T^2)
attention work per token and a fresh compile-sized dispatch each step.
Here decoding is TPU-shaped:

- **prefill**: one causal forward over the prompt that also emits every
  layer's (k, v) into a [L, B, H, T_max, Dh] cache (nn/transformer.py
  block_prefill);
- **decode**: a single jitted ``lax.scan`` over new-token steps, each
  step one cached block pass per layer (nn/attention.py mha_decode) —
  O(T) per token, static shapes throughout, one compilation total;
- **EOS** handling inside the scan: finished rows keep emitting
  ``eos_token_id`` (same observable behavior as the reference's early
  exit, without dynamic shapes).

Greedy by default; ``temperature > 0`` switches to sampling.

Generation runs single-device by default, and TP-SHARDED via
:func:`gpt2_generate_tp`: head-sharded prefill+decode with the
RowParallel psum in every cached attention step and (for
``cfg.vocab_parallel``) vocab-sharded logits assembled by all-gather.
The reference cannot generate under ANY parallelism (gen eval skipped,
GPT2_Trainer.py:509-555) — anything bigger than one chip's HBM can't
eval there; here the same tp mesh that trains also decodes.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from quintnet_tpu.models.gpt2 import GPT2Config, gpt2_logits
from quintnet_tpu.nn.layers import gelu, layer_norm_apply
from quintnet_tpu.nn.transformer import block_decode, block_prefill


def sample_logits(logits, key, *, temperature: float = 1.0,
                  top_k: int = 0, top_p: float = 1.0):
    """Sample next tokens from [B, V] logits: temperature, then top-k
    truncation, then nucleus (top-p). ``temperature <= 0`` is greedy
    argmax regardless of the filters (matches HF semantics; the
    reference supports greedy only, utils/metrics.py:74-149).

    Static-shape throughout: top-k thresholds against the k-th largest
    logit; top-p sorts the full vocab once per step (eval-time cost,
    fine off the training path)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    neg = jnp.finfo(logits.dtype).min
    if top_k and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if 0.0 < top_p < 1.0:
        srt, idx = lax.top_k(logits, logits.shape[-1])  # desc sort
        probs = jax.nn.softmax(srt, axis=-1)
        # drop tokens whose preceding cumulative mass already reached
        # top_p (the first token crossing the threshold is KEPT). The
        # few-ulp slack keeps the boundary decision stable across jax
        # versions: softmax(log(p)) can land a hair under an exactly-
        # representable threshold (e.g. 0.79999995 vs top_p=0.8).
        tol = 16 * jnp.finfo(probs.dtype).eps
        drop = jnp.cumsum(probs, axis=-1) - probs > top_p - tol
        srt = jnp.where(drop, neg, srt)
        # un-sort: position j of the sorted row goes back to column
        # idx[j]; argsort(idx) inverts the permutation
        inv = jnp.argsort(idx, axis=-1)
        logits = jnp.take_along_axis(srt, inv, axis=-1)
    return jax.random.categorical(key, logits, axis=-1)


def _local_heads(cfg: GPT2Config, tp_axis: Optional[str]) -> int:
    if tp_axis is None:
        return cfg.n_head
    return cfg.n_head // lax.axis_size(tp_axis)


def _embed_tok(emb, ids, cfg: GPT2Config, tp_axis: Optional[str]):
    """Token embedding; vocab-sharded lookup + psum under vp."""
    if tp_axis is not None and cfg.vocab_parallel:
        from quintnet_tpu.parallel.tp import vocab_parallel_embedding

        return vocab_parallel_embedding({"table": emb["wte"]}, ids,
                                        axis=tp_axis)
    return jnp.take(emb["wte"], ids, axis=0)


def _logits(params, h, cfg: GPT2Config, tp_axis: Optional[str]):
    """Full-vocab logits. Under vocab_parallel the local [.., V/tp]
    shard is all-gathered on the vocab dim (parallel/tp.py
    vocab_parallel_logits) and padded columns masked."""
    if tp_axis is None or not cfg.vocab_parallel:
        return gpt2_logits(params, h, cfg)
    from quintnet_tpu.models.gpt2 import mask_padded_cols
    from quintnet_tpu.parallel.tp import vocab_parallel_logits

    h = layer_norm_apply(params["head"]["ln_f"], h,
                         eps=cfg.layer_norm_epsilon)
    logits = vocab_parallel_logits(
        params["embedding"]["wte"].T, h, axis=tp_axis).astype(jnp.float32)
    if cfg.padded_vocab_size:
        logits = mask_padded_cols(logits, cfg)
    return logits


def gpt2_prefill(params, input_ids, cfg: GPT2Config, *, cache_len: int,
                 tp_axis: Optional[str] = None):
    """[B, T0] prompt -> (last-position logits [B, V],
    (k_cache, v_cache) each [L, B, H, cache_len, Dh]).
    Under ``tp_axis`` H is LOCAL heads (H/tp)."""
    B, T0 = input_ids.shape
    emb = params["embedding"]
    h = _embed_tok(emb, input_ids, cfg, tp_axis) + emb["wpe"][None, :T0, :]
    heads = _local_heads(cfg, tp_axis)

    def body(x, blk):
        x, (k, v) = block_prefill(blk, x, num_heads=heads, act=gelu,
                                  moe_args=cfg.moe_args, tp_axis=tp_axis)
        return x, (k, v)

    h, (ks, vs) = lax.scan(body, h, params["blocks"])
    pad = [(0, 0), (0, 0), (0, 0), (0, cache_len - T0), (0, 0)]
    return (_logits(params, h[:, -1:, :], cfg, tp_axis)[:, 0, :],
            (jnp.pad(ks, pad), jnp.pad(vs, pad)))


def gpt2_decode_step(params, tok, pos, caches, cfg: GPT2Config,
                     tp_axis: Optional[str] = None):
    """One cached decode step: tok [B] int32, pos scalar, caches
    [L, B, H, T, Dh] -> (logits [B, V], updated caches)."""
    emb = params["embedding"]
    x = (_embed_tok(emb, tok[:, None], cfg, tp_axis)
         + lax.dynamic_slice_in_dim(emb["wpe"], pos, 1, axis=0)[None])

    ks, vs = caches
    heads = _local_heads(cfg, tp_axis)

    def body(h, layer):
        blk, kc, vc = layer
        h, kc, vc = block_decode(blk, h, kc, vc, pos,
                                 num_heads=heads, act=gelu,
                                 moe_args=cfg.moe_args, tp_axis=tp_axis)
        return h, (kc, vc)

    h, (ks, vs) = lax.scan(body, x, (params["blocks"], ks, vs))
    return _logits(params, h, cfg, tp_axis)[:, 0, :], (ks, vs)


def autoregress(prefill_fn, decode_fn, input_ids, key, *,
                max_new_tokens: int, eos_token_id: Optional[int],
                temperature: float, top_k: int = 0, top_p: float = 1.0):
    """Model-agnostic jittable decode loop: ``prefill_fn(ids) ->
    (last-pos logits [B, V], caches)``; ``decode_fn(tok [B], pos,
    caches) -> (logits, caches)``. Sampling/EOS semantics shared by
    every family (GPT-2 here, Llama in models/llama_generate.py)."""
    B, T0 = input_ids.shape
    logits0, caches = prefill_fn(input_ids)

    def pick(logits, k):
        # same key on every tp rank (replicated inputs) -> same
        # sample; no cross-rank divergence to reconcile
        return sample_logits(logits, k, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    def step(carry, _):
        tok, pos, caches, done, k = carry
        k, sub = jax.random.split(k)
        logits, caches = decode_fn(tok, pos, caches)
        nxt = pick(logits, sub).astype(jnp.int32)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        return (nxt, pos + 1, caches, done, k), nxt

    key0, sub0 = jax.random.split(key)
    first = pick(logits0, sub0).astype(jnp.int32)
    done0 = jnp.zeros((B,), bool)
    if eos_token_id is not None:
        done0 = first == eos_token_id
    (_, _, _, _, _), rest = lax.scan(
        step, (first, jnp.int32(T0), caches, done0, key0),
        None, length=max_new_tokens - 1)
    return jnp.concatenate(
        [input_ids, first[:, None], rest.T.astype(jnp.int32)], axis=1)


def _generate_body(params, input_ids, key, cfg: GPT2Config,
                   max_new_tokens: int, eos_token_id: Optional[int],
                   temperature: float, tp_axis: Optional[str] = None,
                   top_k: int = 0, top_p: float = 1.0):
    cache_len = input_ids.shape[1] + max_new_tokens
    return autoregress(
        lambda ids: gpt2_prefill(params, ids, cfg, cache_len=cache_len,
                                 tp_axis=tp_axis),
        lambda tok, pos, caches: gpt2_decode_step(params, tok, pos,
                                                  caches, cfg,
                                                  tp_axis=tp_axis),
        input_ids, key, max_new_tokens=max_new_tokens,
        eos_token_id=eos_token_id, temperature=temperature,
        top_k=top_k, top_p=top_p)


_generate_jit = partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "eos_token_id", "temperature",
    "top_k", "top_p"))(_generate_body)


def gpt2_generate(params, input_ids, cfg: GPT2Config, *,
                  max_new_tokens: int, eos_token_id: Optional[int] = None,
                  temperature: float = 0.0, top_k: int = 0,
                  top_p: float = 1.0, key=None) -> np.ndarray:
    """input_ids [B, T0] -> [B, T0 + max_new_tokens] (greedy when
    ``temperature == 0``; ``top_k``/``top_p`` filter the sampling
    distribution). One jitted program: prefill + scan decode."""
    if max_new_tokens < 1:
        return np.asarray(input_ids)
    if input_ids.shape[1] + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt {input_ids.shape[1]} + max_new {max_new_tokens} "
            f"exceeds n_positions={cfg.n_positions}")
    key = key if key is not None else jax.random.key(0)
    out = _generate_jit(params, jnp.asarray(input_ids, jnp.int32), key,
                        cfg, int(max_new_tokens), eos_token_id,
                        float(temperature), top_k=int(top_k),
                        top_p=float(top_p))
    return np.asarray(out)


def beam_autoregress(prefill_fn, decode_fn, input_ids, *, beams: int,
                     vocab: int, max_new_tokens: int,
                     eos_token_id: Optional[int],
                     length_penalty: float):
    """Model-agnostic beam decode (same prefill_fn/decode_fn contract
    as :func:`autoregress`; ``vocab`` = logits width). GPT-2 wires it
    below; Llama in models/llama_generate.py."""
    B, T0 = input_ids.shape
    K = beams
    V = vocab
    neg = jnp.float32(-1e30)

    logits0, caches = prefill_fn(input_ids)
    # expand to B*K rows (beam-major inside each batch row)
    caches = jax.tree.map(
        lambda c: jnp.repeat(c, K, axis=1), caches)   # [L, B*K, H, T, Dh]
    logp0 = jax.nn.log_softmax(logits0.astype(jnp.float32), axis=-1)

    # first expansion: top-K distinct tokens seed the K beams (scoring
    # all beams from identical states would return K copies of one beam)
    s0, t0 = lax.top_k(logp0, K)                      # [B, K]
    scores = s0
    done = (jnp.zeros((B, K), bool) if eos_token_id is None
            else t0 == eos_token_id)
    toks = jnp.full((B, K, max_new_tokens), 0, jnp.int32)
    toks = toks.at[:, :, 0].set(t0)

    def step(carry, i):
        scores, done, toks, caches = carry
        tok = lax.dynamic_index_in_dim(toks, i - 1, axis=2,
                                       keepdims=False)  # [B, K]
        logits, caches = decode_fn(tok.reshape(B * K),
                                   jnp.int32(T0) + i - 1, caches)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(B, K, V)
        if eos_token_id is not None:
            # finished beams may only re-emit EOS at zero cost, so their
            # score freezes and they stay comparable to live beams
            only_eos = jnp.full((V,), neg).at[eos_token_id].set(0.0)
            logp = jnp.where(done[:, :, None], only_eos[None, None, :],
                             logp)
        total = scores[:, :, None] + logp               # [B, K, V]
        flat_s, flat_i = lax.top_k(total.reshape(B, K * V), K)
        parent = flat_i // V                             # [B, K]
        token = (flat_i % V).astype(jnp.int32)

        # reindex beam state to the selected parents
        batch_idx = jnp.arange(B)[:, None]
        toks = toks[batch_idx, parent]                   # [B, K, T_new]
        toks = toks.at[:, :, i].set(token)
        done = done[batch_idx, parent]
        if eos_token_id is not None:
            done = done | (token == eos_token_id)
        flat_parent = (parent + jnp.arange(B)[:, None] * K).reshape(-1)
        caches = jax.tree.map(lambda c: c[:, flat_parent], caches)
        return (flat_s, done, toks, caches), None

    (scores, done, toks, _), _ = lax.scan(
        step, (scores, done, toks, caches),
        jnp.arange(1, max_new_tokens))

    # pick the best beam by length-normalised score (GNMT-style);
    # length = tokens up to and including the first EOS
    if eos_token_id is not None:
        first_eos = jnp.argmax(toks == eos_token_id, axis=2)  # 0 if none
        has_eos = jnp.any(toks == eos_token_id, axis=2)
        lengths = jnp.where(has_eos, first_eos + 1, max_new_tokens)
    else:
        lengths = jnp.full((B, K), max_new_tokens)
    norm = scores / (lengths.astype(jnp.float32) ** length_penalty)
    best = jnp.argmax(norm, axis=1)                      # [B]
    best_toks = toks[jnp.arange(B), best]                # [B, T_new]
    if eos_token_id is not None:
        # pad everything after the first EOS with EOS (same observable
        # convention as sampling/greedy decode)
        pos = jnp.arange(max_new_tokens)[None, :]
        cut = jnp.where(jnp.any(best_toks == eos_token_id, axis=1),
                        jnp.argmax(best_toks == eos_token_id, axis=1),
                        max_new_tokens)[:, None]
        best_toks = jnp.where(pos > cut, eos_token_id, best_toks)
    return jnp.concatenate([input_ids, best_toks], axis=1)


def _beam_body(params, input_ids, cfg: GPT2Config, beams: int,
               max_new_tokens: int, eos_token_id: Optional[int],
               length_penalty: float):
    cache_len = input_ids.shape[1] + max_new_tokens
    return beam_autoregress(
        lambda ids: gpt2_prefill(params, ids, cfg,
                                 cache_len=cache_len),
        lambda tok, pos, caches: gpt2_decode_step(params, tok, pos,
                                                  caches, cfg),
        input_ids, beams=beams,
        vocab=(cfg.table_vocab_size if cfg.padded_vocab_size
               else cfg.vocab_size),
        max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
        length_penalty=length_penalty)


_beam_jit = partial(jax.jit, static_argnames=(
    "cfg", "beams", "max_new_tokens", "eos_token_id",
    "length_penalty"))(_beam_body)


def gpt2_beam_search(params, input_ids, cfg: GPT2Config, *, beams: int = 4,
                     max_new_tokens: int,
                     eos_token_id: Optional[int] = None,
                     length_penalty: float = 1.0) -> np.ndarray:
    """Beam-search decode with the KV cache: [B, T0] ->
    [B, T0 + max_new_tokens], best of ``beams`` by length-normalised
    log-probability (GNMT penalty).

    One jitted program, static shapes: beams ride a B*K row dimension,
    each step re-indexes the caches to the selected parents inside the
    scan. ``beams=1`` reduces exactly to greedy decode
    (tests/test_beam.py golden). The reference has greedy only
    (utils/metrics.py:74-149).
    """
    if max_new_tokens < 1:
        return np.asarray(input_ids)
    if input_ids.shape[1] + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt {input_ids.shape[1]} + max_new {max_new_tokens} "
            f"exceeds n_positions={cfg.n_positions}")
    out = _beam_jit(params, jnp.asarray(input_ids, jnp.int32), cfg,
                    int(beams), int(max_new_tokens), eos_token_id,
                    float(length_penalty))
    return np.asarray(out)


def gpt2_generate_tp(params, input_ids, cfg: GPT2Config, *, mesh,
                     tp_axis: str = "tp", max_new_tokens: int,
                     eos_token_id: Optional[int] = None,
                     temperature: float = 0.0, top_k: int = 0,
                     top_p: float = 1.0, key=None) -> np.ndarray:
    """TP-sharded generation over a live mesh.

    ``params`` must be in the tp layout (gpt2_to_tp_layout) and sharded
    per gpt2_partition_specs(cfg, tp_axis=tp_axis) — i.e. exactly the
    training layout, so a training run can evaluate generation without
    re-gathering anything. The whole prefill + decode scan runs inside
    one shard_map: head-sharded attention with a psum per cached step
    (nn/attention.py mha_decode), TP mlp, and vocab-sharded logits
    all-gathered under ``cfg.vocab_parallel``. Output tokens are
    replicated — bit-identical to single-device decode
    (tests/test_generate.py golden).

    The reference SKIPS generation eval under any parallelism
    (GPT2_Trainer.py:509-555); 124M fits one chip, but its >1-chip
    models would simply have no eval story.
    """
    if max_new_tokens < 1:
        return np.asarray(input_ids)
    if input_ids.shape[1] + max_new_tokens > cfg.n_positions:
        raise ValueError(
            f"prompt {input_ids.shape[1]} + max_new {max_new_tokens} "
            f"exceeds n_positions={cfg.n_positions}")
    key = key if key is not None else jax.random.key(0)
    fn = _tp_generate_fn(cfg, mesh, tp_axis, int(max_new_tokens),
                         eos_token_id, float(temperature), int(top_k),
                         float(top_p))
    return np.asarray(fn(params, jnp.asarray(input_ids, jnp.int32), key))


@functools.lru_cache(maxsize=32)
def _tp_generate_fn(cfg: GPT2Config, mesh, tp_axis: str,
                    max_new_tokens: int, eos_token_id: Optional[int],
                    temperature: float, top_k: int = 0,
                    top_p: float = 1.0):
    """One cached jitted shard_map program per (cfg, mesh, decode
    params) — a fresh closure per call would defeat the jit cache and
    recompile the whole prefill+decode every generation batch."""
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core import collectives as cc
    from quintnet_tpu.models.gpt2 import gpt2_partition_specs

    specs = gpt2_partition_specs(cfg, tp_axis=tp_axis)

    def local_gen(p, ids, k):
        return _generate_body(p, ids, k, cfg, max_new_tokens,
                              eos_token_id, temperature, tp_axis=tp_axis,
                              top_k=top_k, top_p=top_p)

    return jax.jit(cc.shard_map_fn(
        local_gen, mesh,
        in_specs=(specs, P(), P()),
        out_specs=P()))

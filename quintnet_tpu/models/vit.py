"""Vision Transformer for image classification (MNIST-scale).

TPU-native re-design of the reference ViT (utils/model.py:45-399):
- patch embedding = patchify reshape + one matmul instead of Conv2d
  (model.py:150-195) — same linear map, direct MXU lowering;
- blocks stored stacked [depth, ...] and run with lax.scan instead of a
  ModuleList Python loop (model.py:325-380);
- CLS token + learned position embeddings, pre-LN blocks with ReLU MLP,
  classification head reading the CLS position — structure and widths
  match model.py:235-323 so convergence curves are comparable.

The param tree is partitioned into the same three top-level groups the
reference's pipeline wrapper depends on (``embedding`` / ``blocks`` /
``head``; wrapper.py:89-96): PP slices ``blocks`` and replicates the
small embedding/head params on every stage.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from quintnet_tpu.core.config import ModelConfig
from quintnet_tpu.core.pytree import tree_stack
from quintnet_tpu.nn.layers import (
    cast_floating,
    layer_norm_apply,
    layer_norm_init,
    linear_apply,
    linear_init,
    patchify,
)
from quintnet_tpu.nn.transformer import block_init, stacked_blocks_apply


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 28
    patch_size: int = 7
    in_channels: int = 1
    hidden_dim: int = 64
    depth: int = 8
    num_heads: int = 4
    mlp_ratio: float = 4.0
    num_classes: int = 10
    # One rate for embedding/attention/residual sites (the reference ViT
    # has no dropout at all — utils/model.py — so 0.0 keeps parity; the
    # knob is wired, not silently ignored: vit_apply threads it to the
    # same block sites GPT-2 uses, gated by the train step's seed)
    dropout: float = 0.0
    # lax.scan unroll factor over the block stack (perf knob, same
    # semantics as GPT2Config.scan_unroll)
    scan_unroll: int = 1
    # --- MoE (0 = dense): every block's MLP becomes a routed mixture
    # (nn/moe.py), ep-shardable. ViT is non-causal, so BOTH routers are
    # legal here — including "expert_choice" (the router the causal LM
    # families must reject).
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    expert_capacity: Optional[int] = None
    aux_loss_weight: float = 1e-2
    router_type: str = "topk"

    @property
    def moe_args(self):
        if self.n_experts <= 0:
            return None
        from quintnet_tpu.nn.moe import MoEArgs

        return MoEArgs(n_experts=self.n_experts, top_k=self.expert_top_k,
                       capacity_factor=self.capacity_factor,
                       capacity=self.expert_capacity,
                       aux_weight=self.aux_loss_weight,
                       router=self.router_type)

    @property
    def needs_dropout(self) -> bool:
        return self.dropout > 0.0

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def seq_len(self) -> int:
        return self.num_patches + 1  # + CLS

    @property
    def mlp_hidden(self) -> int:
        return int(self.hidden_dim * self.mlp_ratio)

    @staticmethod
    def from_model_config(m: ModelConfig) -> "ViTConfig":
        names = {f.name for f in dataclasses.fields(ViTConfig)}
        d = {k: v for k, v in dataclasses.asdict(m).items() if k in names}
        return ViTConfig(**d)


def vit_init(key, cfg: ViTConfig, *, dtype=jnp.float32):
    k_patch, k_cls, k_pos, k_blocks, k_head = jax.random.split(key, 5)
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.in_channels

    block_keys = jax.random.split(k_blocks, cfg.depth)
    blocks = tree_stack(
        [block_init(bk, cfg.hidden_dim, mlp_hidden=cfg.mlp_hidden,
                    dtype=dtype, moe=cfg.moe_args)
         for bk in block_keys]
    )

    return {
        "embedding": {
            "patch": linear_init(k_patch, patch_dim, cfg.hidden_dim, dtype=dtype),
            "cls": jax.random.normal(k_cls, (1, 1, cfg.hidden_dim), dtype) * 0.02,
            "pos": jax.random.normal(k_pos, (1, cfg.seq_len, cfg.hidden_dim), dtype) * 0.02,
        },
        "blocks": blocks,
        "head": {
            "ln": layer_norm_init(cfg.hidden_dim, dtype),
            "fc": linear_init(k_head, cfg.hidden_dim, cfg.num_classes, dtype=dtype),
        },
    }


def vit_embed(p_emb, images, patch_size: int, *, pdrop: float = 0.0,
              key=None):
    """images [B, H, W, C] -> tokens [B, N+1, D] (reference ViTEmbedding,
    model.py:271-323). ``key`` enables embedding dropout in training."""
    x = patchify(images, patch_size)
    x = linear_apply(p_emb["patch"], x)
    b = x.shape[0]
    cls = jnp.broadcast_to(p_emb["cls"], (b, 1, x.shape[-1])).astype(x.dtype)
    x = jnp.concatenate([cls, x], axis=1)
    x = x + p_emb["pos"].astype(x.dtype)
    if key is not None and pdrop > 0.0:
        from quintnet_tpu.nn.layers import dropout

        x = dropout(key, x, pdrop, deterministic=False)
    return x


def vit_head(p_head, x):
    """CLS token -> logits (reference ClassificationHead, model.py:235-269)."""
    cls = layer_norm_apply(p_head["ln"], x[:, 0])
    return linear_apply(p_head["fc"], cls)


def vit_forward(
    params,
    images,
    cfg: ViTConfig,
    *,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    remat: bool = False,
    compute_dtype=None,
    key=None,
    fsdp=None,
):
    """[B, H, W, C] (or [B, C, H, W] — auto-detected) ->
    (logits, moe_aux). ``moe_aux`` is 0.0 for dense configs; with
    ``cfg.n_experts > 0`` every block MLP routes through nn/moe.py
    (ViT is non-causal, so expert_choice routing is legal here).

    ``tp_axis``: see nn/transformer.py — heads/MLP column-row sharded;
    ``num_heads`` passed to attention is LOCAL heads.
    ``key``: training-dropout key (rate ``cfg.dropout`` at the embedding
    /attention/residual sites); None -> deterministic eval.
    """
    if images.ndim == 4 and images.shape[1] == cfg.in_channels \
            and images.shape[-1] != cfg.in_channels:
        images = images.transpose(0, 2, 3, 1)  # NCHW (torch layout) -> NHWC
    if compute_dtype is not None:
        images = images.astype(compute_dtype)
        params = cast_floating(params, compute_dtype)

    tp = 1
    if tp_axis is not None:
        tp = jax.lax.axis_size(tp_axis)
    local_heads = cfg.num_heads // tp

    k_embd = k_blocks = None
    if key is not None and cfg.dropout > 0.0:
        k_embd, k_blocks = jax.random.split(key)
    x = vit_embed(params["embedding"], images, cfg.patch_size,
                  pdrop=cfg.dropout, key=k_embd)
    out = stacked_blocks_apply(
        params["blocks"],
        x,
        num_heads=local_heads,
        causal=False,
        act=jax.nn.relu,  # reference ViT MLP uses ReLU (model.py:112-148)
        tp_axis=tp_axis,
        remat=remat,
        moe_args=cfg.moe_args,
        ep_axis=ep_axis,
        attn_pdrop=cfg.dropout,
        resid_pdrop=cfg.dropout,
        key=k_blocks,
        scan_unroll=cfg.scan_unroll,
        fsdp=fsdp,
    )
    x, aux = out if cfg.n_experts > 0 else (out,
                                            jnp.zeros((), jnp.float32))
    return vit_head(params["head"], x).astype(jnp.float32), aux


def vit_apply(params, images, cfg: ViTConfig, *,
              tp_axis: Optional[str] = None, remat: bool = False,
              compute_dtype=None, key=None):
    """Logits only (aux discarded) — the eval/inference view."""
    logits, _ = vit_forward(params, images, cfg, tp_axis=tp_axis,
                            remat=remat, compute_dtype=compute_dtype,
                            key=key)
    return logits


def vit_partition_specs(cfg: Optional[ViTConfig] = None, *,
                        tp_axis: Optional[str] = "tp",
                        pp_axis: Optional[str] = None,
                        ep_axis: Optional[str] = None,
                        fsdp_axis: Optional[str] = None):
    """PartitionSpec tree matching :func:`vit_init`'s param tree.

    Embedding and head are small -> replicated (the reference replicates
    them too: first/last stage modules, wrapper.py:131-184); blocks get
    Megatron column/row TP sharding, and optionally their stacked depth
    dim sharded over ``pp_axis``.
    """
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.parallel.tp import block_specs

    bspecs = block_specs(tp_axis=tp_axis, stacked=True, pp_axis=pp_axis)
    if cfg is not None and cfg.n_experts > 0:
        from quintnet_tpu.nn.moe import moe_specs

        del bspecs["mlp"]
        bspecs["moe"] = moe_specs(ep_axis=ep_axis, tp_axis=tp_axis,
                                  stacked=True, pp_axis=pp_axis)
    if fsdp_axis is not None:
        from quintnet_tpu.parallel.tp import fsdp_shard_specs

        bspecs = fsdp_shard_specs(bspecs, fsdp_axis)
    return {
        "embedding": {
            "patch": {"w": P(), "b": P()},
            "cls": P(),
            "pos": P(),
        },
        "blocks": bspecs,
        "head": {
            "ln": {"scale": P(), "bias": P()},
            "fc": {"w": P(), "b": P()},
        },
    }


def vit_to_tp_layout(params, cfg: ViTConfig, tp: int):
    """Convert a single-device param tree to the tp-blocked fused-QKV
    layout (parallel/tp.py docstring) so sharded and unsharded runs are
    numerically identical. Identity for tp=1."""
    from quintnet_tpu.parallel.tp import qkv_blocked_from_standard

    if tp == 1:
        return params
    out = jax.tree.map(lambda x: x, params)  # shallow copy
    qkv = out["blocks"]["attn"]["qkv"]
    qkv["w"] = qkv_blocked_from_standard(qkv["w"], cfg.num_heads, tp)
    if "b" in qkv:
        qkv["b"] = qkv_blocked_from_standard(qkv["b"], cfg.num_heads, tp)
    return out


def vit_pipeline_fns(cfg: ViTConfig, *, tp_axis: Optional[str] = None,
                     ep_axis: Optional[str] = None, remat: bool = False):
    """(embed_fn, stage_fn, head_loss_fn) for parallel/pp.py schedules.

    Replaces the reference's PipelineParallelWrapper attribute plumbing
    (wrapper.py:89-96: embedding -> stage 0, classification_head -> last
    stage, blocks split in between).

    MoE configs make ``stage_fn`` return ``(h, aux)`` — the schedules
    in parallel/pp.py accumulate each stage's aux into the loss (same
    contract as gpt2_pipeline_fns).
    """

    def embed_fn(params, x, key=None):
        if x.ndim == 4 and x.shape[1] == cfg.in_channels \
                and x.shape[-1] != cfg.in_channels:
            x = x.transpose(0, 2, 3, 1)
        return vit_embed(params["embedding"], x, cfg.patch_size,
                         pdrop=cfg.dropout, key=key)

    def stage_fn(blocks_local, h, key=None):
        tp = 1 if tp_axis is None else jax.lax.axis_size(tp_axis)
        return stacked_blocks_apply(
            blocks_local, h,
            num_heads=cfg.num_heads // tp,
            causal=False,
            act=jax.nn.relu,
            tp_axis=tp_axis,
            remat=remat,
            moe_args=cfg.moe_args,
            ep_axis=ep_axis,
            attn_pdrop=cfg.dropout,
            resid_pdrop=cfg.dropout,
            key=key,
        )

    def head_loss_fn(params, h, y):
        return cross_entropy_loss(vit_head(params["head"], h), y)

    return embed_fn, stage_fn, head_loss_fn


def cross_entropy_loss(logits, labels):
    """Mean CE over the batch (reference Trainer uses nn.CrossEntropyLoss,
    trainer.py:90)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def vit_model_spec(cfg: ViTConfig, *, remat: bool = False):
    """Package the ViT as a strategy-pluggable ModelSpec
    (parallel/strategy.py)."""
    from quintnet_tpu.parallel.strategy import ModelSpec

    def _fsdp(tp_axis, ep_axis, fsdp_axis):
        import functools as _ft

        from quintnet_tpu.parallel.tp import fsdp_info

        return fsdp_info(_ft.partial(vit_partition_specs, cfg),
                         fsdp_axis, tp_axis=tp_axis, ep_axis=ep_axis)

    def loss_fn(params, batch, tp_axis=None, sp_axis=None, ep_axis=None,
                key=None, fsdp_axis=None):
        x, y = batch
        logits, aux = vit_forward(params, x, cfg, tp_axis=tp_axis,
                                  ep_axis=ep_axis, remat=remat, key=key,
                                  fsdp=_fsdp(tp_axis, ep_axis, fsdp_axis))
        return cross_entropy_loss(logits, y) + aux

    def pipeline_fns(tp_axis=None, sp_axis=None, ep_axis=None):
        return vit_pipeline_fns(cfg, tp_axis=tp_axis, ep_axis=ep_axis,
                                remat=remat)

    def partition_specs(tp_axis=None, pp_axis=None, ep_axis=None,
                        fsdp_axis=None):
        return vit_partition_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis,
                                   ep_axis=ep_axis, fsdp_axis=fsdp_axis)

    def to_tp_layout(params, tp):
        return vit_to_tp_layout(params, cfg, tp)

    def eval_metrics_fn(params, batch, tp_axis=None, sp_axis=None,
                        ep_axis=None, fsdp_axis=None):
        x, y = batch
        logits, _ = vit_forward(params, x, cfg, tp_axis=tp_axis,
                                ep_axis=ep_axis, remat=remat,
                                fsdp=_fsdp(tp_axis, ep_axis, fsdp_axis))
        return {"loss": cross_entropy_loss(logits, y),
                "accuracy": accuracy(logits, y)}

    def pipeline_eval_fns(tp_axis=None, sp_axis=None, ep_axis=None):
        embed_fn, stage_fn, _ = vit_pipeline_fns(cfg, tp_axis=tp_axis,
                                                 ep_axis=ep_axis,
                                                 remat=remat)

        def head_metrics_fn(params, h, y):
            logits = vit_head(params["head"], h).astype(jnp.float32)
            return {"loss": cross_entropy_loss(logits, y),
                    "accuracy": accuracy(logits, y)}

        return embed_fn, stage_fn, head_metrics_fn

    return ModelSpec(
        init=lambda key: vit_init(key, cfg),
        loss_fn=loss_fn,
        partition_specs=partition_specs,
        pipeline_fns=pipeline_fns,
        to_tp_layout=to_tp_layout,
        depth=cfg.depth,
        eval_metrics_fn=eval_metrics_fn,
        pipeline_eval_fns=pipeline_eval_fns,
        needs_rng=cfg.needs_dropout,
    )


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))

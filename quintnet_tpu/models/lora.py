"""LoRA: low-rank adaptation for parameter-efficient finetuning.

Not in the reference (full-weight finetuning only — GPT2_Trainer.py
updates every parameter); table stakes for a finetuning framework, and
particularly cheap in this functional design: adapters are just another
pytree, merged into the base weights INSIDE the jitted step
(``w + (alpha/r) * a @ b`` per target matrix), so every existing
strategy, schedule and kernel runs unchanged on the merged weights.

Sharding composes by construction: for a target weight spec
``P(depth, s_in, s_out)`` the adapters shard ``a: P(depth, s_in, -)``,
``b: P(depth, -, s_out)`` — the shard-local product ``a @ b`` then has
exactly the weight's sharding for BOTH column-parallel (out-sharded)
and row-parallel (in-sharded) layers, so the merge needs no
collectives (:func:`lora_partition_specs`).

Optimizer state exists only for the adapters (the point of LoRA: the
Adam m/v for a 124M model shrink from ~1GB to a few MB at r=8).

Typical use::

    lcfg = LoRAConfig(rank=8, alpha=16.0)
    lora = lora_init(key, params["blocks"], lcfg)
    fwd = lora_wrap(lambda p, ids: gpt2_apply(p, ids, cfg), params, lcfg)
    loss = lambda lora, b: clm_loss(fwd(lora, b[0]), b[1])
    # ... optax over `lora` only; export with lora_merge_tree(...)
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

DEFAULT_TARGETS = ("qkv", "proj", "fc")          # GPT-2 / ViT blocks
LLAMA_TARGETS = ("q", "k", "v", "o", "gate", "up", "down")
LLAMA_ATTN_TARGETS = ("q", "v")                  # the classic LoRA subset


@dataclass(frozen=True)
class LoRAConfig:
    rank: int = 8
    alpha: float = 16.0
    # linear-layer names to adapt (dict nodes holding a "w"); defaults
    # cover attention qkv/proj and both MLP matmuls
    targets: Tuple[str, ...] = DEFAULT_TARGETS

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"LoRA rank must be >= 1; got {self.rank}")
        bad = [t for t in self.targets if "," in t]
        if bad:
            # save_lora serialises targets comma-joined in the
            # safetensors header; a comma inside a name would split
            # into phantom targets on reload
            raise ValueError(
                f"LoRA target names must not contain ',': {bad}")
        if not self.targets:
            raise ValueError("LoRA targets must be non-empty")

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _target_paths(blocks, targets: Sequence[str]):
    """Paths (tuples of keys) of every targeted linear in a block tree."""
    out = []

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                if (k in targets and isinstance(v, dict) and "w" in v
                        and getattr(v["w"], "ndim", 0) >= 2):
                    out.append(path + (k,))
                else:
                    walk(v, path + (k,))

    walk(blocks, ())
    return out


def _get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def lora_init(key, blocks, cfg: LoRAConfig) -> Dict:
    """Adapter tree for a (stacked) block param tree: for each targeted
    ``w`` of shape [..., in, out], ``a ~ U(+-1/sqrt(in))`` [..., in, r]
    and ``b = 0`` [..., r, out] (zero init keeps step-0 outputs
    bit-identical to the base model)."""
    paths = _target_paths(blocks, cfg.targets)
    if not paths:
        raise ValueError(f"no LoRA targets {cfg.targets} found")
    tree: Dict = {}
    for path, k in zip(paths, jax.random.split(key, len(paths))):
        w = _get(blocks, path)["w"]
        *lead, fan_in, fan_out = w.shape
        bound = 1.0 / (fan_in ** 0.5)
        node = {
            "a": jax.random.uniform(k, (*lead, fan_in, cfg.rank),
                                    w.dtype, -bound, bound),
            "b": jnp.zeros((*lead, cfg.rank, fan_out), w.dtype),
        }
        sub = tree
        for kk in path[:-1]:
            sub = sub.setdefault(kk, {})
        sub[path[-1]] = node
    return tree


def lora_merge_blocks(blocks, lora, cfg: LoRAConfig):
    """blocks with ``w + scale * a @ b`` at every adapted path; all
    other leaves pass through untouched (same pytree structure)."""

    def walk(node, lnode):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            lv = lnode.get(k) if isinstance(lnode, dict) else None
            if lv is not None and isinstance(lv, dict) and "a" in lv:
                delta = jnp.einsum("...ir,...ro->...io", lv["a"], lv["b"])
                out[k] = {**v, "w": (v["w"]
                                     + cfg.scale * delta.astype(v["w"].dtype))}
            else:
                out[k] = walk(v, lv)
        return out

    return walk(blocks, lora)


def lora_merge_tree(params, lora, cfg: LoRAConfig, *, key: str = "blocks"):
    """Full model params with the adapters folded into ``params[key]``
    (export / merged inference)."""
    return {**params, key: lora_merge_blocks(params[key], lora, cfg)}


def lora_wrap(apply_fn, base_params, cfg: LoRAConfig, *,
              key: str = "blocks"):
    """``fn(lora, *args)`` = ``apply_fn(merge(base, lora), *args)``.
    Differentiating ``fn`` w.r.t. ``lora`` trains ONLY the adapters —
    the base stays a captured constant (no optimizer state for it)."""

    def fn(lora, *args, **kw):
        return apply_fn(lora_merge_tree(base_params, lora, cfg, key=key),
                        *args, **kw)

    return fn


def lora_partition_specs(block_specs, cfg: LoRAConfig, *, blocks=None):
    """PartitionSpec tree for an adapter tree, derived from the weight
    specs: a inherits the in-dim sharding, b the out-dim sharding, rank
    unsharded (see module docstring for why the local merge is then
    exact).

    PartitionSpec omits trailing Nones (P('tp') on a 2-D weight shards
    dim 0), so short specs are right-padded before splitting off the
    (in, out) dims. Pass ``blocks`` (the param tree) to pad to each
    weight's true rank; without it, specs shorter than 2 pad to length
    2 — correct for unstacked weights, ambiguous for stacked weights
    with rank-deficient specs (supply ``blocks`` there)."""
    from jax.sharding import PartitionSpec as P

    def walk(node, bnode):
        if not isinstance(node, dict):
            return None
        out = {}
        for k, v in node.items():
            bv = bnode.get(k) if isinstance(bnode, dict) else None
            if (k in cfg.targets and isinstance(v, dict) and "w" in v
                    and not isinstance(v["w"], dict)):
                wspec = tuple(v["w"])  # PartitionSpec() -> ()
                rank = (bv["w"].ndim if isinstance(bv, dict)
                        and hasattr(bv.get("w"), "ndim")
                        else max(len(wspec), 2))
                wspec = wspec + (None,) * (rank - len(wspec))
                out[k] = {"a": P(*wspec[:-2], wspec[-2], None),
                          "b": P(*wspec[:-2], None, wspec[-1])}
            else:
                sub = walk(v, bv)
                if sub:
                    out[k] = sub
        return out

    return walk(block_specs, blocks) or {}


def lora_param_count(lora) -> int:
    from quintnet_tpu.core.pytree import tree_count_params

    return tree_count_params(lora)


def lora_upcast(lora, dtype=jnp.float32):
    """Cast adapters (e.g. after loading a bf16 checkpoint) — training
    adapters in f32 while the frozen base stays bf16 is the standard
    memory/stability split."""
    return jax.tree.map(lambda l: l.astype(dtype), lora)


def make_lora_train_step(mesh, merged_loss_fn, optimizer, *,
                         base_specs, lora_specs, batch_specs=None,
                         batch_axes=("dp",), model_axes=("tp",)):
    """Sharded adapter-only training over any (dp, tp, ...) mesh.

    ``merged_loss_fn(base_params, lora, batch) -> scalar`` runs INSIDE
    shard_map (it sees local shards and may use collectives — merge with
    :func:`lora_merge_blocks`/``lora_merge_tree`` locally; the spec
    derivation makes that exact, see module docstring). Only the
    adapters carry gradients/optimizer state; the base rides along as a
    frozen sharded input (never donated, no optimizer memory).

    Returns ``step(base, lora, opt_state, batch) ->
    (lora, opt_state, loss)`` — jitted, adapters+state donated.
    """
    import optax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.core import collectives as cc
    from quintnet_tpu.parallel.train_step import (opt_state_specs,
                                                  reduce_grads)

    data_axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    maxes = tuple(a for a in model_axes if a in mesh.axis_names)

    def local_step(base, lora, opt_state, batch):
        loss, g = jax.value_and_grad(
            lambda l: merged_loss_fn(base, l, batch))(lora)
        g = reduce_grads(g, lora_specs, data_axes=data_axes,
                         model_axes=maxes)
        if data_axes:
            loss = lax.pmean(loss, data_axes)
        updates, opt_state = optimizer.update(g, opt_state, lora)
        return optax.apply_updates(lora, updates), opt_state, loss

    compiled = {}

    def step(base, lora, opt_state, batch):
        if "fn" not in compiled:
            o_specs = opt_state_specs(optimizer, lora, lora_specs)
            b_spec = (batch_specs if batch_specs is not None
                      else P(data_axes if data_axes else None))
            compiled["fn"] = jax.jit(cc.shard_map_fn(
                local_step, mesh,
                in_specs=(base_specs, lora_specs, o_specs, b_spec),
                out_specs=(lora_specs, o_specs, P())),
                donate_argnums=(1, 2))
        return compiled["fn"](base, lora, opt_state, batch)

    return step


def _flatten(lora) -> Dict[str, jnp.ndarray]:
    out = {}

    def walk(node, prefix):
        for k, v in node.items():
            name = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                walk(v, name)
            else:
                out[name] = v

    walk(lora, "")
    return out


def save_lora(lora, cfg: LoRAConfig, path: str):
    """Adapters -> one safetensors file (dotted-path keys + the LoRA
    hyperparams in the header metadata); pairs with :func:`load_lora`.
    Tiny by construction — adapters ship separately from the base
    checkpoint, HF-peft style."""
    import numpy as np

    from quintnet_tpu.utils.safetensors_io import save_file

    meta = {"lora_rank": str(cfg.rank), "lora_alpha": str(cfg.alpha),
            "lora_targets": ",".join(cfg.targets)}
    save_file({k: np.asarray(v) for k, v in _flatten(lora).items()},
              path, metadata=meta)


def load_lora(path: str) -> Tuple[Dict, LoRAConfig]:
    """(adapter tree, LoRAConfig) back from :func:`save_lora`."""
    from quintnet_tpu.utils.safetensors_io import SafeTensorFile

    with SafeTensorFile(path) as r:
        meta = r.metadata or {}
        tree: Dict = {}
        for name in r.keys():
            sub = tree
            parts = name.split(".")
            for k in parts[:-1]:
                sub = sub.setdefault(k, {})
            # materialised copy: the mmap closes at `with` exit, so no
            # zero-copy views may outlive it
            sub[parts[-1]] = jnp.asarray(r.tensor(name))
    cfg = LoRAConfig(
        rank=int(meta.get("lora_rank", 8)),
        alpha=float(meta.get("lora_alpha", 16.0)),
        targets=tuple(meta.get("lora_targets",
                               ",".join(DEFAULT_TARGETS)).split(",")))
    return tree, cfg

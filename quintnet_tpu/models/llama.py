"""Llama-family causal LM: RMSNorm + rotary + SwiGLU + GQA.

Beyond the reference (its model zoo is ViT + GPT-2 only,
SURVEY.md §2.4) — this is the "another model family" extension, built to
demonstrate that the framework's machinery is model-agnostic: the block
plugs into the SAME stacked-scan runner (nn/transformer.py
stacked_blocks_apply via ``body_fn``), the same strategies, trainers,
LoRA, ZeRO and flash/ring attention paths GPT-2 uses.

Weights are stored [in, out] (x @ w). HF Llama checkpoints store torch
Linear [out, in]; the import path transposes
(:func:`llama_from_hf_state`). Logits verified against HF
``LlamaForCausalLM`` on identical weights (tests/test_llama.py).

TP sharding: q/k/v column-sharded by (kv-)heads, o row-sharded with one
psum; gate/up column- and down row-sharded (one psum) — the same
Megatron pattern as GPT-2. Requires ``n_kv_heads % tp == 0``.

SP: rope uses GLOBAL positions (sp-offset like gpt2_embed's wpe
lookup), and since rope is applied to q/k BEFORE attention, the
ring/zigzag/ulysses paths run unchanged on the rotated tensors.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from quintnet_tpu.core.pytree import tree_stack
from quintnet_tpu.nn.attention import (apply_rope, repeat_kv, rope_cos_sin,
                                       sdpa)
from quintnet_tpu.nn.layers import (cast_floating, linear_init,
                                    quantized_matmul, rms_norm_apply,
                                    rms_norm_init, swiglu_apply,
                                    swiglu_init)
from quintnet_tpu.nn.moe import moe_apply, moe_init, moe_specs
from quintnet_tpu.nn.transformer import stacked_blocks_apply

from quintnet_tpu.models.gpt2 import (clm_loss, clm_loss_sp,  # shared CLM
                                      clm_loss_vp, mask_padded_cols)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    n_positions: int = 2048          # max_position_embeddings
    dim: int = 2048                  # hidden_size
    n_layers: int = 16
    n_heads: int = 32
    n_kv_heads: int = 8              # GQA groups (== n_heads -> MHA)
    intermediate_size: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = True      # Llama-3.2-1B ties; 7B+ do not
    scan_unroll: int = 1
    # --- MoE (0 = dense): every block's SwiGLU becomes a top-k routed
    # mixture of SwiGLU experts (Mixtral-style; nn/moe.py swiglu expert
    # type), shardable over the ``ep`` mesh axis
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    expert_capacity: Optional[int] = None
    aux_loss_weight: float = 1e-2
    router_type: str = "topk"  # or "expert_choice" (nn/moe.py)
    # --- vocab parallelism: shard the token table (and untied lm head)
    # over tp — at Llama-3's 128256-token vocab the replicated table is
    # the single largest tensor, and the vp loss (models/gpt2.py
    # clm_loss_vp) never materialises full [B, S, V] logits on any
    # rank. Same semantics as GPT2Config.vocab_parallel; requires
    # (padded_)vocab_size % tp == 0 (use padded_vocab_size to round up;
    # padded columns are masked out of the softmax).
    vocab_parallel: bool = False
    padded_vocab_size: Optional[int] = None
    # packed-document isolation: derive attention segment ids from
    # input_ids (new segment after each occurrence of this token) and
    # mask cross-document attention — models/gpt2.py segment_ids_from_input
    # semantics. None = cross-document attention (pretraining default).
    segment_eos_id: Optional[int] = None
    # llama3-style rope scaling (None = unscaled). Tuple (hashable — the
    # config is a jit static arg): (factor, low_freq_factor,
    # high_freq_factor, original_max_position). HF applies this when
    # config.rope_scaling["rope_type"] == "llama3"; real 3.1/3.2
    # checkpoints SHIP with it, so ignoring it silently rotates q/k by
    # wrong angles (round-4 review finding).
    rope_scaling: Optional[Tuple[float, float, float, int]] = None

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def table_vocab_size(self) -> int:
        """tok table rows (padded vocab when padding is configured)."""
        return self.padded_vocab_size or self.vocab_size

    @property
    def moe_args(self):
        if self.n_experts <= 0:
            return None
        if self.router_type == "expert_choice":
            raise ValueError(
                "expert_choice routing is non-causal and unsupported "
                "for the causal LM families; use router_type='topk' "
                "(see nn/moe.py MoEArgs.router)")
        from quintnet_tpu.nn.moe import MoEArgs

        return MoEArgs(n_experts=self.n_experts, top_k=self.expert_top_k,
                       capacity_factor=self.capacity_factor,
                       capacity=self.expert_capacity,
                       aux_weight=self.aux_loss_weight,
                       router=self.router_type)

    @staticmethod
    def llama32_1b() -> "LlamaConfig":
        # vocab_size matches the real Llama-3.2-1B checkpoint (128256);
        # tie_embeddings=True (the default above) also matches 3.2-1B.
        return LlamaConfig(vocab_size=128256, n_positions=131072,
                           rope_scaling=(32.0, 1.0, 4.0, 8192))

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(vocab_size=128256, n_positions=8192, dim=4096,
                           n_layers=32, n_heads=32, n_kv_heads=8,
                           intermediate_size=14336, rope_theta=500000.0,
                           tie_embeddings=False)

    @staticmethod
    def llama_160m() -> "LlamaConfig":
        """GPT-2-base-comparable geometry for cross-family benchmarking
        (not a released Llama size)."""
        return LlamaConfig(vocab_size=32000, n_positions=2048, dim=768,
                           n_layers=12, n_heads=12, n_kv_heads=4,
                           intermediate_size=2048, rope_theta=10000.0,
                           tie_embeddings=True)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        d = dict(vocab_size=128, n_positions=64, dim=32, n_layers=2,
                 n_heads=4, n_kv_heads=2, intermediate_size=64,
                 rope_theta=10000.0, tie_embeddings=False)
        d.update(kw)
        return LlamaConfig(**d)

    @staticmethod
    def from_hf_config(hf) -> "LlamaConfig":
        """Map a transformers LlamaConfig (incl. llama3 rope scaling;
        other rope_type values are rejected loudly rather than silently
        producing wrong rotations)."""
        scaling = None
        rs = getattr(hf, "rope_scaling", None)
        if rs:
            kind = rs.get("rope_type", rs.get("type"))
            if kind != "llama3":
                raise NotImplementedError(
                    f"rope_scaling type {kind!r} not supported "
                    "(llama3 only)")
            scaling = (float(rs["factor"]),
                       float(rs.get("low_freq_factor", 1.0)),
                       float(rs.get("high_freq_factor", 4.0)),
                       int(rs.get("original_max_position_embeddings",
                                  8192)))
        return LlamaConfig(
            vocab_size=hf.vocab_size,
            n_positions=hf.max_position_embeddings,
            dim=hf.hidden_size,
            n_layers=hf.num_hidden_layers,
            n_heads=hf.num_attention_heads,
            n_kv_heads=hf.num_key_value_heads,
            intermediate_size=hf.intermediate_size,
            rope_theta=hf.rope_theta,
            rms_eps=hf.rms_norm_eps,
            tie_embeddings=hf.tie_word_embeddings,
            rope_scaling=scaling,
        )


def llama_upcycle_to_moe(params, cfg: LlamaConfig, key=None):
    """Sparse upcycling: dense Llama params -> SwiGLU-MoE params for a
    config with ``n_experts > 0``. Every expert starts as a copy of the
    dense SwiGLU; routers start near-zero so initial routing is
    ~uniform (same recipe as gpt2_upcycle_to_moe)."""
    if cfg.n_experts <= 0 or "moe" in params["blocks"]:
        return params
    key = key if key is not None else jax.random.key(0)
    E = cfg.n_experts
    blocks = dict(params["blocks"])
    mlp = blocks.pop("mlp")
    L = mlp["gate"]["w"].shape[0]

    def per_expert(x):  # [L, D, H] -> [L, E, D, H]
        return jnp.repeat(x[:, None], E, axis=1)

    blocks["moe"] = {
        "router": {"w": 1e-2 * jax.random.normal(
            key, (L, cfg.dim, E), jnp.float32)},
        "wg": per_expert(mlp["gate"]["w"]),
        "wu": per_expert(mlp["up"]["w"]),
        "wd": per_expert(mlp["down"]["w"]),
    }
    return {**params, "blocks": blocks}


def llama_to_hf_state(params, cfg: LlamaConfig):
    """Inverse of :func:`llama_from_hf_state`: this layout -> an HF
    LlamaForCausalLM state dict of numpy arrays ([out, in] Linear
    weights), loadable via ``model.load_state_dict`` after wrapping in
    torch tensors. Dense configs only (HF has no SwiGLU-MoE Llama)."""
    import numpy as np

    if "moe" in params["blocks"]:
        raise ValueError("HF export supports dense Llama only")
    out = {"model.embed_tokens.weight":
           np.asarray(params["embedding"]["tok"]),
           "model.norm.weight":
           np.asarray(params["head"]["ln_f"]["scale"])}
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.asarray(params["head"]["lm"]["w"]).T
    b = params["blocks"]
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        out[pre + "input_layernorm.weight"] = \
            np.asarray(b["ln1"]["scale"][i])
        out[pre + "post_attention_layernorm.weight"] = \
            np.asarray(b["ln2"]["scale"][i])
        for src, dst in (("q", "self_attn.q_proj"),
                         ("k", "self_attn.k_proj"),
                         ("v", "self_attn.v_proj"),
                         ("o", "self_attn.o_proj")):
            out[pre + dst + ".weight"] = \
                np.asarray(b["attn"][src]["w"][i]).T
        for src, dst in (("gate", "mlp.gate_proj"), ("up", "mlp.up_proj"),
                         ("down", "mlp.down_proj")):
            out[pre + dst + ".weight"] = \
                np.asarray(b["mlp"][src]["w"][i]).T
    return out


def llama3_scaled_inv_freq(cfg: LlamaConfig):
    """Rope inverse frequencies with the llama3 wavelength-dependent
    scaling (HF _compute_llama3_parameters): high-frequency lanes keep
    their period, low-frequency lanes stretch by ``factor``, the band in
    between interpolates smoothly. None scaling -> plain 1/theta^(2i/d).
    Trace-time constant."""
    hd = cfg.head_dim
    inv = 1.0 / (cfg.rope_theta
                 ** (jnp.arange(0, hd, 2, jnp.float32) / hd))
    if cfg.rope_scaling is None:
        return inv
    factor, low_f, high_f, orig_max = cfg.rope_scaling
    low_wavelen = orig_max / low_f
    high_wavelen = orig_max / high_f
    wavelen = 2.0 * math.pi / inv
    smooth = (orig_max / wavelen - low_f) / (high_f - low_f)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    scaled = (1.0 - smooth) * inv / factor + smooth * inv
    out = jnp.where(wavelen > low_wavelen, inv / factor, inv)
    return jnp.where((wavelen <= low_wavelen) & (wavelen >= high_wavelen),
                     scaled, out)


def llama_rope_tables(positions, cfg: LlamaConfig):
    """(cos, sin) for this config at ``positions`` — the single place
    every path (training forward, prefill, decode) gets rope from."""
    return rope_cos_sin(positions, cfg.head_dim, theta=cfg.rope_theta,
                        inv_freq=llama3_scaled_inv_freq(cfg))


def _block_init(key, cfg: LlamaConfig, dtype):
    kq, kk, kv, ko, km = jax.random.split(key, 5)
    d, hd = cfg.dim, cfg.head_dim
    return {
        "ln1": rms_norm_init(d, dtype),
        "attn": {
            "q": linear_init(kq, d, cfg.n_heads * hd, use_bias=False,
                             dtype=dtype),
            "k": linear_init(kk, d, cfg.n_kv_heads * hd, use_bias=False,
                             dtype=dtype),
            "v": linear_init(kv, d, cfg.n_kv_heads * hd, use_bias=False,
                             dtype=dtype),
            "o": linear_init(ko, cfg.n_heads * hd, d, use_bias=False,
                             dtype=dtype),
        },
        "ln2": rms_norm_init(d, dtype),
        **({"moe": moe_init(km, d, cfg.intermediate_size, cfg.n_experts,
                            dtype=dtype, expert_type="swiglu")}
           if cfg.n_experts > 0 else
           {"mlp": swiglu_init(km, d, cfg.intermediate_size, dtype=dtype)}),
    }


def llama_init(key, cfg: LlamaConfig, *, dtype=jnp.float32):
    k_emb, k_blocks, k_head = jax.random.split(key, 3)
    blocks = tree_stack([
        _block_init(bk, cfg, dtype)
        for bk in jax.random.split(k_blocks, cfg.n_layers)])
    params: Dict[str, Any] = {
        "embedding": {"tok": jax.random.normal(
            k_emb, (cfg.table_vocab_size, cfg.dim), dtype) * 0.02},
        "blocks": blocks,
        "head": {"ln_f": rms_norm_init(cfg.dim, dtype)},
    }
    if not cfg.tie_embeddings:
        params["head"]["lm"] = linear_init(
            k_head, cfg.dim, cfg.table_vocab_size, use_bias=False,
            dtype=dtype)
    return params


def llama_qkv(p_attn, a_in, cfg: LlamaConfig, cos, sin, *, tp: int = 1,
              lora=None, lora_scale=None):
    """Projections + rope, shared by training forward, prefill and
    decode: normalized input [B, S, D] -> (q [B, Hq/tp, S, hd] rotated,
    k [B, Hkv/tp, S, hd] rotated, v) — k/v UNrepeated (GQA).

    ``lora``/``lora_scale``: per-slot packed adapters for the serving
    multi-LoRA path (nn/layers.lora_delta) — each present q/k/v target
    adds its low-rank delta on the projection, BEFORE the head reshape
    and rope (exactly where a merged weight would land)."""
    if cfg.n_heads % tp or cfg.n_kv_heads % tp:
        raise ValueError(
            f"tp={tp} must divide n_heads={cfg.n_heads} and "
            f"n_kv_heads={cfg.n_kv_heads} (Megatron head sharding)")
    b, s, _ = a_in.shape
    hd = cfg.head_dim

    def heads(name, n):
        y = quantized_matmul(a_in, p_attn[name])
        if lora is not None and name in lora:
            from quintnet_tpu.nn.layers import lora_delta

            y = y + lora_delta(a_in, lora[name], lora_scale)
        return y.reshape(b, s, n, hd).transpose(0, 2, 1, 3)

    q = apply_rope(heads("q", cfg.n_heads // tp), cos, sin)
    k = apply_rope(heads("k", cfg.n_kv_heads // tp), cos, sin)
    return q, k, heads("v", cfg.n_kv_heads // tp)


def llama_attn_residual(p_attn, x, o, *, tp_axis: Optional[str] = None,
                        lora=None, lora_scale=None):
    """[B, H, S, hd] attention output -> o-proj (+tp psum) + residual.
    ``lora``: an ``o`` target adds its per-slot delta before the psum
    (row-parallel partial sums compose — nn/layers.lora_delta)."""
    b = o.shape[0]
    o = o.transpose(0, 2, 1, 3).reshape(b, o.shape[2], -1)
    y = quantized_matmul(o, p_attn["o"])
    if lora is not None and "o" in lora:
        from quintnet_tpu.nn.layers import lora_delta

        y = y + lora_delta(o, lora["o"], lora_scale)
    if tp_axis is not None:
        y = lax.psum(y, tp_axis)
    return x + y


def llama_mlp_residual(p, x, cfg: LlamaConfig, *,
                       tp_axis: Optional[str] = None,
                       ep_axis: Optional[str] = None,
                       lora=None, lora_scale=None,
                       return_stats: bool = False):
    """-> (x + FFN(ln2(x)), moe_aux) — aux is 0.0 for dense blocks.
    THE one FFN-residual implementation for training forward, prefill
    and decode (a fix here fixes all three). ``lora``: per-slot packed
    gate/up/down adapters (serving multi-LoRA; MoE blocks have no LoRA
    targets and ignore it). ``return_stats`` (serving): widen the
    return to (x, aux, routing_stats_or_None) — the MoE routing-stats
    dict (nn/moe.py moe_apply) the engine's metrics ledger reads."""
    h = rms_norm_apply(p["ln2"], x, eps=cfg.rms_eps)
    if "moe" in p:
        if return_stats:
            y, aux, stats = moe_apply(p["moe"], h, cfg.moe_args,
                                      ep_axis=ep_axis, tp_axis=tp_axis,
                                      return_stats=True)
            return x + y, aux, stats
        y, aux = moe_apply(p["moe"], h, cfg.moe_args, ep_axis=ep_axis,
                           tp_axis=tp_axis)
        return x + y, aux
    out = x + swiglu_apply(p["mlp"], h, tp_axis=tp_axis, lora=lora,
                           lora_scale=lora_scale)
    if return_stats:
        return out, jnp.zeros((), jnp.float32), None
    return out, jnp.zeros((), jnp.float32)


def llama_block_apply(p, x, cfg: LlamaConfig, *, cos, sin,
                      tp_axis: Optional[str] = None,
                      sp_axis: Optional[str] = None, sp_mode: str = "ring",
                      use_flash: bool = False, ep_axis: Optional[str] = None,
                      key=None, segment_ids=None):
    """Returns ``x`` for dense configs, ``(x, aux)`` for MoE (the
    stacked-scan runner's moe path accumulates aux per layer)."""
    del key  # llama has no dropout
    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    a_in = rms_norm_apply(p["ln1"], x, eps=cfg.rms_eps)
    q, k, v = llama_qkv(p["attn"], a_in, cfg, cos, sin, tp=tp)
    rep = q.shape[1] // k.shape[1]
    k, v = repeat_kv(k, rep), repeat_kv(v, rep)

    if sp_axis is not None:
        from quintnet_tpu.ops.ring_attention import (ring_attention,
                                                     zigzag_ring_attention)
        from quintnet_tpu.ops.ulysses_attention import ulysses_attention

        if sp_mode == "ulysses":
            o = ulysses_attention(q, k, v, axis=sp_axis, causal=True,
                                  use_flash=use_flash,
                                  segment_ids=segment_ids)
        elif sp_mode == "zigzag":
            o = zigzag_ring_attention(q, k, v, axis=sp_axis, causal=True,
                                      segment_ids=segment_ids)
        else:
            o = ring_attention(q, k, v, axis=sp_axis, causal=True,
                               segment_ids=segment_ids)
    elif use_flash:
        from quintnet_tpu.ops.flash_attention import flash_attention

        o = flash_attention(q, k, v, causal=True, segment_ids=segment_ids)
    else:
        o = sdpa(q, k, v, causal=True, segment_ids=segment_ids)

    x = llama_attn_residual(p["attn"], x, o, tp_axis=tp_axis)
    x, aux = llama_mlp_residual(p, x, cfg, tp_axis=tp_axis,
                                ep_axis=ep_axis)
    # runner pmeans the aux sum over sp (stacked_blocks_apply moe path)
    return (x, aux) if cfg.n_experts > 0 else x


def llama_block_prefill(p, x, cfg: LlamaConfig, cos, sin,
                        tp_axis: Optional[str] = None):
    """Causal block forward that also returns this layer's UNrepeated
    (k, v) [B, Hkv(/tp), S, hd] for the decode cache. Under ``tp_axis``
    heads are LOCAL (head-sharded cache) with the RowParallel psum in
    the residual."""
    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    a_in = rms_norm_apply(p["ln1"], x, eps=cfg.rms_eps)
    q, k, v = llama_qkv(p["attn"], a_in, cfg, cos, sin, tp=tp)
    rep = q.shape[1] // k.shape[1]
    o = sdpa(q, repeat_kv(k, rep), repeat_kv(v, rep), causal=True)
    x = llama_attn_residual(p["attn"], x, o, tp_axis=tp_axis)
    x, _aux = llama_mlp_residual(p, x, cfg, tp_axis=tp_axis)
    return x, (k, v)


def llama_block_prefill_paged(p, x, kc, vc, positions, tail_len,
                              cfg: LlamaConfig, cos, sin,
                              tp_axis: Optional[str] = None,
                              ep_axis: Optional[str] = None,
                              block_tables=None,
                              block_size: Optional[int] = None,
                              lora=None, lora_scale=None,
                              kv_scales=None, policy=None,
                              attn_kernel: str = "xla"):
    """Chunked prefill over the paged pool (the serve engine's
    prefix-cached path): x [1, P, D] tail hidden states at absolute
    ``positions`` [P], caches are flat pool views
    [N_blocks*block_size, Hkv(/tp), hd]. The tail's UNrepeated (k, v)
    scatter through the request's ``block_tables`` row [M]; attention
    gathers the whole row back — cached prefix blocks + fresh tail —
    and masks causally against absolute positions (exactly
    :func:`llama_block_decode`'s paged math, batched over the tail).
    ``cos``/``sin`` [P, hd] must be built from the SAME absolute
    positions. ``lora``/``lora_scale``: this layer's packed per-slot
    adapters (serving multi-LoRA). ``kv_scales``/``policy``: scaled KV
    layout (serve/kv_quant.py) — dequantized gathered view, quantize on
    scatter. Returns (x, (kc, vc[, k_scale, v_scale])).
    ``attn_kernel="pallas"``: the fused block-table-walking kernel
    (ops/paged_attention.py) — same contract as
    nn/attention.mha_prefill_paged's dispatch."""
    from quintnet_tpu.nn.attention import (_gather_kv, _quant_span,
                                           paged_prefill_update,
                                           paged_quant_update)

    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    attn_lora = lora.get("attn") if lora is not None else None
    a_in = rms_norm_apply(p["ln1"], x, eps=cfg.rms_eps)
    q, k, v = llama_qkv(p["attn"], a_in, cfg, cos, sin, tp=tp,
                        lora=attn_lora, lora_scale=lora_scale)
    if attn_kernel == "pallas":
        tables = block_tables[None]
        if kv_scales is None:
            from quintnet_tpu.ops.paged_attention import paged_attention

            kc, vc = paged_prefill_update(kc, vc, k[0], v[0], positions,
                                          tail_len,
                                          block_tables=block_tables,
                                          block_size=block_size)
            o = paged_attention(q, kc, vc, tables, positions[:1],
                                block_size=block_size)
            pools = (kc, vc)
        else:
            from quintnet_tpu.nn.attention import _paged_attention_scaled

            ks, vs = kv_scales
            o, kc, vc, ks, vs = _paged_attention_scaled(
                policy, kc, vc, ks, vs, q, k, v, positions[None, :],
                jnp.reshape(tail_len, (1,)), tables,
                block_size=block_size,
                max_blocks=_quant_span(positions.shape[0], block_size,
                                       block_tables.shape[0]))
            pools = (kc, vc, ks, vs)
    else:
        if kv_scales is None:
            kc, vc = paged_prefill_update(kc, vc, k[0], v[0], positions,
                                          tail_len,
                                          block_tables=block_tables,
                                          block_size=block_size)
            kg, vg = _gather_kv(kc, vc, None, policy,
                                block_tables[None],
                                block_size=block_size)
            pools = (kc, vc)
        else:
            ks, vs = kv_scales
            tables = block_tables[None]
            kg, vg = _gather_kv(kc, vc, (ks, vs), policy, tables,
                                block_size=block_size)
            span = _quant_span(positions.shape[0], block_size,
                               block_tables.shape[0])
            pos2 = positions[None, :]
            lens = jnp.reshape(tail_len, (1,))
            kc, ks, kg = paged_quant_update(
                policy, kc, ks, kg, k, pos2, lens, block_tables=tables,
                block_size=block_size, max_blocks=span)
            vc, vs, vg = paged_quant_update(
                policy, vc, vs, vg, v, pos2, lens, block_tables=tables,
                block_size=block_size, max_blocks=span)
            pools = (kc, vc, ks, vs)
        rep = q.shape[1] // kg.shape[1]
        kf, vf = repeat_kv(kg, rep), repeat_kv(vg, rep)
        valid = (jnp.arange(kf.shape[2])[None, :]
                 <= positions[:, None])[None, None]      # [1,1,P,M*bs]
        scores = (jnp.einsum("bhqd,bhtd->bhqt", q,
                             kf).astype(jnp.float32)
                  / math.sqrt(cfg.head_dim))
        scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
        o = jnp.einsum("bhqt,bhtd->bhqd",
                       jax.nn.softmax(scores,
                                      axis=-1).astype(q.dtype), vf)
    x = llama_attn_residual(p["attn"], x, o, tp_axis=tp_axis,
                            lora=attn_lora, lora_scale=lora_scale)
    x, _aux, stats = llama_mlp_residual(
        p, x, cfg, tp_axis=tp_axis, ep_axis=ep_axis,
        lora=lora.get("mlp") if lora is not None else None,
        lora_scale=lora_scale, return_stats=True)
    if "moe" in p:
        return x, (*pools, stats)
    return x, pools


def llama_block_prefill_paged_sp(p, x, kc, vc, start, t0,
                                 cfg: LlamaConfig, cos, sin, *,
                                 sp_axis: str,
                                 tp_axis: Optional[str] = None,
                                 block_tables=None,
                                 block_size: Optional[int] = None,
                                 kv_scales=None, policy=None):
    """Sequence-parallel chunked prefill block (the serve engine's
    long-context path): x [1, Pl, D] is this sp rank's slice of the
    chunk's hidden states; ``cos``/``sin`` [Pl, hd] must be built from
    the rank's LOCAL absolute positions (``start + rank*Pl +
    arange(Pl)``) so rope lands exactly where the dense path puts it.
    Attention runs through nn/attention.ring_paged_prefill — K/V
    sharded over ``sp_axis`` during the score pass (GQA UNrepeated on
    the wire), reassembled by one all_gather for the sp-replicated pool
    scatter. Returns (x, (kc, vc[, k_scale, v_scale]))."""
    from quintnet_tpu.nn.attention import ring_paged_prefill

    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    a_in = rms_norm_apply(p["ln1"], x, eps=cfg.rms_eps)
    q, k, v = llama_qkv(p["attn"], a_in, cfg, cos, sin, tp=tp)
    out = ring_paged_prefill(
        q, k, v, start, t0, kc, vc, sp_axis=sp_axis,
        block_tables=block_tables, block_size=block_size,
        kv_scales=kv_scales, policy=policy)
    x = llama_attn_residual(p["attn"], x, out[0], tp_axis=tp_axis)
    x, _aux = llama_mlp_residual(p, x, cfg, tp_axis=tp_axis)
    return x, out[1:]


def llama_block_verify_paged(p, x, kc, vc, positions, tail_lens,
                             cfg: LlamaConfig, cos, sin,
                             tp_axis: Optional[str] = None,
                             ep_axis: Optional[str] = None,
                             block_tables=None,
                             block_size: Optional[int] = None,
                             lora=None, lora_scale=None,
                             kv_scales=None, policy=None,
                             attn_kernel: str = "xla"):
    """Batched draft-verify block step over the paged pool (the serve
    engine's speculative-decode scoring path, serve/spec.py): x
    [S, P, D] per-slot token runs at absolute ``positions`` [S, P],
    caches are flat pool views [N_blocks*block_size, Hkv(/tp), hd].
    Every row's UNrepeated (k, v) run scatters through its
    ``block_tables`` row (pad columns masked to the null block by
    ``tail_lens``); attention gathers each row's whole history back and
    masks causally against absolute positions — exactly
    :func:`llama_block_decode`'s paged math widened from 1 to P tokens
    per row. ``cos``/``sin`` [S, 1, P, hd] must be built from the SAME
    absolute positions. ``lora``/``lora_scale``: this layer's packed
    per-slot adapters. ``kv_scales``/``policy``: scaled KV layout
    (serve/kv_quant.py). Returns (x, (kc, vc[, k_scale, v_scale])).
    ``attn_kernel="pallas"``: the fused block-table-walking kernel
    (ops/paged_attention.py), batched over rows."""
    from quintnet_tpu.nn.attention import (_gather_kv, _quant_span,
                                           paged_quant_update,
                                           paged_verify_update)

    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    attn_lora = lora.get("attn") if lora is not None else None
    a_in = rms_norm_apply(p["ln1"], x, eps=cfg.rms_eps)
    q, k, v = llama_qkv(p["attn"], a_in, cfg, cos, sin, tp=tp,
                        lora=attn_lora, lora_scale=lora_scale)
    if attn_kernel == "pallas":
        if kv_scales is None:
            from quintnet_tpu.ops.paged_attention import paged_attention

            kc, vc = paged_verify_update(kc, vc, k, v, positions,
                                         tail_lens,
                                         block_tables=block_tables,
                                         block_size=block_size)
            o = paged_attention(q, kc, vc, block_tables,
                                positions[:, 0], block_size=block_size)
            pools = (kc, vc)
        else:
            from quintnet_tpu.nn.attention import _paged_attention_scaled

            ks, vs = kv_scales
            o, kc, vc, ks, vs = _paged_attention_scaled(
                policy, kc, vc, ks, vs, q, k, v, positions, tail_lens,
                block_tables, block_size=block_size,
                max_blocks=_quant_span(positions.shape[1], block_size,
                                       block_tables.shape[1]))
            pools = (kc, vc, ks, vs)
    else:
        if kv_scales is None:
            kc, vc = paged_verify_update(kc, vc, k, v, positions,
                                         tail_lens,
                                         block_tables=block_tables,
                                         block_size=block_size)
            kg, vg = _gather_kv(kc, vc, None, policy, block_tables,
                                block_size=block_size)
            pools = (kc, vc)
        else:
            ks, vs = kv_scales
            kg, vg = _gather_kv(kc, vc, (ks, vs), policy, block_tables,
                                block_size=block_size)
            span = _quant_span(positions.shape[1], block_size,
                               block_tables.shape[1])
            kc, ks, kg = paged_quant_update(
                policy, kc, ks, kg, k, positions, tail_lens,
                block_tables=block_tables, block_size=block_size,
                max_blocks=span)
            vc, vs, vg = paged_quant_update(
                policy, vc, vs, vg, v, positions, tail_lens,
                block_tables=block_tables, block_size=block_size,
                max_blocks=span)
            pools = (kc, vc, ks, vs)
        rep = q.shape[1] // kg.shape[1]
        kf, vf = repeat_kv(kg, rep), repeat_kv(vg, rep)
        valid = (jnp.arange(kf.shape[2])[None, None, :]
                 <= positions[:, :, None])[:, None]   # [S, 1, P, M*bs]
        scores = (jnp.einsum("bhqd,bhtd->bhqt", q,
                             kf).astype(jnp.float32)
                  / math.sqrt(cfg.head_dim))
        scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
        o = jnp.einsum("bhqt,bhtd->bhqd",
                       jax.nn.softmax(scores,
                                      axis=-1).astype(q.dtype), vf)
    x = llama_attn_residual(p["attn"], x, o, tp_axis=tp_axis,
                            lora=attn_lora, lora_scale=lora_scale)
    x, _aux, stats = llama_mlp_residual(
        p, x, cfg, tp_axis=tp_axis, ep_axis=ep_axis,
        lora=lora.get("mlp") if lora is not None else None,
        lora_scale=lora_scale, return_stats=True)
    if "moe" in p:
        return x, (*pools, stats)
    return x, pools


def llama_block_decode(p, x, kc, vc, pos, cfg: LlamaConfig, cos, sin,
                       tp_axis: Optional[str] = None,
                       ep_axis: Optional[str] = None,
                       block_tables=None, block_size: Optional[int] = None,
                       lora=None, lora_scale=None,
                       kv_scales=None, policy=None,
                       attn_kernel: str = "xla"):
    """One cached token: x [B, 1, D], caches [B, Hkv(/tp), T, hd] ->
    (x, updated caches). Masked attention over cache[:pos].

    Paged path (``block_tables``/``block_size`` set, quintnet_tpu/serve):
    caches are flat pool views [N_blocks*block_size, Hkv(/tp), hd]
    shared across requests, ``pos`` is a [B] vector, and the caller
    supplies per-row rope tables (cos/sin [B, 1, 1, hd]). The cache
    stays UNrepeated either way — kv-head repeat happens on the
    gathered view. ``lora``/``lora_scale``: this layer's packed
    per-slot adapters (multi-tenant LoRA serving). ``kv_scales``/
    ``policy``: scaled KV layout (serve/kv_quant.py; paged path only) —
    the update tuple grows to (kc, vc, k_scale, v_scale)."""
    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    attn_lora = lora.get("attn") if lora is not None else None
    a_in = rms_norm_apply(p["ln1"], x, eps=cfg.rms_eps)
    q, k, v = llama_qkv(p["attn"], a_in, cfg, cos, sin, tp=tp,
                        lora=attn_lora, lora_scale=lora_scale)
    pools = None
    kf = None
    if block_tables is None:
        if kv_scales is not None:
            raise ValueError(
                "scaled KV layout policies exist only for the paged "
                "pool (block_tables is required)")
        if attn_kernel != "xla":
            raise ValueError(
                "attn_kernel='pallas' exists only for the paged pool "
                "(block_tables is required)")
        kc = lax.dynamic_update_slice_in_dim(kc, k.astype(kc.dtype), pos,
                                             axis=2)
        vc = lax.dynamic_update_slice_in_dim(vc, v.astype(vc.dtype), pos,
                                             axis=2)
        rep = q.shape[1] // kc.shape[1]
        kf, vf = repeat_kv(kc, rep), repeat_kv(vc, rep)
        valid = jnp.arange(kf.shape[2])[None, None, None, :] <= pos
    elif attn_kernel == "pallas":
        if kv_scales is None:
            from quintnet_tpu.nn.attention import paged_cache_update
            from quintnet_tpu.ops.paged_attention import paged_attention

            kc, vc = paged_cache_update(
                kc, vc, k[:, :, 0].astype(kc.dtype),
                v[:, :, 0].astype(vc.dtype), pos,
                block_tables=block_tables, block_size=block_size)
            o = paged_attention(q, kc, vc, block_tables, pos,
                                block_size=block_size)
        else:
            from quintnet_tpu.nn.attention import _paged_attention_scaled

            ks, vs = kv_scales
            o, kc, vc, ks, vs = _paged_attention_scaled(
                policy, kc, vc, ks, vs, q, k, v, pos[:, None],
                jnp.ones(pos.shape, jnp.int32), block_tables,
                block_size=block_size, max_blocks=1)
            pools = (kc, vc, ks, vs)
    elif kv_scales is None:
        from quintnet_tpu.nn.attention import (_gather_kv,
                                               paged_cache_update)

        kc, vc = paged_cache_update(
            kc, vc, k[:, :, 0].astype(kc.dtype), v[:, :, 0].astype(vc.dtype),
            pos, block_tables=block_tables, block_size=block_size)
        kg, vg = _gather_kv(kc, vc, None, policy, block_tables,
                            block_size=block_size)
        rep = q.shape[1] // kg.shape[1]
        kf, vf = repeat_kv(kg, rep), repeat_kv(vg, rep)
        valid = (jnp.arange(kf.shape[2])[None, :]
                 <= pos[:, None])[:, None, None, :]
    else:
        from quintnet_tpu.nn.attention import (_gather_kv,
                                               paged_quant_update)

        ks, vs = kv_scales
        kg, vg = _gather_kv(kc, vc, (ks, vs), policy, block_tables,
                            block_size=block_size)
        ones = jnp.ones(pos.shape, jnp.int32)
        kc, ks, kg = paged_quant_update(
            policy, kc, ks, kg, k, pos[:, None], ones,
            block_tables=block_tables, block_size=block_size,
            max_blocks=1)
        vc, vs, vg = paged_quant_update(
            policy, vc, vs, vg, v, pos[:, None], ones,
            block_tables=block_tables, block_size=block_size,
            max_blocks=1)
        pools = (kc, vc, ks, vs)
        rep = q.shape[1] // kg.shape[1]
        kf, vf = repeat_kv(kg, rep), repeat_kv(vg, rep)
        valid = (jnp.arange(kf.shape[2])[None, :]
                 <= pos[:, None])[:, None, None, :]
    if kf is not None:
        scores = (jnp.einsum("bhqd,bhtd->bhqt", q,
                             kf).astype(jnp.float32)
                  / math.sqrt(cfg.head_dim))
        scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
        o = jnp.einsum("bhqt,bhtd->bhqd",
                       jax.nn.softmax(scores,
                                      axis=-1).astype(q.dtype), vf)
    x = llama_attn_residual(p["attn"], x, o, tp_axis=tp_axis,
                            lora=attn_lora, lora_scale=lora_scale)
    x, _aux, stats = llama_mlp_residual(
        p, x, cfg, tp_axis=tp_axis, ep_axis=ep_axis,
        lora=lora.get("mlp") if lora is not None else None,
        lora_scale=lora_scale, return_stats=True)
    out_pools = pools if pools is not None else (kc, vc)
    if "moe" in p:
        return x, (*out_pools, stats)
    return x, out_pools


def _positions(b, s, sp_axis: Optional[str]):
    """Global position ids for the local sequence shard (sp offsets the
    shard like gpt2_embed's wpe lookup; rope must see global positions)."""
    pos = jnp.arange(s)
    if sp_axis is not None:
        pos = pos + lax.axis_index(sp_axis) * s
    return pos


def llama_hidden(params, input_ids, cfg: LlamaConfig, *,
                 tp_axis: Optional[str] = None,
                 sp_axis: Optional[str] = None, sp_mode: str = "ring",
                 ep_axis: Optional[str] = None,
                 remat: "bool | str" = False, use_flash: bool = False,
                 fsdp=None):
    """-> (final hidden states, moe aux total — 0.0 for dense)."""
    b, s = input_ids.shape
    if cfg.vocab_parallel and tp_axis is not None:
        from quintnet_tpu.parallel.tp import vocab_parallel_embedding

        h = vocab_parallel_embedding(
            {"table": params["embedding"]["tok"]}, input_ids,
            axis=tp_axis)
    else:
        h = jnp.take(params["embedding"]["tok"], input_ids, axis=0)
    cos, sin = llama_rope_tables(_positions(b, s, sp_axis), cfg)
    import functools

    from quintnet_tpu.models.gpt2 import segment_ids_from_input

    seg = segment_ids_from_input(input_ids, cfg, sp_axis=sp_axis)
    body = functools.partial(llama_block_apply, cfg=cfg, cos=cos, sin=sin,
                             tp_axis=tp_axis, sp_axis=sp_axis,
                             sp_mode=sp_mode, use_flash=use_flash,
                             ep_axis=ep_axis, segment_ids=seg)
    out = stacked_blocks_apply(
        params["blocks"], h, num_heads=0, body_fn=body, remat=remat,
        moe_args=cfg.moe_args, sp_axis=sp_axis,
        scan_unroll=cfg.scan_unroll, fsdp=fsdp)
    return out if cfg.n_experts > 0 else (out, jnp.zeros((), jnp.float32))


def llama_logits(params, h, cfg: LlamaConfig):
    """ln_f + lm head (tied: tok.T). With a padded vocab and a
    FULL-width table the padding columns are -inf-masked (single-device
    / no-tp fallback of a vocab_parallel config); vocab-SHARDED tables
    are masked inside clm_loss_vp, which knows the shard offset (same
    split of responsibilities as models/gpt2.py gpt2_logits)."""
    h = rms_norm_apply(params["head"]["ln_f"], h, eps=cfg.rms_eps)
    w = (params["embedding"]["tok"].T if cfg.tie_embeddings
         else params["head"]["lm"]["w"])
    logits = jnp.dot(h, w).astype(jnp.float32)
    if (cfg.padded_vocab_size
            and logits.shape[-1] == cfg.table_vocab_size):
        logits = mask_padded_cols(logits, cfg)
    return logits


def llama_apply(params, input_ids, cfg: LlamaConfig, *,
                tp_axis: Optional[str] = None,
                sp_axis: Optional[str] = None, sp_mode: str = "ring",
                ep_axis: Optional[str] = None,
                remat: "bool | str" = False, use_flash: bool = False):
    h, _aux = llama_hidden(params, input_ids, cfg, tp_axis=tp_axis,
                           sp_axis=sp_axis, sp_mode=sp_mode,
                           ep_axis=ep_axis, remat=remat,
                           use_flash=use_flash)
    return llama_logits(params, h, cfg)


# ---------------------------------------------------------------------------
# sharding / strategy integration

def llama_partition_specs(cfg: Optional[LlamaConfig] = None, *,
                          tp_axis: Optional[str] = "tp",
                          pp_axis: Optional[str] = None,
                          ep_axis: Optional[str] = None,
                          fsdp_axis: Optional[str] = None):
    from jax.sharding import PartitionSpec as P

    t = tp_axis
    col = P(pp_axis, None, t)     # [L, in, out/tp]
    row = P(pp_axis, t, None)     # [L, in/tp, out]
    rep = P(pp_axis, None)
    blocks = {
        "ln1": {"scale": rep},
        "attn": {"q": {"w": col}, "k": {"w": col}, "v": {"w": col},
                 "o": {"w": row}},
        "ln2": {"scale": rep},
    }
    if cfg is not None and cfg.n_experts > 0:
        blocks["moe"] = moe_specs(ep_axis=ep_axis, tp_axis=t,
                                  stacked=True, pp_axis=pp_axis,
                                  expert_type="swiglu")
    else:
        blocks["mlp"] = {"gate": {"w": col}, "up": {"w": col},
                         "down": {"w": row}}
    if fsdp_axis is not None:
        from quintnet_tpu.parallel.tp import fsdp_shard_specs

        blocks = fsdp_shard_specs(blocks, fsdp_axis)
    vp = cfg is not None and cfg.vocab_parallel and tp_axis is not None
    specs = {
        # vp: vocab dim sharded over tp; grads stay un-psummed over tp
        # (train_step.py reduce_grads spec rule) — the vp loss/embed
        # psums supply the tp cotangent factor exactly once
        "embedding": {"tok": P(t, None) if vp else P()},
        "blocks": blocks,
        "head": {"ln_f": {"scale": P()}},
    }
    if cfg is None or not cfg.tie_embeddings:
        specs["head"]["lm"] = {"w": P(None, t) if vp else P()}
    return specs


def _validate_tp(cfg: LlamaConfig, tp: int, params):
    """Separate q/k/v need no qkv re-blocking (identity layout); this
    hook just validates the vp divisibility constraint with a clear
    message before shard_params hits an opaque partition error."""
    if cfg.vocab_parallel and tp > 1 and cfg.table_vocab_size % tp != 0:
        raise ValueError(
            f"vocab_parallel needs (padded_)vocab_size % tp == 0; got "
            f"{cfg.table_vocab_size} % {tp}. Set padded_vocab_size; "
            f"padded columns are masked out of the loss.")
    return params


def llama_model_spec(cfg: LlamaConfig, *, remat: "bool | str" = False,
                     use_flash: bool = False, sp_mode: str = "ring",
                     compute_dtype=None):
    from jax.sharding import PartitionSpec as P

    from quintnet_tpu.parallel.strategy import ModelSpec

    def cast(p):
        return cast_floating(p, compute_dtype) if compute_dtype else p

    def loss_fn(params, batch, tp_axis=None, sp_axis=None, ep_axis=None,
                key=None, fsdp_axis=None):
        del key
        input_ids, labels = batch
        import functools as _ft

        from quintnet_tpu.parallel.tp import fsdp_info

        fsdp = fsdp_info(_ft.partial(llama_partition_specs, cfg),
                         fsdp_axis, tp_axis=tp_axis, ep_axis=ep_axis)
        h, aux = llama_hidden(cast(params), input_ids, cfg,
                              tp_axis=tp_axis, sp_axis=sp_axis,
                              sp_mode=sp_mode, ep_axis=ep_axis,
                              remat=remat, use_flash=use_flash, fsdp=fsdp)
        logits = llama_logits(cast(params), h, cfg)
        if cfg.vocab_parallel and tp_axis is not None:
            return clm_loss_vp(
                logits, labels, tp_axis=tp_axis, sp_axis=sp_axis,
                vocab_size=(cfg.vocab_size if cfg.padded_vocab_size
                            else None)) + aux
        if sp_axis is not None:
            return clm_loss_sp(logits, labels, sp_axis=sp_axis) + aux
        return clm_loss(logits, labels) + aux

    def pipeline_fns(tp_axis=None, sp_axis=None, ep_axis=None):
        if cfg.segment_eos_id is not None:
            raise NotImplementedError(
                "segment_eos_id under pipeline parallelism is not wired "
                "(stage fns receive hidden states, not token ids); use "
                "dp/tp/ep meshes for packed-document isolation")

        def embed_fn(params, input_ids, key=None):
            del key
            tok = cast(params)["embedding"]["tok"]
            if cfg.vocab_parallel and tp_axis is not None:
                from quintnet_tpu.parallel.tp import \
                    vocab_parallel_embedding

                return vocab_parallel_embedding({"table": tok}, input_ids,
                                                axis=tp_axis)
            return jnp.take(tok, input_ids, axis=0)

        def stage_fn(blocks_local, h, key=None):
            del key
            b, s = h.shape[:2]
            cos, sin = llama_rope_tables(_positions(b, s, sp_axis),
                                         cfg)
            import functools

            body = functools.partial(
                llama_block_apply, cfg=cfg, cos=cos, sin=sin,
                tp_axis=tp_axis, sp_axis=sp_axis, sp_mode=sp_mode,
                use_flash=use_flash, ep_axis=ep_axis)
            return stacked_blocks_apply(cast(blocks_local), h, num_heads=0,
                                        body_fn=body, remat=remat,
                                        moe_args=cfg.moe_args,
                                        sp_axis=sp_axis,
                                        scan_unroll=cfg.scan_unroll)

        vp = cfg.vocab_parallel and tp_axis is not None
        if vp or sp_axis is not None:
            # the loss contains collectives (vp lse psums / sp
            # shift+psum), which may not sit inside the schedules'
            # lax.cond gate — split as gpt2_pipeline_fns does
            from quintnet_tpu.parallel.pp import SplitHead

            def head_reduce_fn(logits, labels, valid):
                if vp:
                    loss = clm_loss_vp(
                        logits, labels, tp_axis=tp_axis, sp_axis=sp_axis,
                        vocab_size=(cfg.vocab_size if cfg.padded_vocab_size
                                    else None))
                else:
                    loss = clm_loss_sp(logits, labels, sp_axis=sp_axis)
                return jnp.where(valid, loss, 0.0)

            return embed_fn, stage_fn, SplitHead(
                lambda params, h, labels: llama_logits(cast(params), h, cfg),
                head_reduce_fn)

        def head_loss_fn(params, h, labels):
            return clm_loss(llama_logits(cast(params), h, cfg), labels)

        return embed_fn, stage_fn, head_loss_fn

    def batch_specs(batch_axes, sp_axis=None):
        spec = P(tuple(batch_axes) if batch_axes else None, sp_axis)
        return (spec, spec)

    return ModelSpec(
        init=lambda key: llama_init(key, cfg),
        loss_fn=loss_fn,
        partition_specs=lambda tp_axis=None, pp_axis=None, ep_axis=None, \
                fsdp_axis=None:
            llama_partition_specs(cfg, tp_axis=tp_axis, pp_axis=pp_axis,
                                  ep_axis=ep_axis, fsdp_axis=fsdp_axis),
        pipeline_fns=pipeline_fns,
        to_tp_layout=lambda p, tp: _validate_tp(cfg, tp, p),
        depth=cfg.n_layers,
        batch_specs=batch_specs,
        needs_rng=False,
    )


# ---------------------------------------------------------------------------
# HF interop

def llama_from_hf_state(state: Dict[str, Any], cfg: LlamaConfig):
    """HF LlamaForCausalLM state dict (torch tensors or arrays, Linear
    weights [out, in]) -> this layout ([in, out], stacked blocks)."""
    import numpy as np

    def t(name):
        return np.asarray(state[name].detach().cpu().numpy()
                          if hasattr(state[name], "detach")
                          else state[name])

    blocks = []
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        blocks.append({
            "ln1": {"scale": t(pre + "input_layernorm.weight")},
            "attn": {
                "q": {"w": t(pre + "self_attn.q_proj.weight").T},
                "k": {"w": t(pre + "self_attn.k_proj.weight").T},
                "v": {"w": t(pre + "self_attn.v_proj.weight").T},
                "o": {"w": t(pre + "self_attn.o_proj.weight").T},
            },
            "ln2": {"scale": t(pre + "post_attention_layernorm.weight")},
            "mlp": {
                "gate": {"w": t(pre + "mlp.gate_proj.weight").T},
                "up": {"w": t(pre + "mlp.up_proj.weight").T},
                "down": {"w": t(pre + "mlp.down_proj.weight").T},
            },
        })
    params = {
        "embedding": {"tok": t("model.embed_tokens.weight")},
        "blocks": tree_stack([jax.tree.map(jnp.asarray, b)
                              for b in blocks]),
        "head": {"ln_f": {"scale": t("model.norm.weight")}},
    }
    if not cfg.tie_embeddings:
        params["head"]["lm"] = {"w": t("lm_head.weight").T}
    return jax.tree.map(jnp.asarray, params)

"""Model zoo: ViT (MNIST-scale) and GPT-2 families, as init/apply pairs."""

from quintnet_tpu.models import vit

__all__ = ["vit"]

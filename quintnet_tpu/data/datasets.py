"""Datasets: MNIST, CSV summarization, and synthetic fallbacks.

Reference equivalents: utils/Dataloader.py (CustomDataset for HF arrow
MNIST + mnist_transform :179-214; SummarizationDataset/Collator
:216-319). This environment has no network egress and no HF datasets
package, so loaders read local files when present and fall back to
deterministic synthetic data otherwise (clearly flagged) — throughput
benchmarks and schedule-equivalence tests do not depend on real pixels.

Batching is plain host numpy; devices receive batches via
``Strategy.shard_batch`` (the DistributedSampler role —
examples/full_3d.py:129-155 — is subsumed by batch sharding over dp).
"""

from __future__ import annotations

import gzip
import os
import struct
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}


def _read_idx(path: str) -> np.ndarray:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(dims)


def load_mnist(data_dir: Optional[str] = None, *, split: str = "train",
               synthetic_ok: bool = True,
               synthetic_size: int = 4096) -> Tuple[np.ndarray, np.ndarray]:
    """(images [N,28,28,1] float32 normalised, labels [N] int32).

    Looks for IDX(.gz) files or mnist.npz under ``data_dir`` (or
    $QT_DATA_DIR, ./data); falls back to a deterministic synthetic set of
    class-dependent patterns when allowed.
    Normalisation matches the reference's transform (mean .1307/std .3081,
    utils/Dataloader.py:179-214).
    """
    candidates = [d for d in (data_dir, os.environ.get("QT_DATA_DIR"),
                              "data", os.path.expanduser("~/.cache/mnist"))
                  if d]
    for d in candidates:
        npz = os.path.join(d, "mnist.npz")
        if os.path.exists(npz):
            z = np.load(npz)
            x = z["x_train" if split == "train" else "x_test"]
            y = z["y_train" if split == "train" else "y_test"]
            return _norm(x), y.astype(np.int32)
        img = os.path.join(
            d, MNIST_FILES[f"{'train' if split == 'train' else 'test'}_images"])
        lbl = os.path.join(
            d, MNIST_FILES[f"{'train' if split == 'train' else 'test'}_labels"])
        for im, lb in ((img, lbl), (img[:-3], lbl[:-3])):  # .gz / plain
            if os.path.exists(im) and os.path.exists(lb):
                return _norm(_read_idx(im)), _read_idx(lb).astype(np.int32)

    if not synthetic_ok:
        raise FileNotFoundError(
            f"MNIST not found under {candidates}; place mnist.npz or IDX "
            "files there, or allow synthetic_ok")
    return synthetic_mnist(synthetic_size, seed=0 if split == "train" else 1)


def _norm(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.float32) / 255.0
    x = (x - 0.1307) / 0.3081
    return x.reshape(x.shape[0], 28, 28, 1)


def synthetic_mnist(n: int, *, seed: int = 0,
                    signal: Tuple[float, float] = (0.06, 0.55),
                    noise: float = 1.0) -> Tuple[np.ndarray, np.ndarray]:
    """Learnable-but-hard stand-in: each class is a fixed random 28x28
    prototype scaled by a PER-SAMPLE amplitude drawn from
    ``U[signal[0], signal[1]]``, plus unit Gaussian noise.

    The variable amplitude mimics real MNIST's easy-majority/hard-tail
    structure: high-amplitude samples are learned in the first epoch,
    the low-amplitude tail only as the model refines its estimate of the
    prototype directions — so a 10-epoch run traces a real learning
    curve (~57% epoch 1 -> ~90% epoch 10 for the reference ViT widths)
    rather than saturating at 1.0 in epoch 0, and the Bayes-optimal
    ceiling (nearest-prototype rule, measured over 40k samples) sits at
    ~96%, near the reference's real-MNIST 93.24% val acc
    (/root/reference/README.md:214). The previous constant-amplitude
    design (signal 1.0, noise 0.8) was linearly separable in practice
    and its parity artifacts showed sharding identity but no learning
    trajectory."""
    protos = np.random.default_rng(42).normal(
        size=(10, 28, 28, 1)).astype(np.float32)  # shared across splits
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    amp = rng.uniform(signal[0], signal[1],
                      size=(n, 1, 1, 1)).astype(np.float32)
    eps = rng.normal(scale=noise, size=(n, 28, 28, 1)).astype(np.float32)
    return protos[labels] * amp + eps, labels


@dataclass
class ArrayDataset:
    """In-memory (x, y) pairs with shuffling epochs."""

    x: np.ndarray
    y: np.ndarray

    def __len__(self):
        return len(self.x)


def make_batches(ds: ArrayDataset, batch_size: int, *, seed: int = 0,
                 shuffle: bool = True, drop_last: bool = True,
                 start_batch: int = 0) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Simple epoch iterator. Batches are GLOBAL; sharding over dp happens
    on device via Strategy.shard_batch (so a mid-epoch resume needs no
    per-host bookkeeping — every process sees the same global stream).

    ``start_batch`` skips the first K batches by index arithmetic over
    the (seeded, already-shuffled) permutation — the skip-to-cursor path
    for step-granular resume (quintnet_tpu/ft/): no skipped sample is
    ever materialised, and batch ``start_batch + n`` is bit-identical to
    batch ``start_batch + n`` of a fresh epoch."""
    idx = np.arange(len(ds))
    if shuffle:
        np.random.default_rng(seed).shuffle(idx)
    end = len(idx) - (len(idx) % batch_size) if drop_last else len(idx)
    for i in range(start_batch * batch_size, end, batch_size):
        j = idx[i:i + batch_size]
        yield ds.x[j], ds.y[j]


def skip_batches(batches: Iterator, n: int) -> Iterator:
    """Generic skip-to-cursor for arbitrary batch iterables: consume and
    discard the first ``n`` batches (each IS materialised — correct for
    any iterator, including streaming ones, but pays the host data
    cost). Map-style datasets should prefer their ``start_batch=``
    argument, which skips by index arithmetic instead.

    A stream that ends BEFORE ``n`` batches raises ``ValueError``: the
    resume cursor points past the data, which means the dataset or
    batch size changed since the checkpoint — silently resuming there
    would corrupt the run. (A stream of exactly ``n`` batches is fine —
    that is a legitimate resume at the epoch's end.)"""
    it = iter(batches)
    for k in range(n):
        try:
            next(it)
        except StopIteration:
            raise ValueError(
                f"resume cursor skips {n} batches but the stream ended "
                f"after {k} — dataset or batch size changed since the "
                "checkpoint was written?") from None
    return it


def load_hf_dataset(path: str, split: str = "train"):
    """Load a HuggingFace ``save_to_disk`` directory or a single ``.arrow``
    file (reference CustomDataset, utils/Dataloader.py:38-141).

    Directory: ``load_from_disk``; if it holds a DatasetDict the ``split``
    is selected (unknown split -> ValueError listing the available ones,
    same contract as the reference). ``.arrow`` file: ``Dataset.from_file``.
    The ``datasets`` package is an optional dependency — a clear
    ImportError is raised when absent (this framework's own loaders read
    IDX/npz/CSV without it).
    """
    try:
        from datasets import Dataset, DatasetDict, load_from_disk
    except ImportError as e:
        raise ImportError(
            "load_hf_dataset needs the optional 'datasets' package "
            "(pip install datasets); the built-in IDX/npz/CSV loaders "
            "work without it") from e

    if not os.path.exists(path):
        raise FileNotFoundError(f"dataset path does not exist: {path}")
    if os.path.isdir(path):
        ds = load_from_disk(path)
        if isinstance(ds, DatasetDict):
            if split not in ds:
                raise ValueError(
                    f"split {split!r} not found; available: {list(ds.keys())}")
            return ds[split]
        return ds
    if path.endswith(".arrow"):
        return Dataset.from_file(path)
    raise ValueError(
        f"unsupported dataset path {path!r}: expected a save_to_disk "
        "directory or a .arrow file")


def summarization_from_hf(path: str, tokenizer, *, split: str = "train",
                          max_length: int = 512,
                          article_col: str = "article",
                          summary_col: str = "highlights",
                          limit: Optional[int] = None
                          ) -> "SummarizationDataset":
    """HF CNN/DailyMail-style dataset -> :class:`SummarizationDataset`
    (the reference pairs CustomDataset with SummarizationDataset for the
    same corpus, utils/Dataloader.py:216-260)."""
    ds = load_hf_dataset(path, split)
    n = min(limit, len(ds)) if limit is not None else len(ds)
    rows = []
    for i in range(n):
        row = ds[i]  # one Arrow row decode per index
        rows.append((row[article_col], row[summary_col]))
    return SummarizationDataset(rows, tokenizer, max_length=max_length)


def mnist_from_hf(path: str, *, split: str = "train",
                  image_col: str = "image", label_col: str = "label"
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """HF-format MNIST -> normalised (images [N,28,28,1], labels [N])
    with the same mean/std as :func:`load_mnist` (reference
    mnist_transform, utils/Dataloader.py:179-214). Accepts PIL images or
    nested lists/arrays in ``image_col``."""
    ds = load_hf_dataset(path, split)
    imgs = np.stack([np.asarray(r[image_col], dtype=np.uint8)
                     for r in ds])
    labels = np.asarray([r[label_col] for r in ds], dtype=np.int32)
    return _norm(imgs.reshape(len(imgs), 28, 28)), labels


class ByteTokenizer:
    """Byte-level fallback tokenizer (no-network stand-in for HF
    GPT2Tokenizer): ids 0-255 are bytes, 256=pad/eos."""

    vocab_size = 257
    pad_token_id = 256
    eos_token_id = 256

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8",
                                                            errors="replace")


def prefetch_batches(batches: Iterator, n: int = 2) -> Iterator:
    """Run the host-side batch pipeline (tokenise/stack/shuffle) in a
    background thread, keeping up to ``n`` batches ready. JAX's async
    dispatch already overlaps device compute with the *next* Python
    iteration; this additionally overlaps slow host data work (CSV
    tokenisation, HF arrow reads) with the whole step, which matters
    once datasets stop being synthetic. Exceptions re-raise at the
    consuming site."""
    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=n)
    _END = object()

    def feed():
        try:
            for b in batches:
                q.put(b)
            q.put(_END)
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            q.put(e)

    threading.Thread(target=feed, daemon=True).start()
    while True:
        item = q.get()
        if item is _END:
            return
        if isinstance(item, BaseException):
            raise item
        yield item


def pack_documents(docs: Sequence[Sequence[int]], seq_len: int,
                   *, eos_id: int, drop_remainder: bool = True
                   ) -> np.ndarray:
    """Concat-and-chunk sequence packing: token docs are joined with an
    EOS separator and chunked into [N, seq_len] rows with no padding —
    every position carries training signal (vs the reference's per-row
    right-padding where short rows waste most of the batch,
    utils/Dataloader.py:263-319). Standard LM-pretraining packing;
    cross-document attention is accepted (GPT-2 convention).

    Returns int32 [N, seq_len]. The remainder tail is dropped by
    default (set ``drop_remainder=False`` to keep it EOS-padded)."""
    flat: List[int] = []
    for d in docs:
        flat.extend(int(t) for t in d)
        flat.append(eos_id)
    n = len(flat) // seq_len
    rem = len(flat) - n * seq_len
    if rem and not drop_remainder:
        flat.extend([eos_id] * (seq_len - rem))
        n += 1
    return np.asarray(flat[: n * seq_len], np.int32).reshape(n, seq_len)


def segments_from_tokens(rows: np.ndarray, eos_id: int) -> np.ndarray:
    """Packed rows [N, S] -> per-position document ids [N, S] int32 for
    attention segment masking (ops/flash_attention.flash_attention
    ``segment_ids``): each EOS separator closes its document, so the id
    increments AFTER every eos. Ids restart at 0 per row (attention
    never crosses rows, so only within-row distinctness matters)."""
    rows = np.asarray(rows)
    ends = np.cumsum(rows == eos_id, axis=1)
    seg = np.concatenate([np.zeros_like(ends[:, :1]), ends[:, :-1]], axis=1)
    return seg.astype(np.int32)


class PackedLMDataset:
    """Causal-LM dataset over packed rows: labels ARE the inputs (the
    model's CLM loss does the shift; models/gpt2.py clm_loss), so there
    is no -100 masking and no padding — maximal tokens/step.

    Build from raw texts + any tokenizer with ``encode``/``eos_token_id``
    (HF GPT2Tokenizer or the ByteTokenizer fallback). Cross-document
    attention is the default (GPT-2 convention); pass the rows through
    :func:`segments_from_tokens` and hand the result to the attention
    stack for strict document isolation."""

    def __init__(self, rows: np.ndarray):
        assert rows.ndim == 2, rows.shape
        self.rows = rows

    @staticmethod
    def from_texts(texts: Sequence[str], tokenizer, *, seq_len: int,
                   drop_remainder: bool = True) -> "PackedLMDataset":
        eos = getattr(tokenizer, "eos_token_id", 0) or 0
        docs = [tokenizer.encode(t) for t in texts]
        return PackedLMDataset(pack_documents(docs, seq_len, eos_id=eos,
                                              drop_remainder=drop_remainder))

    def __len__(self):
        return len(self.rows)

    def batches(self, batch_size: int, *, seed: int = 0,
                shuffle: bool = True, drop_last: bool = True,
                start_batch: int = 0
                ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        idx = np.arange(len(self.rows))
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        end = len(idx) - (len(idx) % batch_size) if drop_last else len(idx)
        for i in range(start_batch * batch_size, end, batch_size):
            b = self.rows[idx[i:i + batch_size]]
            yield b, b.copy()


class SummarizationDataset:
    """CSV (article, highlights) pairs -> CLM tensors with the reference's
    prompt format: ``article + "\\n\\nTL;DR: " + summary`` and labels =
    input_ids with prompt/pad masked to -100
    (utils/Dataloader.py:263-319).
    """

    PROMPT = "\n\nTL;DR: "

    def __init__(self, rows: Sequence[Tuple[str, str]], tokenizer,
                 *, max_length: int = 512):
        self.rows = list(rows)
        self.tok = tokenizer
        self.max_length = max_length

    @staticmethod
    def from_csv(path: str, tokenizer, *, max_length: int = 512,
                 article_col: str = "article", summary_col: str = "highlights",
                 limit: Optional[int] = None) -> "SummarizationDataset":
        import csv

        rows = []
        with open(path, newline="", encoding="utf-8") as f:
            for i, rec in enumerate(csv.DictReader(f)):
                if limit is not None and i >= limit:
                    break
                rows.append((rec[article_col], rec[summary_col]))
        return SummarizationDataset(rows, tokenizer, max_length=max_length)

    @staticmethod
    def synthetic(n: int, tokenizer, *, max_length: int = 128, seed: int = 0
                  ) -> "SummarizationDataset":
        rng = np.random.default_rng(seed)
        words = ["alpha", "beta", "gamma", "delta", "epsilon", "zeta",
                 "eta", "theta"]
        rows = []
        for _ in range(n):
            k = rng.integers(8, 20)
            art = " ".join(rng.choice(words, size=k))
            summ = " ".join(art.split()[: max(2, k // 4)])
            rows.append((art, summ))
        return SummarizationDataset(rows, tokenizer, max_length=max_length)

    def __len__(self):
        return len(self.rows)

    def encode_row(self, article: str, summary: str
                   ) -> Tuple[np.ndarray, np.ndarray]:
        pad = getattr(self.tok, "pad_token_id", 0) or 0
        prompt_ids = self.tok.encode(article + self.PROMPT)
        summ_ids = self.tok.encode(summary)
        # Keep the training signal: when prompt+summary overflow, drop
        # article tokens from the LEFT (the "\n\nTL;DR: " marker at the
        # prompt's tail survives). Plain right-truncation can leave a row
        # with every label masked — at small max_length whole batches
        # become no-ops and the loss is silently 0.
        max_prompt = max(self.max_length - len(summ_ids), 0)
        if len(prompt_ids) > max_prompt:
            prompt_ids = prompt_ids[len(prompt_ids) - max_prompt:]
        ids = (prompt_ids + summ_ids)[: self.max_length]
        n_prompt = min(len(prompt_ids), self.max_length)
        labels = [-100] * n_prompt + ids[n_prompt:]
        padlen = self.max_length - len(ids)
        ids = ids + [pad] * padlen
        labels = labels + [-100] * padlen
        return (np.asarray(ids, np.int32), np.asarray(labels, np.int32))

    def batches(self, batch_size: int, *, seed: int = 0, shuffle: bool = True,
                drop_last: bool = True, start_batch: int = 0
                ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        idx = np.arange(len(self.rows))
        if shuffle:
            np.random.default_rng(seed).shuffle(idx)
        end = len(idx) - (len(idx) % batch_size) if drop_last else len(idx)
        # start_batch skips by index — no skipped row is ever tokenised
        # (the win over generic skip_batches is largest here)
        for i in range(start_batch * batch_size, end, batch_size):
            enc = [self.encode_row(*self.rows[j]) for j in idx[i:i + batch_size]]
            yield (np.stack([e[0] for e in enc]),
                   np.stack([e[1] for e in enc]))

    def eval_prompts(self, *, max_prompt_len: int, limit: Optional[int] = None
                     ) -> List[Tuple[List[int], str]]:
        """(prompt token ids, reference summary) pairs for generation
        eval (reference evaluate_generation, utils/metrics.py:152-206).

        Prompts are LEFT-truncated (keep the "...\\n\\nTL;DR: " tail) to
        at most ``max_prompt_len`` and rounded DOWN to a multiple of 8 so
        the jitted decoder compiles for at most max_prompt_len/8 distinct
        shapes instead of one per article length."""
        out = []
        for article, summary in self.rows[: limit or len(self.rows)]:
            ids = self.tok.encode(article + self.PROMPT)
            n = min(len(ids), max_prompt_len)
            n = max((n // 8) * 8, min(n, 8))
            out.append((ids[len(ids) - n:], summary))
        return out

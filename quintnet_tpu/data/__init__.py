"""Datasets and loaders (host-side numpy; sharding happens at
Strategy.shard_batch)."""

from quintnet_tpu.data.datasets import (
    ArrayDataset,
    ByteTokenizer,
    SummarizationDataset,
    load_mnist,
    make_batches,
)

__all__ = [
    "ArrayDataset",
    "ByteTokenizer",
    "SummarizationDataset",
    "load_mnist",
    "make_batches",
]

"""Datasets and loaders (host-side numpy; sharding happens at
Strategy.shard_batch)."""

from quintnet_tpu.data.datasets import (
    ArrayDataset,
    ByteTokenizer,
    PackedLMDataset,
    SummarizationDataset,
    load_mnist,
    make_batches,
    pack_documents,
    prefetch_batches,
    skip_batches,
)

__all__ = [
    "ArrayDataset",
    "ByteTokenizer",
    "PackedLMDataset",
    "SummarizationDataset",
    "load_mnist",
    "make_batches",
    "pack_documents",
    "prefetch_batches",
    "skip_batches",
]

"""Host-RAM second tier under the paged KV pool's prefix cache.

The device pool's prefix index (serve/kv_pool.py) retains refcount-zero
published chains until allocation pressure evicts them — and eviction
used to DESTROY the chain: every future request for that prefix paid a
full re-prefill. At fleet scale the shared-prefix working set (system
prompts x tenants x conversations) vastly exceeds device HBM, so the
hot tail of the LRU is exactly the traffic that keeps getting
re-prefilled.

This module adds the missing tier: when :meth:`KVPool._evict_lru`
would destroy a published block, the pool DEMOTES it here instead — a
host copy of the block's slot data exactly as stored (the layout
policy's ``store_dtype``, so int8 pools demote ~4x smaller records,
plus the per-block-per-head scale rows when scaled: byte-identical to
one record of :meth:`KVPool.export_chain`). Records are keyed by the
block's prefix-index key bytes — the NUL-terminated namespace prefix +
literal token bytes — so host lookups walk the same key ladder device
lookups do and adapter namespaces stay isolated across tiers for free.

Admission then has a THIRD outcome beyond device-hit / miss: a
**host-hit** (the combined device+host walk covers more than the
device chain alone). A host-hit re-promotes the chain through the
pool's existing fused ``import_chain`` scatter instead of
re-prefilling — and promotion is asynchronous: the engine parks the
request in a ``PROMOTING`` state (serve/scheduler.py) and keeps
decoding every other slot while at most a per-step block budget of
host->device copies lands each step (the Sarathi budget discipline
from chunked prefill, applied to memcpy instead of prefill compute).

The tier is BOUNDED: ``byte_budget`` caps resident record bytes with
the tier's own LRU (least-recently demoted/probed records drop first),
so demotion can never grow host memory without limit — and a record
evicted here is simply a miss, never an error: the tier is cache under
cache, and every degraded path falls back to re-prefill, which is
always token-correct.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional


def record_nbytes(rec: Dict) -> int:
    """Host bytes one demoted block record holds (slot data + scale
    rows). The ledger the byte budget is enforced against."""
    n = rec["k"].nbytes + rec["v"].nbytes
    if "k_scale" in rec:
        n += rec["k_scale"].nbytes + rec["v_scale"].nbytes
    return n


class HostTier:
    """Bounded host-RAM store of demoted KV blocks, LRU-evicted.

    One record per demoted block, in the ``export_chain`` per-block
    format (``{"fill", "k", "v"[, "k_scale", "v_scale"]}``), keyed by
    the block's prefix-index key bytes. The tier is INCLUSIVE: a
    promoted record stays resident, so a later re-demotion of the same
    (byte-identical) block is a cheap overwrite, not a loss.

    Single-threaded like the pool that owns it (all mutation happens
    on the engine's step thread); counters are plain ints.
    """

    def __init__(self, *, byte_budget: int):
        if byte_budget <= 0:
            raise ValueError(
                f"byte_budget must be > 0, got {byte_budget} "
                f"(a tier that can hold nothing is prefix_cache-only "
                f"— build the pool without a host tier instead)")
        self.byte_budget = int(byte_budget)
        self.bytes_used = 0
        # ordered oldest -> newest: OrderedDict IS the tier's LRU
        # (move_to_end on every hit, popitem(last=False) to evict)
        self._records: "OrderedDict[bytes, Dict]" = OrderedDict()
        # monotone counters, surfaced through ServeMetrics.summary()
        self.demotions = 0         # blocks demoted in (puts)
        self.promotions = 0        # blocks promoted back to device
        self.promoted_tokens = 0   # token positions those blocks held
        self.evictions = 0         # records dropped for the budget

    def __len__(self) -> int:
        return len(self._records)

    def contains(self, key: bytes) -> bool:
        """Membership WITHOUT an LRU touch — the probe used by chain
        walks (a walk must not rejuvenate records it never moves)."""
        return key in self._records

    def get(self, key: bytes) -> Optional[Dict]:
        """The record for ``key`` (LRU-touched), or None."""
        rec = self._records.get(key)
        if rec is not None:
            self._records.move_to_end(key)
        return rec

    def put(self, key: bytes, rec: Dict) -> bool:
        """Demote one block record. Evicts least-recently-used records
        until the budget holds; a record larger than the whole budget
        is refused (False) rather than flushing the tier for a block
        that can never be retained."""
        nbytes = record_nbytes(rec)
        if nbytes > self.byte_budget:
            return False
        old = self._records.pop(key, None)
        if old is not None:
            self.bytes_used -= record_nbytes(old)
        while self.bytes_used + nbytes > self.byte_budget:
            _k, dropped = self._records.popitem(last=False)
            self.bytes_used -= record_nbytes(dropped)
            self.evictions += 1
        self._records[key] = rec
        self.bytes_used += nbytes
        self.demotions += 1
        return True

    def summary(self) -> Dict:
        """JSON-able tier counters (the engine folds these into
        ``ServeMetrics.summary()`` each step)."""
        return {"records": len(self._records),
                "bytes_used": self.bytes_used,
                "byte_budget": self.byte_budget,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "promoted_tokens": self.promoted_tokens,
                "evictions": self.evictions}


@dataclass
class PromotionState:
    """Host-side progress of one request's asynchronous host->device
    promotion (the ChunkState idiom from serve/longctx.py applied to
    memcpy): the request sits at the head of the waiting queue in the
    ``PROMOTING`` state while the engine feeds at most its per-step
    block budget of promotions each step; when ``next`` reaches the
    end of ``keys`` (or the chain truncates — a host record evicted
    mid-flight), the request returns to ``WAITING`` and the normal
    admission path finds the promoted chain as an ordinary device
    prefix hit. Every early exit is therefore correct by construction:
    whatever landed is cache, whatever did not is re-prefilled."""

    req: object                        # the owning scheduler Request
    keys: List[bytes] = field(default_factory=list)
    next: int = 0                      # keys[:next] already consumed

    @property
    def done(self) -> bool:
        return self.next >= len(self.keys)

    @property
    def remaining(self) -> int:
        return len(self.keys) - self.next

"""Continuous-batching inference engine with a paged KV-cache pool.

The batch decoders (models/gpt2_generate.py, models/llama_generate.py)
serve ONE request batch at a time: every prompt padded to the longest,
one dense [L, B, H, T_max, Dh] cache sized for the worst case, no way to
admit work while a batch is mid-decode. This package turns the same
TP-sharded prefill/decode kernels into an engine that sustains many
concurrent, variably-sized requests (Orca-style iteration-level
scheduling; vLLM-style paged KV blocks):

- :mod:`kv_pool` — fixed-size KV blocks per layer, refcounted
  acquire/release, per-request block tables (no per-batch T_max
  padding), and a PREFIX CACHE: a token-keyed block index (literal
  prefix bytes, not a hash digest — collisions impossible) with LRU
  retention of refcount-zero blocks and copy-on-write sharing, so
  requests with a common prompt prefix (and preemption-resumes /
  migrations) reuse resident KV instead of recomputing it;
- :mod:`kv_quant` — KV-pool LAYOUT POLICIES: f32/bf16/fp8 passthrough,
  int8 blocks with per-block-per-head absmax scales (dequantized
  inside the gathered-view attention kernels, quantized on scatter —
  the same pool bytes hold ~4x the blocks), and the fake-quant
  identity policy whose engine is bit-identical to f32 (the proof the
  scaled code path is numerically inert); also home of the shared
  :class:`~quintnet_tpu.serve.kv_quant.LayoutPolicy` protocol;
- :mod:`weight_quant` — WEIGHT layout policies on the same protocol:
  int8/fp8 per-output-channel absmax weights packed once at engine
  build and dequantized INSIDE the serving matmuls
  (nn/layers.quantized_matmul — one per-column multiply, the wide
  weight never materialized), f32/bf16 passthrough, and the same
  fake-quant bit-identity proof; the LoRA delta path stays
  full-precision on top;
- :mod:`scheduler` — waiting queue, admission by UNCACHED-block budget,
  FCFS + optional priority, preemption-by-eviction of the youngest
  request when the pool is exhausted;
- :mod:`engine` — the step loop: ONE jitted decode-step program over a
  static MAX_SLOTS batch (masked empty slots — no recompiles as
  requests come and go), bucketed chunked prefill for newly admitted
  requests (powers-of-two padded lengths, at most one compiled program
  per bucket), EOS / max-len retirement;
- :mod:`families` — the GPT-2 / Llama model adapters (thin reuse of
  nn/attention.mha_decode's paged path and the generate modules'
  embed/logits helpers);
- :mod:`adapters` — multi-tenant LoRA: an adapter registry (host-side
  LRU of safetensors adapter weights, refcount pinning) + per-slot
  packed low-rank factors so heterogeneous-adapter requests batch into
  the SAME decode step (S-LoRA/Punica style), token-identical to
  dedicated merged-weight engines;
- :mod:`longctx` — long-context serving: Sarathi-style chunked prefill
  (a prompt longer than the largest compiled bucket is admitted whole
  and streamed through the existing bucket programs under a per-step
  token budget, so concurrent decodes never starve) and the planning
  half of the ring-attention sequence-parallel prefill path (chunk K/V
  sharded over an ``sp`` mesh axis while scoring);
- :mod:`api` — blocking ``generate()`` + streaming per-token callbacks;
- :mod:`metrics` — per-step counters and TTFT / tok/s percentiles.

tools/serve_bench.py replays a synthetic Poisson trace through the
engine and emits a one-line JSON throughput/latency report.
"""

from quintnet_tpu.serve.adapters import AdapterEntry, AdapterRegistry
from quintnet_tpu.serve.api import generate, generate_stream
from quintnet_tpu.serve.engine import (ServeEngine, check_admissible)
from quintnet_tpu.serve.families import gpt2_family, llama_family
from quintnet_tpu.serve.kv_pool import AdmitPlan, KVPool
from quintnet_tpu.serve.kv_quant import (KVLayoutPolicy, LayoutPolicy,
                                         make_policy)
from quintnet_tpu.serve.weight_quant import (WeightLayoutPolicy,
                                             make_weight_policy)
from quintnet_tpu.serve.longctx import ChunkState, plan_chunks
from quintnet_tpu.serve.metrics import ServeMetrics, aggregate
from quintnet_tpu.serve.scheduler import (DeadlineExceeded, Request,
                                          RequestProgress, Scheduler)
from quintnet_tpu.serve.spec import NgramDrafter, SpecConfig

__all__ = [
    "AdapterEntry",
    "AdapterRegistry",
    "AdmitPlan",
    "ChunkState",
    "DeadlineExceeded",
    "KVLayoutPolicy",
    "KVPool",
    "LayoutPolicy",
    "NgramDrafter",
    "Request",
    "RequestProgress",
    "Scheduler",
    "ServeEngine",
    "ServeMetrics",
    "SpecConfig",
    "WeightLayoutPolicy",
    "aggregate",
    "check_admissible",
    "generate",
    "generate_stream",
    "gpt2_family",
    "llama_family",
    "make_policy",
    "make_weight_policy",
    "plan_chunks",
]

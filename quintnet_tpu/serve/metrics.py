"""Serving metrics: per-step gauges + per-request latency percentiles.

Counters the engine records every step (running/waiting/preempted,
KV-block utilization, prefill vs decode tokens) and per-request marks
(submit, first token, finish) from which TTFT and tok/s percentiles are
derived. Emission goes through utils/logger.py — the same stdout+file
tee the trainer uses — so a serving process logs like a training one.

All timing uses a caller-injectable clock so tests and the synthetic
trace replayer (tools/serve_bench.py) can drive deterministic
"wall time" without sleeping.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

# default percentile-source bound (see Reservoir): exact below this,
# documented uniform sampling above it
RESERVOIR_CAP = 4096


class Reservoir:
    """Bounded percentile source: EXACT below ``cap`` observations,
    a uniform reservoir sample (Vitter's Algorithm R) above it.

    The percentile source lists (``ttfts``/``latencies``/``itls`` and
    the per-adapter TTFTs) previously grew without limit — a
    long-running replica leaked one float per request/token forever.
    The reservoir keeps memory O(cap) while every stored element
    remains an unbiased uniform draw from the full stream, so the
    p50/p95 estimates stay honest; p99 degrades gracefully (documented
    sampling error ~1/sqrt(cap)). ``n`` is the TRUE stream count —
    ``summary()`` surfaces it so a reader can tell exact-mode
    (``n <= cap``) from sampled.

    List-compatible surface (append/extend/iter/len/bool/indexing) so
    ``aggregate()``'s pooling — extend into a plain list, percentiles
    over the pool — keeps working unchanged; pooling reservoirs pools
    their retained samples, which stays uniform per-replica.

    Deterministic: the replacement RNG is seeded per-instance, so two
    replays of the same trace summarize identically (the bench's A/B
    discipline)."""

    __slots__ = ("cap", "n", "_items", "_rng")

    def __init__(self, cap: int = RESERVOIR_CAP, *, seed: int = 0):
        if cap < 1:
            raise ValueError(f"cap must be >= 1, got {cap}")
        self.cap = int(cap)
        self.n = 0
        self._items: List[float] = []
        self._rng = random.Random(seed)

    def append(self, x: float) -> None:
        self.n += 1
        if len(self._items) < self.cap:
            self._items.append(float(x))
            return
        j = self._rng.randrange(self.n)      # Algorithm R
        if j < self.cap:
            self._items[j] = float(x)

    def extend(self, xs) -> None:
        for x in xs:
            self.append(x)

    def __iter__(self):
        return iter(self._items)

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def __eq__(self, other):
        if isinstance(other, Reservoir):
            return self._items == other._items
        return self._items == other

    def to_list(self) -> List[float]:
        return list(self._items)


def _pooled_pcts(groups) -> Dict[str, float]:
    """Fleet-wide percentiles over several replicas' percentile
    sources, each a ``(samples, true_n)`` pair where ``samples`` may
    be a reservoir-capped subset of a ``true_n``-long stream.

    When every group is exact (``true_n == len(samples)``) this is
    plain pooling — concatenate and take percentiles, bit-identical
    to the pre-reservoir behavior. When any replica exceeded its cap,
    naive pooling would weight every RETAINED sample equally and bias
    the fleet tail toward low-traffic replicas (a 100k-request replica
    and a 5k-request one both retain cap samples); instead each
    retained sample is weighted by the number of observations it
    represents (``true_n / len(samples)``) and the percentiles come
    from the weighted inverted CDF — an unbiased estimate of the true
    pooled distribution, since each reservoir is a uniform draw from
    its own stream."""
    groups = [(list(s), int(n)) for s, n in groups]
    total_n = sum(n for _s, n in groups)
    if all(n == len(s) for s, n in groups):
        pooled: List[float] = []
        for s, _n in groups:
            pooled.extend(s)
        return _pcts(pooled, n=total_n)
    vals: List[float] = []
    wts: List[float] = []
    for s, n in groups:
        if not s:
            continue
        w = n / len(s)
        vals.extend(s)
        wts.extend([w] * len(s))
    if not vals:
        return {"p50": 0.0, "p95": 0.0, "p99": 0.0, "n": total_n}
    v = np.asarray(vals, np.float64)
    w = np.asarray(wts, np.float64)
    order = np.argsort(v)
    v, w = v[order], w[order]
    cw = np.cumsum(w)
    out: Dict[str, float] = {}
    for name, p in (("p50", 50), ("p95", 95), ("p99", 99)):
        idx = int(np.searchsorted(cw, p / 100.0 * cw[-1]))
        out[name] = float(v[min(idx, len(v) - 1)])
    out["n"] = total_n
    return out


def _pcts(xs, n: Optional[int] = None) -> Dict[str, float]:
    """Percentiles over a source list/Reservoir. ``n`` reports the
    TRUE observation count behind the (possibly reservoir-sampled)
    stored values; it defaults to the source's own ``n`` (Reservoir)
    or its length (plain pooled list)."""
    stored = xs if isinstance(xs, list) else list(xs)
    if n is None:
        n = getattr(xs, "n", len(stored))
    if not stored:
        return {"p50": 0.0, "p95": 0.0, "p99": 0.0, "n": int(n)}
    a = np.asarray(stored, np.float64)
    return {"p50": float(np.percentile(a, 50)),
            "p95": float(np.percentile(a, 95)),
            "p99": float(np.percentile(a, 99)),
            "n": int(n)}


@dataclass
class ServeMetrics:
    clock: "callable" = time.monotonic

    # step gauges (overwritten each step) ----------------------------
    running: int = 0
    waiting: int = 0
    kv_blocks_used: int = 0
    kv_blocks_total: int = 0
    # KV capacity gauges (policy-aware, serve/kv_quant.py): the pool's
    # total device bytes and per-resident-token bytes — what makes an
    # equal-bytes capacity A/B legible next to peak_kv_utilization
    # (an int8 pool shows ~4x the blocks at the same kv_pool_bytes)
    kv_pool_bytes: int = 0
    kv_bytes_per_token: float = 0.0
    # weight layout gauges (serve/weight_quant.py): device bytes of the
    # packed weight targets (w + w_scale) and the policy name — the
    # f32/int8 weight_bytes ratio is the decode-bandwidth win the A/B
    # gate ratios (>= 3.5x for int8). Mirrored each step like
    # kv_pool_bytes; the engine owns the truth.
    weight_bytes: int = 0
    weights_dtype: str = "f32"

    # monotone counters ----------------------------------------------
    steps: int = 0
    admitted: int = 0
    preempted: int = 0
    finished: int = 0
    # requests retired MID-GENERATION (or while waiting) because their
    # deadline passed — typed DeadlineExceeded, blocks published
    # (serve/engine.py _sweep_deadlines); disjoint from `finished`
    deadline_exceeded: int = 0
    prefill_tokens: int = 0
    decode_tokens: int = 0
    # prefix-cache ledger: hit tokens are prompt positions served from
    # the cached block chain at admission — exactly the prefill tokens
    # SAVED (they were never recomputed); prefill_tokens above counts
    # only the uncached tail actually pushed through a prefill program
    prefix_hit_tokens: int = 0
    # speculative-decoding ledger (serve/spec.py): decode_steps counts
    # decode/verify program invocations (the denominator that makes
    # multi-token commits visible: tokens_per_decode_step > 1 is the
    # speculation win); spec_steps of those ran a verify bucket;
    # draft_tokens were proposed, accepted_draft_tokens committed
    decode_steps: int = 0
    spec_steps: int = 0
    draft_tokens: int = 0
    accepted_draft_tokens: int = 0
    # chunked-prefill ledger (serve/longctx.py): prefill_chunks counts
    # chunk program invocations; chunk_steps the engine steps that ran
    # >= 1 chunk; chunk_tokens the prompt tokens those steps pushed
    # through chunk programs — chunk_tokens / chunk_steps is the
    # realized per-step prefill spend the Sarathi budget caps
    prefill_chunks: int = 0
    chunk_steps: int = 0
    chunk_tokens: int = 0
    # tiered-KV ledger (serve/kv_tier.py): CUMULATIVE pool/tier
    # counters mirrored (assigned, not summed) each step — the pool
    # owns the truth, the mirror makes eviction/demotion/promotion
    # visible to summary()/aggregate() and the Prometheus exporter.
    # kv_cache_evictions: published device blocks evicted (tier off:
    # chains destroyed; tier on: each eviction first demotes).
    # kv_demotions / kv_promotions: blocks copied device->host /
    # host->device; kv_host_evictions: host records dropped by the
    # tier's own byte-budget LRU; host_hit_tokens: token positions
    # re-promoted from host instead of re-prefilled;
    # decode_blocked_demotions: demotions observed during a plain
    # decode dispatch — structurally 0 (the bench gates it).
    kv_cache_evictions: int = 0
    kv_demotions: int = 0
    kv_promotions: int = 0
    kv_host_evictions: int = 0
    host_hit_tokens: int = 0
    decode_blocked_demotions: int = 0
    # gauge: host bytes the tier currently holds (<= its byte budget)
    host_tier_bytes: int = 0
    peak_kv_utilization: float = 0.0
    peak_running: int = 0

    # MoE routing ledger (nn/moe.py routing stats, drained by the
    # engine once per step; absent for dense families — summary()
    # gates the keys on MoE activity so dense exposition stays
    # byte-identical). moe_routed_tokens counts token-expert
    # assignments the router DEMANDED (pre-capacity-cut, summed over
    # layers and programs: S * top_k per MoE layer per invocation);
    # moe_dropped_tokens the assignments the capacity cut discarded;
    # moe_expert_tokens the cumulative per-expert demand [E] (the
    # honest skew signal — post-cut counts saturate at capacity under
    # a hot expert); entropy is the mean per-token router entropy,
    # averaged over the steps that reported it
    moe_routed_tokens: float = 0.0
    moe_dropped_tokens: float = 0.0
    moe_expert_tokens: Optional[np.ndarray] = None
    moe_entropy_sum: float = 0.0
    moe_stat_steps: int = 0

    # per-adapter ledger (multi-tenant LoRA, serve/adapters.py):
    # adapter id -> {"requests": finished, "gen_tokens": generated,
    # "ttfts": Reservoir} — the per-tenant slice of the totals above
    # (base-model traffic is the remainder)
    per_adapter: Dict[str, Dict] = field(default_factory=dict)

    # per-request marks (percentile SOURCES, reservoir-bounded: exact
    # below RESERVOIR_CAP observations, uniform sampling above — a
    # long-running replica's memory stays O(cap); summary() surfaces
    # the true count as "n" beside the percentiles) -------------------
    ttfts: Reservoir = field(default_factory=Reservoir)
    latencies: Reservoir = field(default_factory=Reservoir)
    # inter-token gaps (seconds between a request's consecutive
    # tokens, pooled across requests) — the decode-starvation signal:
    # a monolithic prefill shows up as one giant gap in every
    # concurrent stream, a budgeted chunked prefill does not
    itls: Reservoir = field(default_factory=Reservoir)
    _t0: Optional[float] = None
    _t_end: Optional[float] = None

    # ---- recording --------------------------------------------------
    def record_step(self, *, running: int, waiting: int,
                    kv_blocks_used: int, kv_blocks_total: int,
                    prefill_tokens: int, decode_tokens: int,
                    prefix_hit_tokens: int = 0,
                    spec_step: bool = False,
                    draft_tokens: int = 0,
                    accepted_draft_tokens: int = 0,
                    prefill_chunks: int = 0,
                    kv_pool_bytes: int = 0,
                    kv_bytes_per_token: float = 0.0,
                    weight_bytes: int = 0,
                    weights_dtype: str = "f32",
                    kv_cache_evictions: int = 0,
                    kv_demotions: int = 0,
                    kv_promotions: int = 0,
                    kv_host_evictions: int = 0,
                    host_hit_tokens: int = 0,
                    host_tier_bytes: int = 0,
                    decode_blocked_demotions: int = 0,
                    moe_routed_tokens: float = 0.0,
                    moe_dropped_tokens: float = 0.0,
                    moe_expert_tokens=None,
                    moe_router_entropy: Optional[float] = None) -> None:
        now = self.clock()
        if self._t0 is None:
            self._t0 = now
        self._t_end = now
        self.steps += 1
        self.running = running
        self.waiting = waiting
        self.kv_blocks_used = kv_blocks_used
        self.kv_blocks_total = kv_blocks_total
        self.kv_pool_bytes = kv_pool_bytes
        self.kv_bytes_per_token = kv_bytes_per_token
        self.weight_bytes = weight_bytes
        self.weights_dtype = weights_dtype
        self.prefill_tokens += prefill_tokens
        self.decode_tokens += decode_tokens
        self.prefix_hit_tokens += prefix_hit_tokens
        if decode_tokens > 0:
            self.decode_steps += 1
        if spec_step:
            self.spec_steps += 1
        self.draft_tokens += draft_tokens
        self.accepted_draft_tokens += accepted_draft_tokens
        self.prefill_chunks += prefill_chunks
        if prefill_chunks > 0:
            self.chunk_steps += 1
            self.chunk_tokens += prefill_tokens
        # tier ledger: cumulative mirrors (assigned, never summed —
        # the engine passes the pool/tier counters' current values)
        self.kv_cache_evictions = kv_cache_evictions
        self.kv_demotions = kv_demotions
        self.kv_promotions = kv_promotions
        self.kv_host_evictions = kv_host_evictions
        self.host_hit_tokens = host_hit_tokens
        self.host_tier_bytes = host_tier_bytes
        self.decode_blocked_demotions = decode_blocked_demotions
        self.moe_routed_tokens += float(moe_routed_tokens)
        self.moe_dropped_tokens += float(moe_dropped_tokens)
        if moe_expert_tokens is not None:
            et = np.asarray(moe_expert_tokens, np.float64)
            if self.moe_expert_tokens is None:
                self.moe_expert_tokens = np.zeros_like(et)
            self.moe_expert_tokens = self.moe_expert_tokens + et
        if moe_router_entropy is not None:
            self.moe_entropy_sum += float(moe_router_entropy)
            self.moe_stat_steps += 1
        util = kv_blocks_used / max(kv_blocks_total, 1)
        self.peak_kv_utilization = max(self.peak_kv_utilization, util)
        self.peak_running = max(self.peak_running, running)

    def record_admit(self) -> None:
        self.admitted += 1

    def record_preempt(self) -> None:
        self.preempted += 1

    def record_deadline_exceeded(self) -> None:
        self.deadline_exceeded += 1

    def _adapter(self, adapter_id: str) -> Dict:
        return self.per_adapter.setdefault(
            adapter_id,
            {"requests": 0, "gen_tokens": 0, "ttfts": Reservoir()})

    def record_adapter_token(self, adapter_id: str) -> None:
        """One generated token attributed to ``adapter_id`` (the engine
        calls this beside its committed-token bookkeeping, so adapter
        ledgers count exactly the tokens the tenant received HERE —
        a migrated request's earlier tokens stay on the exporter)."""
        self._adapter(adapter_id)["gen_tokens"] += 1

    def record_first_token(self, ttft_s: float,
                           adapter_id: Optional[str] = None) -> None:
        self.ttfts.append(ttft_s)
        if adapter_id is not None:
            self._adapter(adapter_id)["ttfts"].append(ttft_s)

    def record_itl(self, gap_s: float) -> None:
        """One inter-token gap (seconds since the same request's
        previous token)."""
        self.itls.append(gap_s)

    def record_finish(self, latency_s: float,
                      adapter_id: Optional[str] = None) -> None:
        self.finished += 1
        self.latencies.append(latency_s)
        if adapter_id is not None:
            self._adapter(adapter_id)["requests"] += 1

    # ---- reporting --------------------------------------------------
    @property
    def gen_tokens(self) -> int:
        """GENERATED tokens: every admission samples exactly one
        (prefill) token; the rest come from decode steps. The single
        definition behind ``tokens_per_sec`` — consumers (the serve
        bench) read it here rather than re-deriving it."""
        return self.decode_tokens + self.admitted

    @property
    def wall_s(self) -> float:
        if self._t0 is None or self._t_end is None:
            return 0.0
        return max(self._t_end - self._t0, 0.0)

    @property
    def prefill_tokens_saved(self) -> int:
        """Prefill tokens never computed because the prefix cache
        already held them (== prefix_hit_tokens; the name states what
        the number buys)."""
        return self.prefix_hit_tokens

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of required prefill positions served from the
        cache: hit / (hit + actually-prefilled)."""
        denom = self.prefix_hit_tokens + self.prefill_tokens
        return self.prefix_hit_tokens / denom if denom else 0.0

    @property
    def host_hit_rate(self) -> float:
        """Fraction of all warm-or-computed prefill positions that
        were served by a HOST-tier promotion rather than device cache
        or fresh prefill: host_hit / (prefix_hit + prefill). Promoted
        positions surface again as prefix_hit_tokens when the request
        admits (the promoted chain is a device hit by then), so the
        denominator already contains the numerator — the rate reads
        as "share of prefill demand the host tier rescued"."""
        denom = self.prefix_hit_tokens + self.prefill_tokens
        return self.host_hit_tokens / denom if denom else 0.0

    @property
    def tokens_per_decode_step(self) -> float:
        """Mean tokens committed per decode/verify invocation, summed
        over the batch — ~(mean active slots) for plain decoding (one
        token per active row per step), multiplied by the mean accepted
        run length when speculation commits drafts. An A/B over the
        SAME trace isolates the speculation factor; in isolation the
        number conflates concurrency with acceptance."""
        return (self.decode_tokens / self.decode_steps
                if self.decode_steps else 0.0)

    @property
    def draft_acceptance_rate(self) -> float:
        """Fraction of drafted tokens the verify step committed."""
        return (self.accepted_draft_tokens / self.draft_tokens
                if self.draft_tokens else 0.0)

    @property
    def chunk_tokens_per_step(self) -> float:
        """Mean prompt tokens pushed through chunk programs per
        chunk-running engine step — bounded above by the engine's
        ``prefill_chunk_budget`` (the Sarathi cap made observable)."""
        return (self.chunk_tokens / self.chunk_steps
                if self.chunk_steps else 0.0)

    @property
    def moe_drop_rate(self) -> float:
        """Fraction of routed token-expert assignments the capacity
        cut discarded."""
        return (self.moe_dropped_tokens / self.moe_routed_tokens
                if self.moe_routed_tokens else 0.0)

    @property
    def moe_expert_skew(self) -> float:
        """max/mean of cumulative per-expert routed demand — 1.0 is
        perfectly balanced, E is a single hot expert taking all of
        it."""
        et = self.moe_expert_tokens
        if et is None or float(np.sum(et)) == 0.0:
            return 0.0
        return float(np.max(et) / np.mean(et))

    @property
    def moe_router_entropy(self) -> float:
        """Mean per-token router-distribution entropy over the steps
        that reported one (nats; ln(E) is uniform)."""
        return (self.moe_entropy_sum / self.moe_stat_steps
                if self.moe_stat_steps else 0.0)

    def summary(self) -> Dict:
        """One JSON-able dict: throughput, TTFT/latency percentiles,
        peak pool pressure. tok/s counts GENERATED (decode + prefill-
        sampled) tokens — the serving-throughput number, not prompt
        reading speed. MoE keys appear only when routing stats were
        recorded, so a dense engine's summary is byte-identical to
        what it was before MoE serving existed."""
        wall = self.wall_s
        gen_tokens = self.gen_tokens
        out = {
            "steps": self.steps,
            "gen_tokens": gen_tokens,
            "admitted": self.admitted,
            "finished": self.finished,
            "preempted": self.preempted,
            "deadline_exceeded": self.deadline_exceeded,
            "prefill_tokens": self.prefill_tokens,
            "decode_tokens": self.decode_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "prefix_hit_rate": round(self.prefix_hit_rate, 4),
            "decode_steps": self.decode_steps,
            "tokens_per_decode_step": round(self.tokens_per_decode_step, 4),
            "spec_steps": self.spec_steps,
            "draft_tokens": self.draft_tokens,
            "accepted_draft_tokens": self.accepted_draft_tokens,
            "draft_acceptance_rate": round(self.draft_acceptance_rate, 4),
            "prefill_chunks": self.prefill_chunks,
            "chunk_steps": self.chunk_steps,
            "chunk_tokens": self.chunk_tokens,
            "chunk_tokens_per_step": round(self.chunk_tokens_per_step, 4),
            "kv_cache_evictions": self.kv_cache_evictions,
            "kv_demotions": self.kv_demotions,
            "kv_promotions": self.kv_promotions,
            "kv_host_evictions": self.kv_host_evictions,
            "host_hit_tokens": self.host_hit_tokens,
            "host_hit_rate": round(self.host_hit_rate, 4),
            "host_tier_bytes": self.host_tier_bytes,
            "decode_blocked_demotions": self.decode_blocked_demotions,
            "wall_s": round(wall, 4),
            "tokens_per_sec": round(gen_tokens / wall, 2) if wall > 0
            else 0.0,
            "ttft_s": _pcts(self.ttfts),
            "latency_s": _pcts(self.latencies),
            "itl_s": _pcts(self.itls),
            "peak_kv_utilization": round(self.peak_kv_utilization, 4),
            "kv_pool_bytes": self.kv_pool_bytes,
            "kv_bytes_per_token": round(self.kv_bytes_per_token, 4),
            "weight_bytes": self.weight_bytes,
            "weights_dtype": self.weights_dtype,
            "peak_running": self.peak_running,
            "adapters": {
                aid: {"requests": d["requests"],
                      "gen_tokens": d["gen_tokens"],
                      "ttft_s": _pcts(d["ttfts"])}
                for aid, d in sorted(self.per_adapter.items())},
        }
        if self.moe_stat_steps or self.moe_routed_tokens:
            out["moe_routed_tokens"] = int(self.moe_routed_tokens)
            out["moe_dropped_tokens"] = int(self.moe_dropped_tokens)
            out["moe_drop_rate"] = round(self.moe_drop_rate, 4)
            out["moe_expert_skew"] = round(self.moe_expert_skew, 4)
            out["moe_router_entropy"] = round(self.moe_router_entropy,
                                              4)
            out["moe_expert_tokens"] = (
                {str(e): int(v)
                 for e, v in enumerate(self.moe_expert_tokens)}
                if self.moe_expert_tokens is not None else {})
        return out

    def log_step(self, logger: Optional[logging.Logger], *,
                 every: int = 1) -> None:
        if logger is None or self.steps % max(every, 1):
            return
        logger.info(
            "serve step=%d running=%d waiting=%d kv=%d/%d (%.0f%%) "
            "prefill_toks=%d decode_toks=%d preempted=%d finished=%d",
            self.steps, self.running, self.waiting, self.kv_blocks_used,
            self.kv_blocks_total,
            100.0 * self.kv_blocks_used / max(self.kv_blocks_total, 1),
            self.prefill_tokens, self.decode_tokens, self.preempted,
            self.finished)


def aggregate(all_metrics: List["ServeMetrics"]) -> Dict:
    """Fleet-level roll-up of several engines' :class:`ServeMetrics`
    into one summary-shaped dict (quintnet_tpu/fleet/ reads it for the
    whole-fleet throughput line).

    Counters are summed; the TTFT/latency percentile SOURCES (now
    reservoir-bounded, see :class:`Reservoir`) are pooled per replica
    with each retained sample weighted by the observations it
    represents (:func:`_pooled_pcts`) — true fleet-wide tails, not an
    average of per-replica percentiles, and not biased toward
    low-traffic replicas when a busy one exceeded its cap; the wall
    clock spans the earliest first step to the latest last step across
    replicas, so ``tokens_per_sec`` is aggregate fleet throughput, not
    a per-replica mean. Replicas that never stepped contribute
    counters only."""
    t0s = [m._t0 for m in all_metrics if m._t0 is not None]
    ends = [m._t_end for m in all_metrics if m._t_end is not None]
    wall = (max(ends) - min(t0s)) if t0s and ends else 0.0
    wall = max(wall, 0.0)
    gen_tokens = sum(m.gen_tokens for m in all_metrics)

    def _true_n(src) -> int:
        return getattr(src, "n", len(src))

    def _group(src):
        return (src, _true_n(src))

    ttft_groups = [_group(m.ttfts) for m in all_metrics]
    lat_groups = [_group(m.latencies) for m in all_metrics]
    itl_groups = [_group(m.itls) for m in all_metrics]
    # per-adapter ledgers merge the same way the totals do: counters
    # summed across replicas, TTFT sources pooled (weighted) before
    # percentiles
    adapters: Dict[str, Dict] = {}
    for m in all_metrics:
        for aid, d in m.per_adapter.items():
            agg = adapters.setdefault(
                aid, {"requests": 0, "gen_tokens": 0, "groups": []})
            agg["requests"] += d["requests"]
            agg["gen_tokens"] += d["gen_tokens"]
            agg["groups"].append(_group(d["ttfts"]))
    hit = sum(m.prefix_hit_tokens for m in all_metrics)
    host_hit = sum(m.host_hit_tokens for m in all_metrics)
    prefill = sum(m.prefill_tokens for m in all_metrics)
    dsteps = sum(m.decode_steps for m in all_metrics)
    dtok = sum(m.decode_tokens for m in all_metrics)
    drafted = sum(m.draft_tokens for m in all_metrics)
    accepted = sum(m.accepted_draft_tokens for m in all_metrics)
    out = {
        "replicas": len(all_metrics),
        "steps": sum(m.steps for m in all_metrics),
        "gen_tokens": gen_tokens,
        "admitted": sum(m.admitted for m in all_metrics),
        "finished": sum(m.finished for m in all_metrics),
        "preempted": sum(m.preempted for m in all_metrics),
        "deadline_exceeded": sum(m.deadline_exceeded
                                 for m in all_metrics),
        "prefill_tokens": prefill,
        "decode_tokens": dtok,
        "prefix_hit_tokens": hit,
        "prefill_tokens_saved": hit,
        "prefix_hit_rate": round(hit / (hit + prefill), 4)
        if (hit + prefill) else 0.0,
        "decode_steps": dsteps,
        "tokens_per_decode_step": round(dtok / dsteps, 4) if dsteps
        else 0.0,
        "spec_steps": sum(m.spec_steps for m in all_metrics),
        "draft_tokens": drafted,
        "accepted_draft_tokens": accepted,
        "draft_acceptance_rate": round(accepted / drafted, 4) if drafted
        else 0.0,
        "prefill_chunks": sum(m.prefill_chunks for m in all_metrics),
        "chunk_steps": sum(m.chunk_steps for m in all_metrics),
        "chunk_tokens": sum(m.chunk_tokens for m in all_metrics),
        "chunk_tokens_per_step": round(
            sum(m.chunk_tokens for m in all_metrics)
            / max(sum(m.chunk_steps for m in all_metrics), 1), 4),
        "kv_cache_evictions": sum(m.kv_cache_evictions
                                  for m in all_metrics),
        "kv_demotions": sum(m.kv_demotions for m in all_metrics),
        "kv_promotions": sum(m.kv_promotions for m in all_metrics),
        "kv_host_evictions": sum(m.kv_host_evictions
                                 for m in all_metrics),
        "host_hit_tokens": host_hit,
        "host_hit_rate": round(host_hit / (hit + prefill), 4)
        if (hit + prefill) else 0.0,
        # fleet host-tier residency is the SUM of the replicas' tiers
        # (each replica spills to its own host RAM)
        "host_tier_bytes": sum(m.host_tier_bytes for m in all_metrics),
        "decode_blocked_demotions": sum(m.decode_blocked_demotions
                                        for m in all_metrics),
        "wall_s": round(wall, 4),
        "tokens_per_sec": round(gen_tokens / wall, 2) if wall > 0 else 0.0,
        "ttft_s": _pooled_pcts(ttft_groups),
        "latency_s": _pooled_pcts(lat_groups),
        "itl_s": _pooled_pcts(itl_groups),
        "peak_kv_utilization": round(
            max((m.peak_kv_utilization for m in all_metrics), default=0.0),
            4),
        # fleet KV memory is the SUM of the replicas' pools; bytes per
        # token is a per-replica layout property — report the worst
        # (largest) so a mixed-policy fleet surfaces its heaviest pool
        "kv_pool_bytes": sum(m.kv_pool_bytes for m in all_metrics),
        "kv_bytes_per_token": round(
            max((m.kv_bytes_per_token for m in all_metrics), default=0.0),
            4),
        # fleet weight residency is the SUM of the replicas' packed
        # trees; the dtype roll-up names every policy in play so a
        # mixed-layout fleet is legible at a glance
        "weight_bytes": sum(m.weight_bytes for m in all_metrics),
        "weights_dtype": ",".join(sorted(
            {m.weights_dtype for m in all_metrics if m.weights_dtype}))
        or "f32",
        "peak_running": max((m.peak_running for m in all_metrics),
                            default=0),
        "adapters": {
            aid: {"requests": d["requests"],
                  "gen_tokens": d["gen_tokens"],
                  "ttft_s": _pooled_pcts(d["groups"])}
            for aid, d in sorted(adapters.items())},
    }
    # MoE roll-up mirrors summary(): counters summed across replicas,
    # per-expert demand summed elementwise, keys gated on activity so
    # a dense fleet's aggregate is unchanged
    moe_routed = sum(m.moe_routed_tokens for m in all_metrics)
    moe_steps = sum(m.moe_stat_steps for m in all_metrics)
    if moe_steps or moe_routed:
        moe_dropped = sum(m.moe_dropped_tokens for m in all_metrics)
        ets = [m.moe_expert_tokens for m in all_metrics
               if m.moe_expert_tokens is not None]
        et = np.sum(ets, axis=0) if ets else None
        out["moe_routed_tokens"] = int(moe_routed)
        out["moe_dropped_tokens"] = int(moe_dropped)
        out["moe_drop_rate"] = (round(moe_dropped / moe_routed, 4)
                                if moe_routed else 0.0)
        out["moe_expert_skew"] = (
            round(float(np.max(et) / np.mean(et)), 4)
            if et is not None and float(np.sum(et)) else 0.0)
        out["moe_router_entropy"] = (
            round(sum(m.moe_entropy_sum for m in all_metrics)
                  / moe_steps, 4) if moe_steps else 0.0)
        out["moe_expert_tokens"] = (
            {str(e): int(v) for e, v in enumerate(et)}
            if et is not None else {})
    return out

"""Model-family adapters for the serving engine.

One tiny record per family (GPT-2, Llama) giving the engine a uniform
(chunked-prefill, paged-decode, partition-specs) surface. Nothing here
forks model math: prefill scans the paged block bodies
(nn/transformer.block_prefill_paged / models/llama.llama_block_prefill_paged
— the same attention math as the decode path, batched over the tail),
paged decode scans block_decode / llama_block_decode with
``block_tables`` (the nn/attention.mha_decode paged path), and
embedding/logits reuse the generate modules' vocab-parallel-aware
helpers — a fix in any of those fixes serving too.

Prefill contract (chunked, prefix-cache aware): ``prefill_from(params,
k_pool, v_pool, ids [1, P], start, t0, table_row [M], block_size,
tp_axis) -> (logits [1, V] at position t0-1, k_pool, v_pool)`` — ids
hold the UNCACHED TAIL ``tokens[start:t0]`` right-padded to the
engine's static bucket width P; positions ``[0, start)`` are already
resident in the pool blocks the table references (a prefix-cache hit,
or nothing when ``start == 0`` — cache-off and cache-on run the same
program). The tail's KV is scattered through the table, attention runs
against the gathered whole row, and the returned logits are read at
the DYNAMIC index ``t0 - 1 - start``, so one compiled program per
bucket width serves every (start, t0) split.

Decode contract: ``decode(params, k_pool, v_pool, tok [S], pos [S],
tables [S, M], block_size, tp_axis) -> (logits [S, V], k_pool, v_pool)``
— per-row positions, paged pool views, static S.

Verify contract (speculative decoding, serve/spec.py): ``verify(params,
k_pool, v_pool, ids [S, P], starts [S], tail_lens [S], tables [S, M],
block_size, tp_axis) -> (logits [S, P, V], k_pool, v_pool)`` — the
decode step widened from 1 to P tokens per row. Row s's ids hold its
last sampled token + up to P-1 drafted continuations at absolute
positions ``starts[s] + arange(P)``; columns at or beyond
``tail_lens[s]`` are pad (their KV scatters to the null block, their
logits are garbage the engine never reads). Logits come back for ALL P
positions — ``logits[s, i]`` is the next-token distribution after row
s's first i+1 run tokens — so one forward scores a whole draft + the
bonus token. The attention math is the gathered-view decode math
exactly (nn/attention.mha_verify_paged), which is what makes
verify-committed tokens bit-equal to plain decoded ones.

Quantized KV (serve/kv_quant.py): every contract additionally takes
``kv_scales=None, policy=None`` — under a SCALED layout policy (int8,
fake_quant) ``kv_scales`` is the ``(k_scale, v_scale)`` pair of
``[L, num_blocks, H_kv]`` per-block-per-head scale arrays that ride
the layer scan beside the pools, and the return tuple widens
symmetrically to ``(logits, k_pool, v_pool, k_scale, v_scale)``. The
block bodies dequantize inside the gathered view and quantize on
scatter; ``kv_scales=None`` (the passthrough policies) is
byte-identical to the pre-policy programs.

Attention backend (ops/paged_attention.py): every contract
additionally takes ``attn_kernel="xla"`` — "xla" is the gathered-view
math above (the reference oracle), "pallas" routes each block's paged
attention through the fused block-table-walking kernel
(bit-parity-pinned, tests/test_paged_attention.py). The contract
surface, collective census, and compile-count bounds are identical for
both backends; the sp path stays XLA-only (the engine rejects the
combination).

Multi-tenant LoRA (serve/adapters.py): every contract additionally
takes ``lora=None, lora_scale=None`` — a nested pytree of PACKED
per-slot adapter factors, one ``{"a": [L, S_or_1, in, r], "b": [L,
S_or_1, r, out]}`` node per targeted matmul (leading L rides the layer
scan exactly like the block params), plus the per-slot ``alpha/rank``
scales. Each targeted matmul adds its row's low-rank delta
(nn/layers.lora_delta); zero rows ARE the base model. Decode/verify
take the full [S]-slot pack; prefill (one request at a time) takes the
admitted slot's [1]-row slice. ``lora=None`` is byte-identical to the
pre-adapter programs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclass(frozen=True)
class Family:
    name: str
    cfg: Any
    n_layers: int
    n_kv_heads: int          # GLOBAL kv heads (pool head dim)
    head_dim: int
    max_positions: int
    prefill_from: Callable   # (params, kp, vp, ids, start, t0, row, bs,
    #                           tp_axis, ep_axis, lora, lora_scale)
    #                           -> (logits, kp, vp[, moe_stats])
    decode: Callable         # (params, kp, vp, tok, pos, tables, bs,
    #                           tp_axis, ep_axis, lora, lora_scale)
    verify: Callable         # (params, kp, vp, ids [S, P], starts [S],
    #                           tail_lens [S], tables, bs, tp_axis,
    #                           ep_axis, lora, lora_scale)
    #                           -> (logits [S, P, V], kp, vp[, moe_stats])
    partition_specs: Callable  # (tp_axis, ep_axis=None) -> param specs
    # MoE families (cfg.moe_args set) widen every contract's return by
    # one trailing routing-stats dict — per-expert routed counts,
    # capacity drops, assignments, router entropy, already reduced over
    # layers (_reduce_moe_stats) — and take ``ep_axis``: experts
    # sharded over the axis with one all_to_all each way per MoE layer
    # (nn/moe.py); None runs the dense-replicated MoE math.
    # sequence-parallel prefill (long-context serving, serve/longctx.py):
    # same contract as prefill_from except ids is THIS SP RANK's slice
    # [1, P/sp] of the bucket (the engine's shard_map splits dim 1) and
    # the body runs ring attention over sp_axis
    # (nn/attention.ring_paged_prefill). None = family has no sp path.
    prefill_from_sp: Optional[Callable] = None
    kv_dtype: Any = jnp.float32
    # default LoRA target names for this family's blocks (engine's
    # lora_targets default — models/lora.py ladder names)
    lora_targets: Tuple[str, ...] = ()
    # paths (relative to one block node) of the linear nodes a weight
    # layout policy packs (serve/weight_quant.py): the decode-bandwidth
    # matmuls. Embeddings, head, LNs and MoE experts stay
    # full-precision.
    weight_targets: Tuple[Tuple[str, ...], ...] = ()
    # host-side layout hook: (path, b_factor [L, r, out], tp) -> the
    # factor permuted into the layout the SERVING weights use under tp.
    # GPT-2's fused qkv stores tp-BLOCKED columns (gpt2_to_tp_layout);
    # an adapter's b trained against the standard [q|k|v] layout must
    # be re-blocked the same way before packing, or its delta would
    # land on the wrong columns. None = identity (llama: separate
    # q/k/v, column order preserved per rank).
    lora_layout: Optional[Callable] = None


# --------------------------------------------------------------------
# GPT-2
# --------------------------------------------------------------------

def _scan_xs(blocks, k_pool, v_pool, lora, kv_scales=None):
    """The layer-scan xs: block params + pool views (+ the per-layer
    (k_scale, v_scale) pair for scaled KV layout policies, + the packed
    lora tree when adapters ride — every leaf has leading L)."""
    xs = (blocks, k_pool, v_pool)
    if kv_scales is not None:
        xs = xs + tuple(kv_scales)
    if lora is not None:
        xs = xs + (lora,)
    return xs


def _scan_layer(layer, lora, scaled: bool = False):
    """(blk, kc, vc, (ks, vs)-or-None, per-layer-lora-or-None) from one
    scan slice, mirroring :func:`_scan_xs`'s packing order."""
    it = iter(layer)
    blk, kc, vc = next(it), next(it), next(it)
    sc = (next(it), next(it)) if scaled else None
    lr = next(it) if lora is not None else None
    return blk, kc, vc, sc, lr


def _reduce_moe_stats(st):
    """Layer-stacked routing stats (each leaf leading [L], the scan's
    ys) -> per-program totals: counts summed over layers, entropy
    meaned. Every value is replicated across ep/tp ranks (routing is
    computed on the replicated token batch), so the engine's shard_map
    emits them with a replicated out-spec."""
    return {
        "expert_tokens": jnp.sum(st["expert_tokens"], axis=0),
        "dropped": jnp.sum(st["dropped"]),
        "assigned": jnp.sum(st["assigned"]),
        "entropy": jnp.mean(st["entropy"]),
    }


def gpt2_family(cfg) -> Family:
    from quintnet_tpu.models.gpt2 import gpt2_partition_specs
    from quintnet_tpu.models.gpt2_generate import (_embed_tok, _local_heads,
                                                   _logits)
    from quintnet_tpu.models.lora import DEFAULT_TARGETS
    from quintnet_tpu.nn.attention import sp_last_hidden
    from quintnet_tpu.nn.layers import gelu
    from quintnet_tpu.nn.transformer import (block_decode,
                                             block_prefill_paged,
                                             block_prefill_paged_sp,
                                             block_verify_paged)

    def prefill_from(params, k_pool, v_pool, ids, start, t0, table_row,
                     block_size, tp_axis=None, ep_axis=None, lora=None,
                     lora_scale=None, kv_scales=None, policy=None,
                     attn_kernel="xla"):
        B, P = ids.shape
        emb = params["embedding"]
        positions = start + jnp.arange(P, dtype=jnp.int32)
        # pad rows may sit past n_positions; clip their (ignored) wpe read
        safe_pos = jnp.clip(positions, 0, emb["wpe"].shape[0] - 1)
        h = (_embed_tok(emb, ids, cfg, tp_axis)
             + jnp.take(emb["wpe"], safe_pos, axis=0)[None])
        heads = _local_heads(cfg, tp_axis)
        tail_len = t0 - start
        scaled = kv_scales is not None

        def body(x, layer):
            blk, kc, vc, sc, lr = _scan_layer(layer, lora, scaled)
            out = block_prefill_paged(
                blk, x, kc, vc, positions, tail_len, num_heads=heads,
                act=gelu, moe_args=cfg.moe_args, ep_axis=ep_axis,
                tp_axis=tp_axis,
                block_tables=table_row, block_size=block_size,
                lora=lr, lora_scale=lora_scale,
                kv_scales=sc, policy=policy, attn_kernel=attn_kernel)
            return out[0], out[1:]

        h, pools = lax.scan(
            body, h, _scan_xs(params["blocks"], k_pool, v_pool, lora,
                              kv_scales))
        if cfg.moe_args is not None:
            *pools, st = pools
            pools = (*pools, _reduce_moe_stats(st))
        h_last = lax.dynamic_slice_in_dim(h, t0 - 1 - start, 1, axis=1)
        return (_logits(params, h_last, cfg, tp_axis)[:, 0, :], *pools)

    def decode(params, k_pool, v_pool, tok, pos, tables, block_size,
               tp_axis=None, ep_axis=None, lora=None, lora_scale=None,
               kv_scales=None, policy=None, attn_kernel="xla"):
        emb = params["embedding"]
        x = (_embed_tok(emb, tok[:, None], cfg, tp_axis)
             + jnp.take(emb["wpe"], pos, axis=0)[:, None, :])
        heads = _local_heads(cfg, tp_axis)
        scaled = kv_scales is not None

        def body(h, layer):
            blk, kc, vc, sc, lr = _scan_layer(layer, lora, scaled)
            out = block_decode(blk, h, kc, vc, pos, num_heads=heads,
                               act=gelu, moe_args=cfg.moe_args,
                               ep_axis=ep_axis,
                               tp_axis=tp_axis, block_tables=tables,
                               block_size=block_size,
                               lora=lr, lora_scale=lora_scale,
                               kv_scales=sc, policy=policy,
                               attn_kernel=attn_kernel)
            return out[0], out[1:]

        h, pools = lax.scan(
            body, x, _scan_xs(params["blocks"], k_pool, v_pool, lora,
                              kv_scales))
        if cfg.moe_args is not None:
            *pools, st = pools
            pools = (*pools, _reduce_moe_stats(st))
        return (_logits(params, h, cfg, tp_axis)[:, 0, :], *pools)

    def verify(params, k_pool, v_pool, ids, starts, tail_lens, tables,
               block_size, tp_axis=None, ep_axis=None, lora=None,
               lora_scale=None, kv_scales=None, policy=None,
               attn_kernel="xla"):
        S, P = ids.shape
        emb = params["embedding"]
        positions = (starts[:, None]
                     + jnp.arange(P, dtype=jnp.int32)[None, :])  # [S, P]
        safe_pos = jnp.clip(positions, 0, emb["wpe"].shape[0] - 1)
        h = (_embed_tok(emb, ids, cfg, tp_axis)
             + jnp.take(emb["wpe"], safe_pos, axis=0))
        heads = _local_heads(cfg, tp_axis)
        scaled = kv_scales is not None

        def body(x, layer):
            blk, kc, vc, sc, lr = _scan_layer(layer, lora, scaled)
            out = block_verify_paged(
                blk, x, kc, vc, positions, tail_lens, num_heads=heads,
                act=gelu, moe_args=cfg.moe_args, ep_axis=ep_axis,
                tp_axis=tp_axis,
                block_tables=tables, block_size=block_size,
                lora=lr, lora_scale=lora_scale,
                kv_scales=sc, policy=policy, attn_kernel=attn_kernel)
            return out[0], out[1:]

        h, pools = lax.scan(
            body, h, _scan_xs(params["blocks"], k_pool, v_pool, lora,
                              kv_scales))
        if cfg.moe_args is not None:
            *pools, st = pools
            pools = (*pools, _reduce_moe_stats(st))
        return (_logits(params, h, cfg, tp_axis), *pools)

    def prefill_from_sp(params, k_pool, v_pool, ids, start, t0,
                        table_row, block_size, *, sp_axis: str,
                        tp_axis=None, kv_scales=None, policy=None):
        # ids: [1, P/sp] — THIS sp rank's slice of the padded chunk
        # (the engine shard_maps the bucket over sp); positions are the
        # rank's absolute offsets, so embedding/rope/masking all land
        # exactly where the single-device program puts them
        B, Pl = ids.shape
        idx = lax.axis_index(sp_axis)
        emb = params["embedding"]
        positions = (start + idx * Pl
                     + jnp.arange(Pl, dtype=jnp.int32))
        safe_pos = jnp.clip(positions, 0, emb["wpe"].shape[0] - 1)
        h = (_embed_tok(emb, ids, cfg, tp_axis)
             + jnp.take(emb["wpe"], safe_pos, axis=0)[None])
        heads = _local_heads(cfg, tp_axis)
        scaled = kv_scales is not None

        def body(x, layer):
            blk, kc, vc, sc, _ = _scan_layer(layer, None, scaled)
            out = block_prefill_paged_sp(
                blk, x, kc, vc, start, t0, num_heads=heads,
                sp_axis=sp_axis, act=gelu, moe_args=cfg.moe_args,
                tp_axis=tp_axis, block_tables=table_row,
                block_size=block_size, kv_scales=sc, policy=policy)
            return out[0], out[1:]

        h, pools = lax.scan(
            body, h, _scan_xs(params["blocks"], k_pool, v_pool, None,
                              kv_scales))
        h_last = sp_last_hidden(h, start, t0, sp_axis=sp_axis)
        return (_logits(params, h_last, cfg, tp_axis)[:, 0, :], *pools)

    def lora_layout(path, b, tp):
        # fused qkv columns are tp-BLOCKED in the serving layout
        # (parallel/tp.py gpt2_to_tp_layout); re-block the adapter's b
        # the same way so its delta lands on the matching columns
        if path[-1] == "qkv" and tp > 1:
            from quintnet_tpu.parallel.tp import qkv_blocked_from_standard

            return qkv_blocked_from_standard(b, cfg.n_head, tp)
        return b

    return Family(
        name="gpt2", cfg=cfg, n_layers=cfg.n_layer, n_kv_heads=cfg.n_head,
        head_dim=cfg.n_embd // cfg.n_head, max_positions=cfg.n_positions,
        prefill_from=prefill_from, decode=decode, verify=verify,
        prefill_from_sp=prefill_from_sp,
        partition_specs=lambda tp_axis, ep_axis=None: gpt2_partition_specs(
            cfg, tp_axis=tp_axis, ep_axis=ep_axis),
        lora_targets=DEFAULT_TARGETS, lora_layout=lora_layout,
        weight_targets=(("attn", "qkv"), ("attn", "proj"),
                        ("mlp", "fc"), ("mlp", "proj")),
    )


# --------------------------------------------------------------------
# Llama (GQA: the pool holds UNrepeated kv heads)
# --------------------------------------------------------------------

def llama_family(cfg) -> Family:
    from quintnet_tpu.models.llama import (llama_block_decode,
                                           llama_block_prefill_paged,
                                           llama_block_prefill_paged_sp,
                                           llama_block_verify_paged,
                                           llama_partition_specs,
                                           llama_rope_tables)
    from quintnet_tpu.models.llama_generate import _embed, _full_logits
    from quintnet_tpu.models.lora import LLAMA_TARGETS
    from quintnet_tpu.nn.attention import sp_last_hidden

    def prefill_from(params, k_pool, v_pool, ids, start, t0, table_row,
                     block_size, tp_axis=None, ep_axis=None, lora=None,
                     lora_scale=None, kv_scales=None, policy=None,
                     attn_kernel="xla"):
        B, P = ids.shape
        h = _embed(params, ids, cfg, tp_axis)
        positions = start + jnp.arange(P, dtype=jnp.int32)
        cos, sin = llama_rope_tables(positions, cfg)      # [P, hd]
        tail_len = t0 - start
        scaled = kv_scales is not None

        def body(x, layer):
            blk, kc, vc, sc, lr = _scan_layer(layer, lora, scaled)
            x, pools = llama_block_prefill_paged(
                blk, x, kc, vc, positions, tail_len, cfg, cos, sin,
                tp_axis=tp_axis, ep_axis=ep_axis, block_tables=table_row,
                block_size=block_size, lora=lr, lora_scale=lora_scale,
                kv_scales=sc, policy=policy, attn_kernel=attn_kernel)
            return x, pools

        h, pools = lax.scan(
            body, h, _scan_xs(params["blocks"], k_pool, v_pool, lora,
                              kv_scales))
        if cfg.moe_args is not None:
            *pools, st = pools
            pools = (*pools, _reduce_moe_stats(st))
        h_last = lax.dynamic_slice_in_dim(h, t0 - 1 - start, 1, axis=1)
        return (_full_logits(params, h_last, cfg, tp_axis)[:, 0, :],
                *pools)

    def decode(params, k_pool, v_pool, tok, pos, tables, block_size,
               tp_axis=None, ep_axis=None, lora=None, lora_scale=None,
               kv_scales=None, policy=None, attn_kernel="xla"):
        x = _embed(params, tok[:, None], cfg, tp_axis)        # [S, 1, D]
        cos, sin = llama_rope_tables(pos, cfg)                # [S, hd]
        cos, sin = cos[:, None, None, :], sin[:, None, None, :]
        scaled = kv_scales is not None

        def body(h, layer):
            blk, kc, vc, sc, lr = _scan_layer(layer, lora, scaled)
            h, pools = llama_block_decode(
                blk, h, kc, vc, pos, cfg, cos, sin, tp_axis=tp_axis,
                ep_axis=ep_axis,
                block_tables=tables, block_size=block_size,
                lora=lr, lora_scale=lora_scale,
                kv_scales=sc, policy=policy, attn_kernel=attn_kernel)
            return h, pools

        h, pools = lax.scan(
            body, x, _scan_xs(params["blocks"], k_pool, v_pool, lora,
                              kv_scales))
        if cfg.moe_args is not None:
            *pools, st = pools
            pools = (*pools, _reduce_moe_stats(st))
        return (_full_logits(params, h, cfg, tp_axis)[:, 0, :], *pools)

    def verify(params, k_pool, v_pool, ids, starts, tail_lens, tables,
               block_size, tp_axis=None, ep_axis=None, lora=None,
               lora_scale=None, kv_scales=None, policy=None,
               attn_kernel="xla"):
        S, P = ids.shape
        h = _embed(params, ids, cfg, tp_axis)                 # [S, P, D]
        positions = (starts[:, None]
                     + jnp.arange(P, dtype=jnp.int32)[None, :])
        cos, sin = llama_rope_tables(positions, cfg)          # [S, P, hd]
        cos, sin = cos[:, None], sin[:, None]                 # [S,1,P,hd]
        scaled = kv_scales is not None

        def body(x, layer):
            blk, kc, vc, sc, lr = _scan_layer(layer, lora, scaled)
            x, pools = llama_block_verify_paged(
                blk, x, kc, vc, positions, tail_lens, cfg, cos, sin,
                tp_axis=tp_axis, ep_axis=ep_axis, block_tables=tables,
                block_size=block_size, lora=lr, lora_scale=lora_scale,
                kv_scales=sc, policy=policy, attn_kernel=attn_kernel)
            return x, pools

        h, pools = lax.scan(
            body, h, _scan_xs(params["blocks"], k_pool, v_pool, lora,
                              kv_scales))
        if cfg.moe_args is not None:
            *pools, st = pools
            pools = (*pools, _reduce_moe_stats(st))
        return (_full_logits(params, h, cfg, tp_axis), *pools)

    def prefill_from_sp(params, k_pool, v_pool, ids, start, t0,
                        table_row, block_size, *, sp_axis: str,
                        tp_axis=None, kv_scales=None, policy=None):
        # ids: [1, P/sp] — this sp rank's chunk slice; rope tables come
        # from the rank's LOCAL absolute positions
        B, Pl = ids.shape
        idx = lax.axis_index(sp_axis)
        h = _embed(params, ids, cfg, tp_axis)
        positions = (start + idx * Pl
                     + jnp.arange(Pl, dtype=jnp.int32))
        cos, sin = llama_rope_tables(positions, cfg)      # [Pl, hd]
        scaled = kv_scales is not None

        def body(x, layer):
            blk, kc, vc, sc, _ = _scan_layer(layer, None, scaled)
            x, pools = llama_block_prefill_paged_sp(
                blk, x, kc, vc, start, t0, cfg, cos, sin,
                sp_axis=sp_axis, tp_axis=tp_axis,
                block_tables=table_row, block_size=block_size,
                kv_scales=sc, policy=policy)
            return x, pools

        h, pools = lax.scan(
            body, h, _scan_xs(params["blocks"], k_pool, v_pool, None,
                              kv_scales))
        h_last = sp_last_hidden(h, start, t0, sp_axis=sp_axis)
        return (_full_logits(params, h_last, cfg, tp_axis)[:, 0, :],
                *pools)

    return Family(
        name="llama", cfg=cfg, n_layers=cfg.n_layers,
        n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        max_positions=cfg.n_positions,
        prefill_from=prefill_from, decode=decode, verify=verify,
        prefill_from_sp=prefill_from_sp,
        partition_specs=lambda tp_axis, ep_axis=None: llama_partition_specs(
            cfg, tp_axis=tp_axis, ep_axis=ep_axis),
        lora_targets=LLAMA_TARGETS,
        weight_targets=(("attn", "q"), ("attn", "k"), ("attn", "v"),
                        ("attn", "o"), ("mlp", "gate"), ("mlp", "up"),
                        ("mlp", "down")),
    )

"""Weight layout policies: what dtype the serving matmul weights are
stored in, and how they get there.

KV capacity is solved (serve_r14: 4.1x usable blocks at equal bytes),
which leaves decode WEIGHT-bandwidth-bound — at serving batch sizes
the weights dominate bytes moved per token (the KVQuant framing;
AWQ/LLM.int8 attack the same bottleneck from the weights side). This
module makes the packed-weight dtype a POLICY OBJECT on the shared
:class:`~quintnet_tpu.serve.kv_quant.LayoutPolicy` contract, so
weights and KV consume ONE quantize/dequant/scale-layout protocol:

- ``f32`` — the identity: ``quantize_params`` returns the tree
  UNTOUCHED (same arrays, same bytes — the pre-policy engine).
- ``bf16`` — passthrough narrowing: weights stored bf16, upcast by
  jax's native promotion inside the dot. Half the bytes, no scales.
- ``int8`` — PER-OUTPUT-CHANNEL absmax (``scale[l, o] = max_i
  |w[l, i, o]| / 127``, f32, stored as a ``w_scale`` leaf BESIDE the
  packed ``w``). The channel is the quantization group because the
  scale then commutes out of the contraction: ``x @ dq(w) = (x @ q)
  * scale`` — dequant happens INSIDE the matmul
  (nn/layers.quantized_matmul) as one cheap per-column multiply, and
  the packed weight is never materialized wide.
- ``fp8`` — scaled ``float8_e4m3fn`` storage (qmax 448, the e4m3
  finite max): same per-channel scales, but the narrowing cast keeps
  the fraction (no integer rounding) — e4m3's mantissa does the
  rounding. Same 4x byte ratio as int8 with a float-shaped error.
- ``fake_quant`` — the PROOF policy: f32 storage, all-ones scales,
  the full scaled code path (pack -> quantized_matmul -> per-channel
  multiply) with quantization mathematically the identity. An engine
  on ``fake_quant`` weights is BIT-IDENTICAL to the f32 engine, which
  pins the quantized-matmul seam as numerically inert and leaves the
  rounding itself as the only quality variable (gated by the
  paged_eval_nll ppl delta + the per-channel round-trip bound).

Quantization happens ONCE at engine build (``ServeEngine(
weights_dtype=...)``), host-side, AFTER adapter setup — the LoRA
delta path stays full-precision on top (nn/layers.lora_delta computes
from activations and adds after the scaled dot, exactly where a
merged weight would land). Under tp the ``w_scale`` leaf shards
exactly like the out-dim of the weight it scales
(:func:`augment_weight_specs`: column-parallel scales shard with the
columns, row-parallel scales replicate), so zero new collectives and
ZERO new compiled programs per policy — the policy is baked into the
param tree before the first trace (ladder pinned in
analysis/specs.weight_layout_policies, compile bound unchanged).

The targeted nodes are the family's ``weight_targets``
(serve/families.py; gpt2: qkv/proj/fc, llama: q/k/v/o/gate/up/down).
Embeddings, logits head, LayerNorms and MoE experts stay
full-precision — they are either bandwidth-cheap per token or
precision-critical (the router-ordering lesson, nn/layers.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

import jax.numpy as jnp

from quintnet_tpu.serve.kv_quant import FLOAT8_DTYPE, LayoutPolicy


@dataclass(frozen=True)
class WeightLayoutPolicy(LayoutPolicy):
    """The weights face of :class:`LayoutPolicy`: per-output-channel
    absmax groups (axes = the in-features dim) instead of per-block
    KV groups. All quant math is inherited — one contract."""


_WEIGHT_POLICIES = {
    "f32": WeightLayoutPolicy("f32", jnp.float32, scaled=False),
    "bf16": WeightLayoutPolicy("bf16", jnp.bfloat16, scaled=False),
    "int8": WeightLayoutPolicy("int8", jnp.int8, scaled=True,
                               qmax=127.0),
    "fp8": WeightLayoutPolicy("fp8", FLOAT8_DTYPE, scaled=True,
                              qmax=448.0),
    "fake_quant": WeightLayoutPolicy("fake_quant", jnp.float32,
                                     scaled=True, qmax=0.0),
}


def weight_policy_names() -> Tuple[str, ...]:
    """The canonical weight-policy ladder (pinned in analysis/specs.py —
    compile counts are UNCHANGED per policy)."""
    return tuple(_WEIGHT_POLICIES)


def make_weight_policy(weights_dtype) -> WeightLayoutPolicy:
    """Resolve ``ServeEngine(weights_dtype=...)`` input to a policy: a
    policy passes through, a name looks up the ladder, a raw
    f32/bf16 dtype maps to its passthrough policy, None is f32 (the
    pre-policy engine, byte-identical)."""
    if weights_dtype is None:
        return _WEIGHT_POLICIES["f32"]
    if isinstance(weights_dtype, WeightLayoutPolicy):
        return weights_dtype
    if isinstance(weights_dtype, str):
        if weights_dtype not in _WEIGHT_POLICIES:
            raise ValueError(
                f"unknown weights_dtype {weights_dtype!r}; expected one "
                f"of {weight_policy_names()}")
        pol = _WEIGHT_POLICIES[weights_dtype]
        if pol.store_dtype is None:
            raise ValueError(
                f"weights_dtype {weights_dtype!r} needs "
                "jnp.float8_e4m3fn, which this jax build does not "
                "provide")
        return pol
    dt = jnp.dtype(weights_dtype)
    if dt == jnp.dtype(jnp.float32):
        return _WEIGHT_POLICIES["f32"]
    if dt == jnp.dtype(jnp.bfloat16):
        return _WEIGHT_POLICIES["bf16"]
    raise ValueError(
        f"no weight policy for dtype {dt}; use one of "
        f"{weight_policy_names()}")


# ---------------------------------------------------------------------
# tree surgery (host-side, once at engine build)
# ---------------------------------------------------------------------

def _node_at(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _with_node(tree, path, node):
    """Functional path-replace: shallow-copies dicts along ``path``
    only — untouched siblings keep their identity (and their device
    buffers)."""
    if not path:
        return node
    out = dict(tree)
    out[path[0]] = _with_node(tree[path[0]], path[1:], node)
    return out


def present_targets(params, targets) -> Tuple[Tuple[str, ...], ...]:
    """Filter a family's ``weight_targets`` to the paths that actually
    exist in THIS param tree — an MoE block swaps ``mlp`` for ``moe``
    (experts stay full-precision), so the dense-mlp targets simply
    drop out instead of KeyError-ing."""
    out = []
    for path in targets:
        node = params["blocks"]
        for k in path:
            if not isinstance(node, dict) or k not in node:
                node = None
                break
            node = node[k]
        if isinstance(node, dict) and "w" in node:
            out.append(path)
    return tuple(out)


def _quantize_node(node, policy):
    """One targeted linear node {w: [L, in, out](, b)} -> its packed
    form: ``w`` narrowed to the store dtype, plus a per-output-channel
    ``w_scale`` [L, out] f32 leaf when scaled. Bias (and any LoRA
    machinery outside the tree) stays full-precision."""
    w = node["w"]
    out = dict(node)
    if policy.scaled:
        scale = policy.compute_scale(w, axes=(-2,))        # [L, out]
        out["w"] = policy.quant(w, jnp.expand_dims(scale, -2))
        out["w_scale"] = scale
    else:
        out["w"] = w.astype(policy.store_dtype)
    return out


def quantize_params(params, targets, policy: WeightLayoutPolicy):
    """Pack every ``targets`` path under ``params["blocks"]`` per the
    policy. The f32 policy returns ``params`` UNCHANGED (same object:
    the byte-identical pre-policy engine); every other policy replaces
    only the targeted nodes."""
    if policy.name == "f32":
        return params
    blocks = params["blocks"]
    for path in targets:
        node = _node_at(blocks, path)
        blocks = _with_node(blocks, path, _quantize_node(node, policy))
    return {**params, "blocks": blocks}


def weight_bytes(params, targets) -> int:
    """Device bytes of the TARGETED weight nodes (packed ``w`` +
    ``w_scale`` where present) — the number the int8 A/B gate ratios
    (>= 3.5x vs f32 on the same targets; whole-tree bytes would be
    embedding-diluted on tiny configs)."""
    total = 0
    blocks = params["blocks"]
    for path in targets:
        node = _node_at(blocks, path)
        total += int(node["w"].size) * jnp.dtype(node["w"].dtype).itemsize
        if "w_scale" in node:
            total += (int(node["w_scale"].size)
                      * jnp.dtype(node["w_scale"].dtype).itemsize)
    return int(total)


def augment_weight_specs(specs, targets):
    """Mirror :func:`quantize_params`'s tree surgery on a partition-spec
    tree: each targeted node gains a ``w_scale`` spec sharded exactly
    like the OUT dim of its weight — ``P(lead, out)`` from the weight's
    ``P(lead, in, out)``. Column-parallel scales shard with their
    columns; row-parallel scales replicate (their psum-side out dim is
    unsharded). Call only when the policy is scaled (the spec tree must
    match the param tree leaf-for-leaf under shard_map)."""
    from jax.sharding import PartitionSpec as P

    blocks = specs["blocks"]
    for path in targets:
        node = _node_at(blocks, path)
        w = tuple(node["w"])
        w = w + (None,) * (3 - len(w))
        blocks = _with_node(blocks, path, {**node,
                                           "w_scale": P(w[0], w[2])})
    return {**specs, "blocks": blocks}

"""Paged KV-cache pool: refcounted blocks + prefix cache + free list.

The dense decoders allocate [L, B, H, T_max, Dh] per batch — every
request pays for the longest possible sequence. Here KV memory is a
single pool of ``num_blocks`` blocks of ``block_size`` token slots,
shared by all in-flight requests; each request owns just the blocks its
current length needs (vLLM's PagedAttention memory model). Fragmentation
is bounded to < 1 block per request and T_max padding disappears.

Device layout (per k and v): ``[L, num_blocks * block_size, H_kv, Dh]``
— the flat "slot" dim is what nn/attention.paged_cache_update scatters
into and paged_gather pages out of; keeping L leading lets the decode
step lax.scan over layers exactly like the dense path. Under TP the
H_kv dim is head-sharded over the mesh (each rank holds its local
heads' pool, same invariant as the dense TP cache). WHAT a slot stores
is a :class:`~quintnet_tpu.serve.kv_quant.KVLayoutPolicy`: f32/bf16
passthrough, or int8 with per-block-per-head absmax scales carried in
``[L, num_blocks, H_kv]`` f32 arrays beside the pools (head-sharded
the same way) — same pool bytes, ~4x the blocks.

Block 0 is permanently reserved as the NULL block: inactive engine
slots point their table rows (and positions) at it, so masked rows'
scatters land in memory nobody reads and the decode step needs no
dynamic shapes. The allocator therefore hands out blocks [1, num_blocks).

Allocation is host-side bookkeeping — the device arrays never reshape;
"allocating" a block just means an engine slot's block table starts
referencing it.

Prefix caching (the PagedAttention sharing model + SGLang-style prefix
reuse, block-granular):

- every block carries a **refcount** — the number of live block tables
  (plus transient admission pins) referencing it; ``acquire``/``release``
  replace grow-only alloc/free with share-aware accounting;
- a **prefix index** maps ``token_ids[:n].tobytes()`` -> the pool block
  holding positions ``[n - fill, n)`` of that exact token chain. Full
  blocks are keyed at block boundaries (``n = (j+1) * block_size``); a
  final partially-filled block is keyed at its exact token count. The
  full-token key (not a hash) makes collisions impossible — a wrong
  match would silently corrupt the golden token-parity contract;
- on retire/preempt the engine **publishes** a request's blocks into
  the index instead of freeing them; a published block whose refcount
  drops to zero is RETAINED in an LRU set rather than pushed onto the
  free list. Allocation consumes the LIFO free list first (warm pages)
  and only then **evicts** the least-recently-touched cached block —
  cached-but-unreferenced memory is free memory that happens to still
  be useful;
- a later request with the same token prefix re-acquires the cached
  chain (refcount back up, table entries cloned) and prefills only the
  uncached tail. When the reusable chain ends inside a partially-filled
  block, the engine **copies-on-write**: the cached block's filled
  slots are copied into a private block before the new request writes
  its own (diverging) continuation — the cached copy is immutable while
  the index references it.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

import jax.numpy as jnp

from quintnet_tpu.serve.kv_quant import KVLayoutPolicy, make_policy
from quintnet_tpu.serve.kv_tier import HostTier

NULL_BLOCK = 0


@dataclass
class AdmitPlan:
    """Host-side admission plan for one request's token sequence.

    ``cached_tokens`` positions are served from the prefix index:
    ``shared_blocks`` are re-referenced whole (read-only, one refcount
    each), and — when the chain ends inside a partially-filled block —
    ``cow_src`` names the cached block whose first ``cow_len`` slots
    must be copied into the request's first private block before its
    tail is written (copy-on-write). ``n_new_blocks`` private blocks
    complete the table."""

    cached_tokens: int                  # prefill starts at this offset
    shared_blocks: List[int] = field(default_factory=list)
    cow_src: Optional[int] = None
    cow_len: int = 0
    n_new_blocks: int = 0

    @property
    def pinned_blocks(self) -> List[int]:
        """Blocks that must be refcount-pinned before any allocation
        (allocation may evict refcount-zero cached blocks — including,
        without the pin, the very chain this plan reuses)."""
        return self.shared_blocks + (
            [self.cow_src] if self.cow_src is not None else [])


class KVPool:
    """Refcounted block allocator + prefix cache over paged KV storage.

    ``n_kv_heads`` is the GLOBAL kv-head count; pass ``sharding`` (a
    ``jax.sharding.NamedSharding`` with the head dim on the tp axis) to
    lay the pool out head-sharded for a TP engine. ``prefix_cache=False``
    disables the index entirely (lookup misses, publish is a no-op,
    release always frees) — the A/B switch tools/serve_bench.py flips.
    """

    def __init__(self, *, n_layers: int, n_kv_heads: int, head_dim: int,
                 block_size: int, num_blocks: int, dtype=jnp.float32,
                 policy: "KVLayoutPolicy | str | None" = None,
                 sharding=None, scale_sharding=None,
                 prefix_cache: bool = True,
                 host_tier: Optional[HostTier] = None):
        if block_size < 1 or num_blocks < 2:
            raise ValueError(
                f"need block_size >= 1 and num_blocks >= 2 (block 0 is "
                f"the reserved null block); got {block_size}, {num_blocks}")
        self.n_layers = n_layers
        self.n_kv_heads = n_kv_heads
        self.head_dim = head_dim
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.prefix_cache = bool(prefix_cache)
        # layout policy (serve/kv_quant.py): ``policy`` wins; the plain
        # ``dtype`` arg (the pre-policy surface) maps to its
        # passthrough policy. Scaled policies additionally allocate one
        # f32 per-block-per-head scale array per pool — under tp the
        # head dim shards exactly like the pool's (``scale_sharding``).
        self.policy: KVLayoutPolicy = make_policy(
            policy if policy is not None else dtype)
        shape = (n_layers, num_blocks * block_size, n_kv_heads, head_dim)
        k = jnp.zeros(shape, self.policy.store_dtype)
        v = jnp.zeros(shape, self.policy.store_dtype)
        k_scale = v_scale = None
        if self.policy.scaled:
            k_scale = jnp.ones((n_layers, num_blocks, n_kv_heads),
                               jnp.float32)
            v_scale = jnp.ones((n_layers, num_blocks, n_kv_heads),
                               jnp.float32)
        if sharding is not None:
            import jax

            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
            if k_scale is not None and scale_sharding is not None:
                k_scale = jax.device_put(k_scale, scale_sharding)
                v_scale = jax.device_put(v_scale, scale_sharding)
        self.k = k
        self.v = v
        self.k_scale = k_scale
        self.v_scale = v_scale
        # LIFO free list: reuse recently-freed blocks first (warm pages).
        # The membership set keeps release's double-free check O(1)
        # instead of an O(free-list) scan per block.
        self._free: List[int] = list(range(num_blocks - 1, NULL_BLOCK, -1))
        self._free_set: Set[int] = set(self._free)
        self._ref: List[int] = [0] * num_blocks
        # prefix index: token-prefix bytes -> block id (and its inverse)
        self._index: Dict[bytes, int] = {}
        self._block_key: Dict[int, bytes] = {}
        self._block_fill: Dict[int, int] = {}     # published slots used
        # refcount-zero published blocks, retained for reuse until the
        # free list runs dry; evicted least-recently-touched first
        self._cached_free: Set[int] = set()
        self._lru: Dict[int, int] = {}
        self._touch_counter = 0
        # lazy-deletion eviction heap over (touch stamp, block): every
        # touch pushes, eviction pops until an entry matches the
        # block's CURRENT stamp — O(log touches) per eviction instead
        # of min() over the whole retention set, which matters once
        # eviction means a device->host demotion copy
        self._lru_heap: List[Tuple[int, int]] = []
        # host-RAM second tier (serve/kv_tier.py): eviction demotes
        # published blocks here instead of destroying them. Meaningful
        # only under the prefix cache — there is nothing to spill when
        # nothing is retained.
        self.host_tier = host_tier if self.prefix_cache else None
        # eviction counter (hit accounting lives in ServeMetrics,
        # which sees per-admission cached-token counts)
        self.cache_evictions = 0
        # blocks acquired for a SPECULATIVE tail (serve/spec.py):
        # referenced like any private block, but their slots hold
        # unverified draft KV until the engine commits or rolls back —
        # the prefix index must never see them (publish() refuses)
        self._tentative: Set[int] = set()

    # ---- accounting -------------------------------------------------
    @property
    def bytes_per_block(self) -> int:
        """Device bytes one block costs under this pool's layout
        policy (k + v slot data across layers + the per-block scale
        rows when scaled). Policy-aware: int8 blocks cost ~1/4 of f32
        ones, so the same pool bytes hold ~4x the blocks — THE
        capacity-is-concurrency equation tools/serve_bench.py's
        --kv-capacity A/B solves for equal bytes."""
        return self.policy.bytes_per_block(
            n_layers=self.n_layers, n_kv_heads=self.n_kv_heads,
            head_dim=self.head_dim, block_size=self.block_size)

    @property
    def pool_bytes(self) -> int:
        """Total device bytes of the pool's KV storage (+ scales)."""
        return self.num_blocks * self.bytes_per_block

    @property
    def bytes_per_token(self) -> float:
        """Device bytes one resident token position costs."""
        return self.bytes_per_block / self.block_size

    @property
    def usable_blocks(self) -> int:
        """Blocks available to requests (null block excluded)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        """Truly free blocks (not referenced, not cached)."""
        return len(self._free)

    @property
    def num_cached(self) -> int:
        """Refcount-zero blocks retained by the prefix index —
        reusable as cache hits, evictable on demand."""
        return len(self._cached_free)

    @property
    def num_available(self) -> int:
        """Blocks an acquire can produce: free + evictable cached."""
        return len(self._free) + len(self._cached_free)

    @property
    def num_used(self) -> int:
        """Blocks referenced by at least one live block table."""
        return self.usable_blocks - self.num_free - self.num_cached

    @property
    def utilization(self) -> float:
        return self.num_used / max(self.usable_blocks, 1)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` token slots."""
        return -(-n_tokens // self.block_size)

    def can_acquire(self, n: int) -> bool:
        return n <= self.num_available

    def refcount(self, block: int) -> int:
        return self._ref[block]

    def is_cached(self, block: int) -> bool:
        """Is the block referenced by the prefix index (published)?"""
        return block in self._block_key

    # ---- acquire / release ------------------------------------------
    def _touch(self, b: int) -> None:
        self._touch_counter += 1
        self._lru[b] = self._touch_counter
        heapq.heappush(self._lru_heap, (self._touch_counter, b))
        if len(self._lru_heap) > 8 * self.num_blocks + 64:
            # lazy-deletion debt outgrew the pool: rebuild from the
            # live stamps (at most one entry per touched block)
            self._lru_heap = [(s, blk) for blk, s in self._lru.items()]
            heapq.heapify(self._lru_heap)

    def _evict_lru(self) -> int:
        """Drop the least-recently-touched refcount-zero cached block
        from the index and hand it back as a plain free block —
        demoting its slot data to the host tier first when one is
        attached, so the chain survives as a host-hit instead of
        costing a future re-prefill. Only unreferenced blocks are
        candidates, so an evicted block is — by construction —
        unreachable from every live block table. Heap entries whose
        stamp is no longer the block's current one are stale (the
        block was re-touched, re-referenced, or already evicted) and
        are discarded on pop."""
        while self._lru_heap:
            stamp, b = heapq.heappop(self._lru_heap)
            if b in self._cached_free and self._lru.get(b) == stamp:
                break
        else:
            # unreachable while the heap invariant holds (every cached
            # block's latest touch is in the heap); kept as a guard so
            # a bookkeeping bug degrades to the old O(n) scan instead
            # of corrupting the allocator
            b = min(self._cached_free, key=self._lru.__getitem__)
        if self.host_tier is not None:
            self._demote(b)
        self._cached_free.remove(b)
        self._unpublish(b)
        self.cache_evictions += 1
        return b

    def _demote(self, b: int) -> bool:
        """Copy published block ``b`` to the host tier before eviction
        destroys it: one export-format record — the full block's slot
        data exactly as stored (``store_dtype``) plus its scale rows
        when the policy is scaled — keyed by the block's prefix-index
        key, so the host tier walks the same key ladder the device
        index does. A device->host copy on the ALLOCATION path only:
        the engine's step phasing keeps it off every decode dispatch."""
        key = self._block_key.get(b)
        fill = self._block_fill.get(b, 0)
        if key is None or fill <= 0:
            return False
        bs = self.block_size
        rec = {"fill": int(fill),
               "k": np.asarray(self.k[:, b * bs:(b + 1) * bs]),
               "v": np.asarray(self.v[:, b * bs:(b + 1) * bs])}
        if self.policy.scaled:
            rec["k_scale"] = np.asarray(self.k_scale[:, b])
            rec["v_scale"] = np.asarray(self.v_scale[:, b])
        return self.host_tier.put(key, rec)

    def _unpublish(self, b: int) -> None:
        key = self._block_key.pop(b, None)
        if key is not None and self._index.get(key) == b:
            del self._index[key]
        self._block_fill.pop(b, None)
        self._lru.pop(b, None)

    def acquire(self, n: int) -> Optional[List[int]]:
        """Take ``n`` private blocks (refcount 1 each): pop the LIFO
        free list first, then evict LRU cached blocks. Returns None if
        even eviction cannot cover ``n`` (caller decides whether to
        wait or preempt — the pool never partially allocates)."""
        if n > self.num_available:
            return None
        taken: List[int] = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
                self._free_set.remove(b)
            else:
                b = self._evict_lru()
            self._ref[b] = 1
            taken.append(b)
        return taken

    def acquire_cached(self, blocks: Sequence[int]) -> None:
        """Pin cached/shared blocks for one more holder (a cache hit:
        the admitting request's table will reference them, or a
        transient COW-source pin for the duration of one prefill).
        Refcount-zero blocks leave the evictable retention set."""
        for b in blocks:
            if self._ref[b] == 0:
                if b not in self._cached_free:
                    raise ValueError(
                        f"block {b} is neither referenced nor cached — "
                        f"cannot acquire it as a prefix hit")
                self._cached_free.remove(b)
            self._ref[b] += 1
            self._touch(b)

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference per listed block. A block reaching
        refcount zero returns to the free list — unless it is published
        in the prefix index, in which case it is RETAINED (evictable,
        LRU) for future prefix hits. O(1) per block."""
        need: Dict[int, int] = {}
        for b in blocks:
            if not (NULL_BLOCK < b < self.num_blocks):
                raise ValueError(f"releasing invalid block id {b}")
            need[b] = need.get(b, 0) + 1
            if b in self._free_set or need[b] > self._ref[b]:
                raise ValueError(f"double free of block {b}")
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] == 0:
                if b in self._block_key:
                    self._cached_free.add(b)
                else:
                    self._free.append(b)
                    self._free_set.add(b)

    # ---- tentative (speculative-tail) blocks -------------------------
    def is_tentative(self, block: int) -> bool:
        return block in self._tentative

    @property
    def num_tentative(self) -> int:
        return len(self._tentative)

    def tentative_acquire(self, n: int) -> Optional[List[int]]:
        """Take ``n`` private blocks for a SPECULATIVE tail: drafted
        slots will be written into them before verification resolves.
        Same allocator as :meth:`acquire` (free list first, then LRU
        eviction, never partial), but the blocks are marked tentative
        until :meth:`commit_tentative` or :meth:`rollback_tentative` —
        the engine resolves every tentative block within the step that
        acquired it, so publish/index state never observes one."""
        got = self.acquire(n)
        if got is not None:
            self._tentative.update(got)
        return got

    def commit_tentative(self, blocks: Sequence[int]) -> None:
        """Verification accepted drafts reaching into ``blocks``: they
        become ordinary private blocks of the owning request (the
        refcount they already hold is the request's table reference)."""
        for b in blocks:
            if b not in self._tentative:
                raise ValueError(f"block {b} is not tentative")
            self._tentative.remove(b)

    def rollback_tentative(self, blocks: Sequence[int]) -> None:
        """Verification rejected the drafts in ``blocks``: drop the
        speculative reference and return them to the allocator. The
        draft KV they hold is garbage nobody can reach — the blocks
        were never published and leave every live table now."""
        for b in blocks:
            if b not in self._tentative:
                raise ValueError(f"block {b} is not tentative")
            self._tentative.remove(b)
        self.release(blocks)

    # legacy names (PR 1 surface): plain allocation without sharing
    def alloc(self, n: int) -> Optional[List[int]]:
        return self.acquire(n)

    def free(self, blocks: Sequence[int]) -> None:
        self.release(blocks)

    def can_alloc(self, n: int) -> bool:
        return self.can_acquire(n)

    # ---- prefix index -----------------------------------------------
    @staticmethod
    def _key(tokens: np.ndarray, n: int,
             namespace: Optional[str] = None) -> bytes:
        """Index key for ``tokens[:n]``. ``namespace`` partitions the
        index (multi-tenant LoRA serving, serve/adapters.py): identical
        token prefixes hold DIFFERENT KV under different adapters, so a
        chain cached under one adapter must never hit for another (or
        for the base model). EVERY key is a NUL-terminated namespace
        prefix (empty for the base model) + the literal token bytes —
        adapter ids cannot contain NUL, so the first NUL always delimits
        the namespace and two keys are equal only when both namespace
        and token prefix are (a bare token-bytes base key could collide
        with an id whose bytes happen to open another key's body)."""
        body = np.ascontiguousarray(tokens[:n], dtype=np.int32).tobytes()
        if namespace is None:
            return b"\x00" + body
        return namespace.encode("utf-8") + b"\x00" + body

    def lookup(self, tokens, max_tokens: Optional[int] = None, *,
               namespace: Optional[str] = None) -> AdmitPlan:
        """Longest cached block-chain for ``tokens``: full blocks are
        matched at block boundaries, then the longest published partial
        leaf extending the chain. The match is capped at
        ``max_tokens`` (callers pass ``len(tokens) - 1`` so at least
        one token is always prefilled — prefill must produce the
        next-token logits). ``namespace``: the requesting adapter id
        (chains are shared per adapter — see :meth:`_key`). Read-only;
        returns a plan with ``n_new_blocks`` unset."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        limit = len(tokens) if max_tokens is None else min(
            int(max_tokens), len(tokens))
        if not self.prefix_cache or limit <= 0:
            return AdmitPlan(cached_tokens=0)
        bs = self.block_size
        full: List[int] = []
        while (len(full) + 1) * bs <= limit:
            b = self._index.get(self._key(tokens, (len(full) + 1) * bs,
                                          namespace))
            if b is None:
                break
            full.append(b)
        m = len(full) * bs
        cow_src, cow_len = None, 0
        for f in range(min(bs - 1, limit - m), 0, -1):
            b = self._index.get(self._key(tokens, m + f, namespace))
            if b is not None:
                cow_src, cow_len = b, f
                break
        return AdmitPlan(cached_tokens=m + cow_len, shared_blocks=full,
                         cow_src=cow_src, cow_len=cow_len)

    def plan_admission(self, tokens, total_tokens: int, *,
                       namespace: Optional[str] = None) -> AdmitPlan:
        """Best ADMISSIBLE plan for a request whose table must cover
        ``total_tokens`` slots (prefill length + the first decode
        write): the longest cached chain plus the private blocks that
        complete the table. Only ``n_new_blocks`` must come from the
        allocator — the admission budget counts uncached blocks only.

        A maximal chain is not always admissible: pinning it removes
        its blocks from the evictable set, and the transient COW pin
        occupies one more block than the table itself, so near the
        capacity edge the longest-hit plan can need more simultaneous
        blocks than the pool holds — FOREVER, since nothing else would
        ever evict the pinned chain. Rather than wedge the queue head
        (and everything behind it), degrade: drop the COW hit first,
        then fall back to a cache-cold plan, which is admissible
        whenever the request can run at all (submit-time fail-fast
        checked ``blocks_for(total) <= usable_blocks``)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n_total = self.blocks_for(int(total_tokens))
        plan = self.lookup(tokens, max_tokens=len(tokens) - 1,
                           namespace=namespace)
        plan.n_new_blocks = n_total - len(plan.shared_blocks)
        if self.can_admit(plan) or not plan.pinned_blocks:
            return plan
        if plan.cow_src is not None:
            plan = AdmitPlan(
                cached_tokens=len(plan.shared_blocks) * self.block_size,
                shared_blocks=plan.shared_blocks,
                n_new_blocks=plan.n_new_blocks)
            if self.can_admit(plan):
                return plan
        return AdmitPlan(cached_tokens=0, n_new_blocks=n_total)

    def can_admit(self, plan: AdmitPlan) -> bool:
        """Can ``plan.n_new_blocks`` be acquired once the plan's own
        chain is pinned? Pinned blocks stop being eviction candidates,
        so they must not be counted as available."""
        pinned_evictable = sum(1 for b in plan.pinned_blocks
                               if b in self._cached_free)
        return plan.n_new_blocks <= self.num_available - pinned_evictable

    def publish(self, tokens, blocks: Sequence[int], n_tokens: int, *,
                namespace: Optional[str] = None) -> None:
        """Index ``blocks`` as the cached chain for
        ``tokens[:n_tokens]`` (the retire/preempt path — instead of
        freeing, make the request's KV findable). Full blocks are keyed
        at block boundaries; a trailing partial block at its exact
        count. ``namespace``: the adapter id whose programs WROTE this
        KV — the chain is findable only by requests bound to the same
        adapter (see :meth:`_key`). A key already mapping to a
        DIFFERENT block (an identical request published first) keeps
        the incumbent — the duplicate stays unpublished and will return
        to the free list on release. Publish BEFORE release: release
        retains published blocks."""
        if not self.prefix_cache or n_tokens <= 0:
            return
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n_tokens = min(int(n_tokens), len(tokens))
        q, f = divmod(n_tokens, self.block_size)
        used = q + (1 if f else 0)
        bad = [b for b in blocks[:used] if b in self._tentative]
        if bad:
            # the invariant speculative decoding must never break:
            # cached/published chains hold COMMITTED positions only —
            # a tentative block here means the engine tried to publish
            # an unresolved speculative tail
            raise ValueError(
                f"publish would index tentative block(s) {bad}: "
                f"speculative drafts must be committed or rolled back "
                f"before a request's blocks are published")
        for j in range(q):
            self._publish_one(blocks[j], self._key(tokens, (j + 1)
                                                   * self.block_size,
                                                   namespace),
                              self.block_size)
        if f and q < len(blocks):
            self._publish_one(blocks[q],
                              self._key(tokens, n_tokens, namespace), f)

    def _publish_one(self, b: int, key: bytes, fill: int) -> None:
        cur = self._index.get(key)
        if cur == b:
            self._touch(b)
            return
        if cur is not None:
            return  # identical content already cached under this key
        if b in self._block_key:
            # already indexed under another key (cannot happen through
            # the engine: a block holds exactly one chain position) —
            # keep the existing mapping rather than corrupt the index
            return
        self._index[key] = b
        self._block_key[b] = key
        self._block_fill[b] = fill
        self._touch(b)

    # ---- host tier: combined walk, peek, promotion -------------------
    def _walk_chain(self, tokens: np.ndarray, limit: int,
                    namespace: Optional[str]) -> Tuple[int, List[Tuple]]:
        """The longest chain covering ``tokens[:limit]`` from EITHER
        tier: full blocks at block boundaries, then the longest partial
        leaf, exactly the :meth:`lookup` walk — but a boundary missing
        from the device index may be satisfied by a host-tier record.
        Returns ``(covered_tokens, entries)`` with entries in chain
        order: ``("dev", block, fill)`` for device-resident blocks,
        ``("host", key, fill)`` for host-resident ones. Read-only (host
        probes use :meth:`HostTier.contains`, which does not touch the
        tier's LRU)."""
        entries: List[Tuple] = []
        if not self.prefix_cache or limit <= 0:
            return 0, entries
        tier = self.host_tier
        bs = self.block_size
        n = 0
        while (n + 1) * bs <= limit:
            key = self._key(tokens, (n + 1) * bs, namespace)
            b = self._index.get(key)
            if b is not None:
                entries.append(("dev", b, bs))
            elif tier is not None and tier.contains(key):
                entries.append(("host", key, bs))
            else:
                break
            n += 1
        m = n * bs
        for f in range(min(bs - 1, limit - m), 0, -1):
            key = self._key(tokens, m + f, namespace)
            b = self._index.get(key)
            if b is not None:
                entries.append(("dev", b, f))
                m += f
                break
            if tier is not None and tier.contains(key):
                entries.append(("host", key, f))
                m += f
                break
        return m, entries

    def peek_chain_tokens(self, tokens, *,
                          namespace: Optional[str] = None) -> int:
        """Token positions this pool could serve warm for ``tokens`` —
        the device chain PLUS its host-tier extension. No data moves
        and nothing is pinned or touched: this is the cheap probe the
        fleet's tier peer lookup sends every replica (``kv_peek``)
        before deciding whom to pull a chain from."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        covered, _entries = self._walk_chain(tokens, len(tokens),
                                             namespace)
        return covered

    def plan_promotion(self, tokens, max_tokens: Optional[int] = None,
                       *, namespace: Optional[str] = None,
                       ) -> Tuple[int, List[bytes]]:
        """The host-resident boundaries a promotion must import so the
        DEVICE chain covers everything the combined walk can. Returns
        ``(covered_tokens, host_keys)`` — empty ``host_keys`` means
        there is nothing to promote (pure device hit, or a miss in
        both tiers). The third admission outcome in one probe:
        device-hit (covered > 0, no keys), host-hit (keys to promote),
        miss (covered == 0)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        limit = len(tokens) if max_tokens is None else min(
            int(max_tokens), len(tokens))
        if self.host_tier is None:
            return 0, []
        covered, entries = self._walk_chain(tokens, limit, namespace)
        keys = [e[1] for e in entries if e[0] == "host"]
        return covered, keys

    def promote_chain(self, keys: Sequence[bytes], *,
                      max_blocks: Optional[int] = None,
                      ) -> Tuple[int, int]:
        """Re-promote up to ``max_blocks`` host-tier records into
        freshly acquired device blocks — ONE fused scatter per pool
        array, the same device-write shape as :meth:`import_chain`, so
        promotion compiles nothing new — publishing each under its own
        boundary key and releasing (the chain lands refcount-zero in
        the retention set, an ordinary device prefix hit for the next
        admission).

        Returns ``(keys_consumed, blocks_promoted)``: the caller (the
        engine's per-step promotion feed) advances its cursor by the
        first and charges the second against its budget. Keys already
        device-resident are consumed for free. A key missing from the
        host tier (its record was budget-evicted while the promotion
        was in flight) TRUNCATES the chain: later records could never
        be reached past the gap by a device walk, so the remainder is
        consumed unpromoted and admission re-prefills from the gap —
        degraded, never wrong."""
        keys = list(keys)
        if self.host_tier is None or not keys:
            return len(keys), 0
        budget = len(keys) if max_blocks is None else max(0,
                                                          int(max_blocks))
        avail = self.num_available
        taken = 0
        todo: List[Tuple[bytes, Dict]] = []
        terminal = False
        for key in keys:
            if key in self._index:
                taken += 1
                continue
            if len(todo) >= budget or len(todo) >= avail:
                break       # out of budget/capacity — retry next step
            rec = self.host_tier.get(key)
            if rec is None:
                terminal = True
                break
            todo.append((key, rec))
            taken += 1
        if todo:
            blocks = self.acquire(len(todo))
            assert blocks is not None  # len(todo) <= num_available
            bs = self.block_size
            idx = np.concatenate([np.arange(b * bs, (b + 1) * bs)
                                  for b in blocks])
            k_new = np.concatenate([np.asarray(r["k"])
                                    for _, r in todo], axis=1)
            v_new = np.concatenate([np.asarray(r["v"])
                                    for _, r in todo], axis=1)
            k = self.k.at[:, idx].set(
                jnp.asarray(k_new, self.policy.store_dtype))
            v = self.v.at[:, idx].set(
                jnp.asarray(v_new, self.policy.store_dtype))
            if self.policy.scaled:
                barr = np.asarray(blocks, np.int32)
                ks = np.stack([np.asarray(r["k_scale"])
                               for _, r in todo], axis=1)
                vs = np.stack([np.asarray(r["v_scale"])
                               for _, r in todo], axis=1)
                self.update(k, v,
                            self.k_scale.at[:, barr].set(
                                jnp.asarray(ks, jnp.float32)),
                            self.v_scale.at[:, barr].set(
                                jnp.asarray(vs, jnp.float32)))
            else:
                self.update(k, v)
            for b, (key, rec) in zip(blocks, todo):
                self._publish_one(b, key, int(rec["fill"]))
            self.release(blocks)
            self.host_tier.promotions += len(todo)
            self.host_tier.promoted_tokens += sum(
                int(r["fill"]) for _, r in todo)
        if terminal:
            taken = len(keys)
        return taken, len(todo)

    # ---- chain export / import (disaggregated KV handoff) -----------
    def export_chain(self, tokens, *,
                     namespace: Optional[str] = None) -> Optional[Dict]:
        """Snapshot the longest PUBLISHED chain for ``tokens`` as host
        data — the prefill→decode handoff payload of the disaggregated
        fleet (fleet/wire.py frames it, fleet/proc.py ships it). Each
        record carries one block's slot data exactly as stored (the
        policy's ``store_dtype`` — int8 blocks export as int8, ~4x
        smaller than f32) plus its per-block-per-head scale rows when
        the policy is scaled, so an import is a byte-exact replica of
        the source blocks. When a host tier is attached the chain is
        assembled ACROSS tiers: device-resident boundaries come from
        the fused pool gather, host-resident ones from their demoted
        records (already host bytes, zero device traffic) — so a
        replica can serve its whole retained working set to a peer,
        not just the slice that happens to sit in HBM. Returns ``None``
        when nothing is cached for the prefix (evicted from both
        tiers, or never published). Read-only: refcounts, the index
        and the LRUs are untouched beyond a touch."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        covered, entries = self._walk_chain(tokens, len(tokens),
                                            namespace)
        if not entries:
            return None
        bs = self.block_size
        # ONE gather per pool array for the device-resident blocks
        # (then split host-side), not one device op per block: a chain
        # transfer must cost O(chain bytes), never O(blocks * pool
        # bytes)
        dev = [(j, e[1]) for j, e in enumerate(entries)
               if e[0] == "dev"]
        if dev:
            idx = np.concatenate([np.arange(b * bs, (b + 1) * bs)
                                  for _, b in dev])
            k_all = np.asarray(self.k[:, idx])
            v_all = np.asarray(self.v[:, idx])
            if self.policy.scaled:
                barr = np.asarray([b for _, b in dev], np.int32)
                ks_all = np.asarray(self.k_scale[:, barr])
                vs_all = np.asarray(self.v_scale[:, barr])
        dev_slot = {j: s for s, (j, _b) in enumerate(dev)}
        records: List[Dict] = []
        n_out = 0
        for j, (kind, ref, fill) in enumerate(entries):
            if kind == "dev":
                s = dev_slot[j]
                rec = {"fill": int(fill),
                       "k": k_all[:, s * bs:(s + 1) * bs],
                       "v": v_all[:, s * bs:(s + 1) * bs]}
                if self.policy.scaled:
                    rec["k_scale"] = ks_all[:, s]
                    rec["v_scale"] = vs_all[:, s]
            else:
                rec = self.host_tier.get(ref)
                if rec is None:
                    # cannot happen single-threaded (the walk just saw
                    # it); truncate at the gap rather than ship a
                    # chain with a hole
                    break
            records.append(rec)
            n_out += int(fill)
        if not records:
            return None
        return {"tokens": tokens[:n_out].copy(),
                "n_tokens": int(n_out),
                "policy": self.policy.name,
                "block_size": bs,
                "n_layers": self.n_layers,
                "n_kv_heads": self.n_kv_heads,
                "head_dim": self.head_dim,
                "blocks": records}

    def _check_chain_geometry(self, chain: Dict) -> None:
        mine = {"policy": self.policy.name,
                "block_size": self.block_size,
                "n_layers": self.n_layers,
                "n_kv_heads": self.n_kv_heads,
                "head_dim": self.head_dim}
        theirs = {k: chain[k] for k in mine}
        if theirs != mine:
            diffs = {k: (theirs[k], mine[k]) for k in mine
                     if theirs[k] != mine[k]}
            raise ValueError(
                f"KV chain layout does not match this pool "
                f"({{field: (chain, pool)}} = {diffs}) — the exporting "
                f"and importing engines must be built from the same "
                f"spec (same KV layout policy and pool geometry)")

    def import_chain(self, chain: Dict, *,
                     namespace: Optional[str] = None) -> int:
        """Admit an exported chain as a warm prefix hit: allocate
        private blocks, write the transferred slot data (and scales)
        into them byte-exactly, PUBLISH them under the chain's token
        prefix, and release — published refcount-zero blocks are
        retained in the LRU exactly like a retired request's, so the
        next admission for this prefix hits instead of re-prefilling.
        Returns the number of token positions now served from cache
        (0 when the pool cannot hold any of the chain or the prefix
        cache is off — the caller's fallback is local re-prefill,
        which is always correct). A chain LARGER than the pool can
        hold is not discarded: the longest block-aligned prefix that
        fits is imported instead — the chain is cache, so a partial
        import is always correct and still saves that many prefill
        tokens (the dropped tail includes any partially-filled leaf).
        Keys already published keep their incumbent block (the
        duplicate import frees on release), so a racing local prefill
        can never be corrupted by a late handoff."""
        self._check_chain_geometry(chain)
        records = chain["blocks"]
        n_tokens = int(chain["n_tokens"])
        if not self.prefix_cache or n_tokens <= 0 or not records:
            return 0
        q, f = divmod(n_tokens, self.block_size)
        if len(records) != q + (1 if f else 0):
            raise ValueError(
                f"KV chain block count {len(records)} does not cover "
                f"n_tokens={n_tokens} at block_size={self.block_size}")
        n_fit = min(len(records), self.num_available)
        if n_fit <= 0:
            return 0
        if n_fit < len(records):
            records = records[:n_fit]
            n_tokens = n_fit * self.block_size
        blocks = self.acquire(len(records))
        if blocks is None:  # unreachable: capacity checked above
            return 0
        bs = self.block_size
        # ONE fused scatter per pool array — a per-block .at[].set
        # would copy the whole pool once per block (O(blocks * pool
        # bytes)); this is the decode-replica hot path during a
        # handoff and must not stall decode steps behind pool-sized
        # memcpys
        idx = np.concatenate([np.arange(b * bs, (b + 1) * bs)
                              for b in blocks])
        k_new = np.concatenate([np.asarray(r["k"]) for r in records],
                               axis=1)
        v_new = np.concatenate([np.asarray(r["v"]) for r in records],
                               axis=1)
        k = self.k.at[:, idx].set(
            jnp.asarray(k_new, self.policy.store_dtype))
        v = self.v.at[:, idx].set(
            jnp.asarray(v_new, self.policy.store_dtype))
        if self.policy.scaled:
            barr = np.asarray(blocks, np.int32)
            ks = np.stack([np.asarray(r["k_scale"]) for r in records],
                          axis=1)
            vs = np.stack([np.asarray(r["v_scale"]) for r in records],
                          axis=1)
            k_scale = self.k_scale.at[:, barr].set(
                jnp.asarray(ks, jnp.float32))
            v_scale = self.v_scale.at[:, barr].set(
                jnp.asarray(vs, jnp.float32))
            self.update(k, v, k_scale, v_scale)
        else:
            self.update(k, v)
        tokens = np.asarray(chain["tokens"], np.int32).reshape(-1)
        self.publish(tokens, blocks, n_tokens, namespace=namespace)
        self.release(blocks)
        return n_tokens

    # ---- device views ----------------------------------------------
    def caches(self):
        """The pool's device arrays, as carried through the jitted step
        functions (the engine writes the returned/donated results back
        via :meth:`update`): ``(k, v)`` for passthrough policies,
        ``(k, v, k_scale, v_scale)`` for scaled ones — call sites splat
        the tuple, so the policy never changes their shape."""
        if self.policy.scaled:
            return self.k, self.v, self.k_scale, self.v_scale
        return self.k, self.v

    def update(self, k, v, k_scale=None, v_scale=None) -> None:
        self.k, self.v = k, v
        if self.policy.scaled:
            if k_scale is None or v_scale is None:
                raise ValueError(
                    f"policy {self.policy.name!r} carries scale arrays; "
                    f"update() needs all four pool buffers")
            self.k_scale, self.v_scale = k_scale, v_scale

"""Paged KV-cache pool: fixed-size blocks + free-list allocator.

The dense decoders allocate [L, B, H, T_max, Dh] per batch — every
request pays for the longest possible sequence. Here KV memory is a
single pool of ``num_blocks`` blocks of ``block_size`` token slots,
shared by all in-flight requests; each request owns just the blocks its
current length needs (vLLM's PagedAttention memory model). Fragmentation
is bounded to < 1 block per request and T_max padding disappears.

Device layout (per k and v): ``[L, num_blocks * block_size, H_kv, Dh]``
— the flat "slot" dim is what nn/attention.paged_cache_update scatters
into and paged_gather pages out of; keeping L leading lets the decode
step lax.scan over layers exactly like the dense path. Under TP the
H_kv dim is head-sharded over the mesh (each rank holds its local
heads' pool, same invariant as the dense TP cache).

Block 0 is permanently reserved as the NULL block: inactive engine
slots point their table rows (and positions) at it, so masked rows'
scatters land in memory nobody reads and the decode step needs no
dynamic shapes. The allocator therefore hands out blocks [1, num_blocks).

Allocation is host-side bookkeeping (a free list of ints) — the device
arrays never reshape; "allocating" a block just means an engine slot's
block table starts referencing it.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

NULL_BLOCK = 0


class KVPool:
    """Free-list allocator over paged per-layer KV storage.

    ``n_kv_heads`` is the GLOBAL kv-head count; pass ``sharding`` (a
    ``jax.sharding.NamedSharding`` with the head dim on the tp axis) to
    lay the pool out head-sharded for a TP engine.
    """

    def __init__(self, *, n_layers: int, n_kv_heads: int, head_dim: int,
                 block_size: int, num_blocks: int, dtype=jnp.float32,
                 sharding=None):
        if block_size < 1 or num_blocks < 2:
            raise ValueError(
                f"need block_size >= 1 and num_blocks >= 2 (block 0 is "
                f"the reserved null block); got {block_size}, {num_blocks}")
        self.n_layers = n_layers
        self.n_kv_heads = n_kv_heads
        self.head_dim = head_dim
        self.block_size = block_size
        self.num_blocks = num_blocks
        shape = (n_layers, num_blocks * block_size, n_kv_heads, head_dim)
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        if sharding is not None:
            import jax

            k = jax.device_put(k, sharding)
            v = jax.device_put(v, sharding)
        self.k = k
        self.v = v
        # LIFO free list: reuse recently-freed blocks first (warm pages)
        self._free: List[int] = list(range(num_blocks - 1, NULL_BLOCK, -1))

    # ---- accounting -------------------------------------------------
    @property
    def usable_blocks(self) -> int:
        """Blocks available to requests (null block excluded)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_used(self) -> int:
        return self.usable_blocks - self.num_free

    @property
    def utilization(self) -> float:
        return self.num_used / max(self.usable_blocks, 1)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` token slots."""
        return -(-n_tokens // self.block_size)

    def can_alloc(self, n: int) -> bool:
        return n <= self.num_free

    # ---- alloc/free -------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` blocks off the free list, or None (caller decides
        whether to wait or preempt — the pool never partially
        allocates)."""
        if n > len(self._free):
            return None
        taken = [self._free.pop() for _ in range(n)]
        return taken

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if not (NULL_BLOCK < b < self.num_blocks):
                raise ValueError(f"freeing invalid block id {b}")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
        self._free.extend(blocks)

    # ---- device views ----------------------------------------------
    def caches(self):
        """The (k, v) device arrays, as carried through the jitted step
        functions (the engine writes the returned/donated results back
        via :meth:`update`)."""
        return self.k, self.v

    def update(self, k, v) -> None:
        self.k, self.v = k, v
